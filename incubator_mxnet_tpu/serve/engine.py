"""Paged slot-cache compiled decode programs (the device half of `mx.serve`).

PR 4's engine kept one monolithic KV slot per request — shape
``(L, max_slots, H, max_len, d)`` — so every slot reserved ``max_len``
HBM regardless of actual request length and every prompt paid a full
prefill. This module replaces it with a **paged** pool, the
vLLM/PagedAttention block-allocation idea re-expressed TPU-natively
(static shapes, gather-by-page-table, zero steady-state recompiles):

- **page pool** — one persistent device array per K and V **per
  layer**: a tuple of L arrays of shape ``(n_pages, H, page_tokens,
  d)``. Page 0 is a reserved *trash* page: unallocated page-table
  entries and inactive-slot writes land there, and its contents are
  never attended (the validity mask excludes them before softmax).
  The per-layer split is load-bearing for cost, not cosmetics: with a
  single stacked ``(L, ...)`` array threaded through a
  ``lax.scan``-over-layers, XLA re-stacks the scan's per-layer outputs
  into a FRESH pool buffer every step — ``memory_analysis`` temp bytes
  ~ the whole pool, i.e. per-token cost O(L × n_pages). With per-layer
  leaves and a Python-unrolled layer loop, every leaf aliases its
  donated input in place (``input_output_alias`` covers all 2L pool
  leaves) and a step's temp bytes are O(active slots × page) — the
  compile ledger (`telemetry.compiles`) records both facts per
  program. The layout is also the pod-sharding-friendly one: each leaf
  can carry its own `PartitionSpec` (heads sharded, pages replicated)
  without resharding a fused 5-D array.
- **page table** — a host-side ``(max_slots, pages_per_slot)`` int32
  array mapping each slot's token range to pool pages (mirrored to the
  device lazily, refreshed only when allocation changes). Decode gathers
  a slot's logical KV view with a static-shape ``jnp.take`` over the
  table row; prefill writes whole pages with a static-shape scatter.
- **allocator + prefix cache** — `PageAllocator` (host-only free list +
  refcounts; OOM raises the loud `PagePoolExhausted`, nothing is ever
  silently evicted while referenced) and `PrefixCache` (hash of the
  page-aligned token prefix → page list). A common system prompt is
  prefilled once and its pages attached read-only to every later request
  with the same prefix; "copy-on-extend" is structural: a request only
  ever *writes* pages past its shared prefix (partial tail pages are
  re-prefilled privately, and decode's first write position provably
  lands beyond every shared page), so shared pages need no copies and no
  write-protection machinery.

Two compiled program families in the base configuration:

- **chunked prefill** (one program per chunk-length bucket,
  `models.decoding.chunk_buckets`): one page-aligned chunk of ONE
  request's prompt — embeds the chunk at its true positions (traced
  ``t_start``), writes the chunk's K/V pages into the pool, attends the
  chunk's queries against the slot's gathered view (prefix pages +
  itself) under a causal-with-offset mask, and samples a first token
  from the chunk's last real row (used by the host only on the final
  chunk). Splitting long prompts into chunks lets the scheduler
  interleave decode steps between chunks, so a long-prompt arrival no
  longer stalls every running request for a whole monolithic prefill.
- **decode** (ONE program): one token for ALL slots — per-slot scatter
  of the new K/V at ``page_table[s, pos//page_tokens]`` (inactive slots
  are redirected to the trash page), gather of each slot's view, masked
  attention, per-slot sampling.

With **speculative decoding** armed (``spec_k > 0``), decode is
replaced by two more families that advance up to ``k + 1`` tokens per
round instead of one per launch:

- **verify** (ONE program): the target model runs ``k + 1`` token rows
  for ALL slots in one batched pass — row ``i`` consumes
  ``[last, d_1..d_k][i]`` at position ``pos + i``, writes its K/V to
  the slot's pages (beyond-budget rows are redirected to the trash
  page) and emits the greedy next token. Because row ``i`` only
  attends positions ``<= pos + i``, the batched pass is mathematically
  identical to ``k + 1`` sequential decode steps — the same identity
  chunked prefill already relies on — which is what makes greedy spec
  decode token-for-token equal to the non-spec engine.
- **draft** (ONE program, model drafts only): ``k`` unrolled greedy
  decode steps of the small draft model against its OWN per-layer pool
  (same page table and allocator, so draft pages track target pages
  exactly). The ``draft="ngram"`` fallback drafts on the host
  (`models.decoding.NgramProposer`) and adds NO device program.

Acceptance runs on host numpy in the scheduler: the longest drafted
prefix matching the verify row outputs commits (plus the bonus token
from the first mismatching row), and pages speculatively extended for
rejected suffixes roll back through `PageAllocator.decref`.

All families donate the pool buffers (``donate_argnums``) so XLA
updates them in place. Optional **int8 KV**
(``MXNET_SERVE_KV_DTYPE=int8``) stores each layer's pool as int8 with
one scale per (page, head) — the symmetric ±127 convention of
`contrib.quantization` (`quantize_symmetric`) — halving resident KV
bytes per slot; decode re-quantizes only the single page it writes
(grow-only per-page scale).

Stale-row safety (unchanged argument, now per page): position ``p`` of a
slot only enters the attention mask once the slot's ``pos`` reaches
``p``, and the program that advances ``pos`` to ``p`` writes ``p``'s K/V
first — so a freed-and-reused page's previous contents, chunk padding,
and generation headroom are all dead by construction.
"""
from __future__ import annotations

import hashlib
import math
import os
import weakref

import numpy as onp

from ..models.decoding import (GPTDecoder, NgramProposer, bucket_chunk,
                               chunk_buckets)
from ..telemetry import compiles as _compiles
from ..telemetry import hbm as _hbm
from ..telemetry import registry

__all__ = ["SlotDecoder", "PageAllocator", "PrefixCache",
           "PagePoolExhausted", "DEFAULT_PAGE_TOKENS",
           "DEFAULT_PREFILL_CHUNK"]

#: Tokens per KV page (MXNET_SERVE_PAGE_TOKENS). Smaller pages pack
#: tighter and share more; larger pages shrink the page table and the
#: gather fan-in.
DEFAULT_PAGE_TOKENS = 16
#: Prefill chunk ceiling in tokens (MXNET_SERVE_PREFILL_CHUNK); must be
#: a multiple of the page size (rounded up if not).
DEFAULT_PREFILL_CHUNK = 64

PAD_TOKENS = registry.counter(
    "mx_decode_bucket_pad_tokens_total",
    "prompt tokens added by pad-to-bucket in the decode/serving "
    "path (padding waste)")


def _j():
    import jax

    return jax


class PagePoolExhausted(RuntimeError):
    """The KV page pool cannot satisfy an allocation — loud, like
    `QueueFull`: pages referenced by live requests or the prefix cache
    are NEVER silently evicted to make room. Shed load, shrink
    max_new_tokens, raise ``n_pages``, or let running requests retire."""


class PageAllocator:
    """Host-side page accounting for the paged KV pool.

    Pure bookkeeping — it never touches device memory. Page 0 is
    reserved as the trash page (write target for inactive slots and
    padding; never allocated, never read through a mask). Shared pages
    are reference-counted: a page returns to the free list only when its
    LAST reference (requests + prefix-cache entries) drops it.
    """

    def __init__(self, n_pages, page_tokens):
        if n_pages < 2:
            raise ValueError(f"n_pages must be >= 2 (page 0 is reserved), "
                             f"got {n_pages}")
        self.n_pages = int(n_pages)
        self.page_tokens = int(page_tokens)
        # LIFO free list: hot pages get reused while their tiles are warm
        self._free = list(range(self.n_pages - 1, 0, -1))
        self._ref = onp.zeros(self.n_pages, onp.int64)

    @property
    def usable_pages(self):
        """Allocatable pages (total minus the reserved trash page)."""
        return self.n_pages - 1

    @property
    def free_pages(self):
        return len(self._free)

    @property
    def used_pages(self):
        """Pages currently referenced — shared pages counted ONCE."""
        return self.usable_pages - len(self._free)

    def refcount(self, page):
        return int(self._ref[page])

    def alloc(self, n):
        """Take `n` fresh pages (refcount 1 each). Raises the loud
        `PagePoolExhausted` when the pool cannot satisfy the request —
        the caller decides whether to evict unused prefix-cache entries
        and retry, or to keep the request queued."""
        n = int(n)
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PagePoolExhausted(
                f"KV page pool exhausted: need {n} pages, "
                f"{len(self._free)}/{self.usable_pages} free "
                f"({self.used_pages} referenced by live requests or the "
                "prefix cache) — shed load, raise n_pages, or wait for "
                "running requests to retire; shared pages are never "
                "silently evicted")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def incref(self, pages):
        for p in pages:
            if self._ref[p] <= 0:
                raise RuntimeError(
                    f"incref on free page {p} — a shared page was dropped "
                    "while still mapped (allocator bookkeeping bug)")
            self._ref[p] += 1

    def decref(self, pages):
        """Release one reference per page; pages whose count reaches zero
        return to the free list."""
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)
            elif self._ref[p] < 0:
                raise RuntimeError(
                    f"double free of page {p} (refcount went negative) — "
                    "allocator bookkeeping bug")


class _PrefixEntry:
    __slots__ = ("pages", "tokens", "last_used")

    def __init__(self, pages, tokens, last_used):
        self.pages = pages
        self.tokens = tokens
        self.last_used = last_used


class PrefixCache:
    """Shared-prefix page reuse: hash(page-aligned token prefix) → pages.

    Entries hold their OWN page references, so a cached prefix outlives
    the request that prefilled it; `evict_unused` drops
    least-recently-used entries (their references only — pages still
    mapped into live requests stay allocated, which is the "no silent
    eviction of shared pages" contract).

    Every page boundary of a registered prompt gets its own entry, so a
    later prompt matching any page-aligned prefix reuses the longest
    match. Lookups always leave ≥ 1 prompt token uncovered: the final
    token must run through prefill compute to produce the first sampled
    token.
    """

    def __init__(self, allocator, enabled=True):
        self._alloc = allocator
        self._entries = {}
        self._clock = 0
        self.enabled = bool(enabled)

    def __len__(self):
        return len(self._entries)

    @property
    def cached_pages(self):
        """Pages referenced by at least one cache entry (counted once)."""
        seen = set()
        for e in self._entries.values():
            seen.update(e.pages)
        return len(seen)

    def _page_digests(self, prompt, n_pages):
        """Rolling blake2b digest at each of the first `n_pages` page
        boundaries of `prompt` (one pass over the token bytes)."""
        pt = self._alloc.page_tokens
        arr = onp.ascontiguousarray(onp.asarray(prompt, onp.int32))
        h = hashlib.blake2b(digest_size=16)
        out = []
        for jj in range(n_pages):
            h.update(arr[jj * pt:(jj + 1) * pt].tobytes())
            out.append(h.digest())
        return out

    def shared_tokens(self, prompt):
        """Length of the longest cached page-aligned proper prefix of
        `prompt`, in tokens (0 when nothing matches). Read-only probe —
        no LRU touch — for the scheduler's remaining-chunk SJF key."""
        tokens, _ = self._match(prompt, touch=False)
        return tokens

    def lookup(self, prompt):
        """Longest cached page-aligned proper prefix → ``(tokens,
        pages)``. Does NOT take page references — the caller increfs the
        returned pages if (and only if) it maps them into a request."""
        return self._match(prompt, touch=True)

    def _match(self, prompt, touch):
        if not self.enabled or not self._entries:
            return 0, []
        pt = self._alloc.page_tokens
        max_pages = (len(prompt) - 1) // pt
        if max_pages < 1:
            return 0, []
        digests = self._page_digests(prompt, max_pages)
        for jj in range(max_pages, 0, -1):
            e = self._entries.get(digests[jj - 1])
            if e is not None:
                if touch:
                    self._clock += 1
                    e.last_used = self._clock
                return jj * pt, list(e.pages)
        return 0, []

    def register(self, prompt, pages):
        """Make the prompt's full pages shareable. `pages` is the
        request's page list (its prefill must be COMPLETE — the pool
        holds valid K/V for every full prompt page). Returns the number
        of new entries. Idempotent per prefix."""
        if not self.enabled:
            return 0
        pt = self._alloc.page_tokens
        n_full = len(prompt) // pt
        if n_full < 1:
            return 0
        digests = self._page_digests(prompt, n_full)
        added = 0
        for jj in range(1, n_full + 1):
            d = digests[jj - 1]
            if d in self._entries:
                continue
            entry_pages = tuple(int(p) for p in pages[:jj])
            self._alloc.incref(entry_pages)
            self._clock += 1
            self._entries[d] = _PrefixEntry(entry_pages, jj * pt,
                                            self._clock)
            added += 1
        return added

    def evict_unused(self, pages_needed):
        """Drop least-recently-used entries until at least `pages_needed`
        pages are free or no entries remain. Only cache references are
        dropped: a page still mapped into a live request keeps a nonzero
        refcount and is NEVER reused from under it. Returns entries
        dropped."""
        if self._alloc.free_pages >= pages_needed:
            return 0
        dropped = 0
        for d, e in sorted(self._entries.items(),
                           key=lambda kv: kv[1].last_used):
            if self._alloc.free_pages >= pages_needed:
                break
            self._alloc.decref(e.pages)
            del self._entries[d]
            dropped += 1
        if dropped:
            registry.counter(
                "mx_serve_prefix_evictions_total",
                "prefix-cache entries dropped to free pages (cache refs "
                "only — live requests keep their pages)").inc(dropped)
        return dropped

    def clear(self):
        for e in self._entries.values():
            self._alloc.decref(e.pages)
        self._entries.clear()


class SlotDecoder:
    """Paged slot-cache decoder over a `GPTDecoder` (or the
    `GPTModel`-shaped Block it wraps).

    Parameters
    ----------
    source : GPTDecoder or Block
        The model to serve.
    max_slots : int
        Static batch width of the decode program.
    max_len : int
        Per-slot sequence capacity (prompt + generated); defaults to the
        model's position-embedding length.
    page_tokens : int
        Tokens per KV page (default ``MXNET_SERVE_PAGE_TOKENS`` or 16).
    prefill_chunk : int
        Prefill chunk ceiling in tokens (default
        ``MXNET_SERVE_PREFILL_CHUNK`` or 64); rounded up to a multiple
        of `page_tokens` and capped at the slot view.
    n_pages : int
        Total pool pages INCLUDING the reserved trash page 0. Defaults
        to full backing for every slot (``max_slots * pages_per_slot``
        + 1); smaller values trade HBM for admission pressure
        (`PagePoolExhausted` is the loud limit).
    kv_dtype : "fp" | "int8"
        KV storage (default ``MXNET_SERVE_KV_DTYPE`` or the parameter
        dtype). int8 halves resident KV bytes with one scale per
        (layer, page, head).
    prefix_reuse : bool
        Arm the shared-prefix cache (default True).
    do_sample / top_k : sampling mode, STATIC per engine; `temperature`
        stays a runtime per-request argument.
    spec_k : int
        Speculative decoding draft length (default
        ``MXNET_SERVE_SPEC_K`` or 0 = off). Greedy engines only
        (``do_sample=False``): greedy verification is what makes spec
        output token-for-token identical to plain decode.
    draft : "ngram" | GPTDecoder | Block
        Draft source when ``spec_k > 0`` (default
        ``MXNET_SERVE_SPEC_DRAFT`` or ``"ngram"``): the host n-gram
        proposer, or a small GPT whose vocabulary matches the target
        (drafted ids index the target embedding).
    """

    def __init__(self, source, max_slots=8, max_len=None, page_tokens=None,
                 prefill_chunk=None, n_pages=None, kv_dtype=None,
                 prefix_reuse=True, do_sample=False, top_k=None,
                 spec_k=None, draft=None):
        if isinstance(source, GPTDecoder):
            self._dec = source
        elif hasattr(source, "blocks") and hasattr(source, "position_embed"):
            self._dec = GPTDecoder(source)
        else:
            raise TypeError(
                "SlotDecoder needs a GPTDecoder or a GPT-shaped Block "
                f"(blocks + position_embed), got {type(source).__name__}")
        model_max = self._dec._max_length
        self.max_len = int(max_len) if max_len is not None else model_max
        if self.max_len > model_max:
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the model's position "
                f"table ({model_max})")
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")

        from ..util import env_int

        pt = int(page_tokens) if page_tokens is not None else \
            env_int("MXNET_SERVE_PAGE_TOKENS", DEFAULT_PAGE_TOKENS)
        if pt < 1:
            raise ValueError(f"page_tokens must be >= 1, got {pt}")
        self.page_tokens = pt
        self.pages_per_slot = -(-self.max_len // pt)          # ceil
        self.view_tokens = self.pages_per_slot * pt
        chunk = int(prefill_chunk) if prefill_chunk is not None else \
            env_int("MXNET_SERVE_PREFILL_CHUNK", DEFAULT_PREFILL_CHUNK)
        chunk = max(pt, -(-chunk // pt) * pt)                 # page-align up
        self.prefill_chunk = min(chunk, self.view_tokens)
        self.chunk_buckets = chunk_buckets(pt, self.prefill_chunk)

        if kv_dtype is None:
            kv_dtype = os.environ.get("MXNET_SERVE_KV_DTYPE", "fp")
        if kv_dtype not in ("fp", "int8"):
            raise ValueError(
                f"kv_dtype must be 'fp' or 'int8', got {kv_dtype!r} "
                "(MXNET_SERVE_KV_DTYPE)")
        self.kv_dtype = kv_dtype
        self._int8 = kv_dtype == "int8"

        default_pages = self.max_slots * self.pages_per_slot + 1
        self.n_pages = int(n_pages) if n_pages is not None else default_pages
        self.allocator = PageAllocator(self.n_pages, pt)
        self.prefix_cache = PrefixCache(self.allocator,
                                        enabled=bool(prefix_reuse))
        registry.register_pull_gauge(
            "mx_serve_page_occupancy",
            _occupancy_probe(self.allocator),
            "fraction of usable KV pool pages referenced (shared pages "
            "counted once) [0, 1]")

        self._do_sample = bool(do_sample)
        self._top_k = None if top_k is None else int(top_k)

        # host page table + lazy device mirror (refreshed only when an
        # allocation changes it — steady-state decode re-sends nothing)
        self._table = onp.zeros((self.max_slots, self.pages_per_slot),
                                onp.int32)
        self._table_dev = None
        self._table_dirty = True

        # per-layer paged K/V: tuples of L arrays (n_pages, H, pt, d)
        self._pk = self._pv = None
        self._sk = self._sv = None          # int8 per-(page, H) scales
        self._prefill_jit = None
        self._decode_jit = None

        # -- speculative decoding --------------------------------------
        sk_env = env_int("MXNET_SERVE_SPEC_K", 0)
        self.spec_k = int(spec_k) if spec_k is not None else sk_env
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {self.spec_k}")
        if draft is None:
            draft = os.environ.get("MXNET_SERVE_SPEC_DRAFT", "ngram")
        self.draft_kind = "off"
        self._draft_dec = None
        self._ngram = None
        if self.spec_k:
            if self._do_sample:
                raise ValueError(
                    "speculative decoding (spec_k > 0) requires greedy "
                    "decoding (do_sample=False): greedy verification is "
                    "what makes spec output token-for-token identical")
            if isinstance(draft, str):
                if draft not in ("ngram",):
                    raise ValueError(
                        f"unknown draft source {draft!r} "
                        "(MXNET_SERVE_SPEC_DRAFT): expected 'ngram', a "
                        "GPTDecoder, or a GPT-shaped Block")
                self.draft_kind = "ngram"
                self._ngram = NgramProposer(self.spec_k)
            else:
                dd = draft if isinstance(draft, GPTDecoder) \
                    else GPTDecoder(draft)
                if dd._max_length < self.max_len:
                    raise ValueError(
                        f"draft model position table ({dd._max_length}) "
                        f"is shorter than max_len ({self.max_len})")
                dv = dd._params["embed"].shape[0]
                tv = self._dec._params["embed"].shape[0]
                if dv != tv:
                    raise ValueError(
                        f"draft vocab ({dv}) != target vocab ({tv}) — "
                        "drafted token ids index the target embedding")
                self.draft_kind = "model"
                self._draft_dec = dd
        self._dpk = self._dpv = None        # draft-model per-layer pools
        self._dsk = self._dsv = None
        self._verify_jit = None
        self._draft_jit = None
        self._draft_prefill_jit = None
        self._spec_drafted = 0              # lifetime drafted tokens
        self._spec_accepted = 0             # lifetime accepted drafts
        self._spec_gauge = False

        # compile-ledger / HBM-census attribution label; the gateway
        # overrides this per model BEFORE the first prefill so ledger
        # families and census owners carry the tenant name
        self.census_name = "serve"

    # -- page table ---------------------------------------------------------

    def set_slot_pages(self, slot, pages):
        """Bind `pages` (host ints) as `slot`'s logical token range;
        entries past the list point at the trash page."""
        if len(pages) > self.pages_per_slot:
            raise ValueError(
                f"{len(pages)} pages exceed the slot view "
                f"({self.pages_per_slot})")
        self._table[slot, :] = 0
        self._table[slot, :len(pages)] = pages
        self._table_dirty = True

    def clear_slot(self, slot):
        self._table[slot, :] = 0
        self._table_dirty = True

    def _table_device(self):
        if self._table_dirty or self._table_dev is None:
            self._table_dev = _j().numpy.asarray(self._table)
            self._table_dirty = False
        return self._table_dev

    # -- pool ---------------------------------------------------------------

    def _make_pools(self, dec):
        """Per-layer page pools for `dec`: TUPLES of L device arrays of
        shape ``(n_pages, H, page_tokens, d)`` (int8 adds per-layer
        ``(n_pages, H)`` scale planes). Separate leaves — not one
        stacked 5-D array — so every compiled program's donation map
        aliases each layer's pool in place; see the module docstring
        for why the stacked layout forces an O(L × n_pages) rewrite."""
        jnp = _j().numpy
        layers = dec._params["layers"]
        L = layers["ln1_g"].shape[0]
        H = dec._n_heads
        d = dec._units // H
        shape = (self.n_pages, H, self.page_tokens, d)
        if self._int8:
            pk = tuple(jnp.zeros(shape, jnp.int8) for _ in range(L))
            pv = tuple(jnp.zeros(shape, jnp.int8) for _ in range(L))
            sk = tuple(jnp.zeros((self.n_pages, H), jnp.float32)
                       for _ in range(L))
            sv = tuple(jnp.zeros((self.n_pages, H), jnp.float32)
                       for _ in range(L))
            return pk, pv, sk, sv
        dtype = layers["qkv_w"].dtype
        pk = tuple(jnp.zeros(shape, dtype) for _ in range(L))
        pv = tuple(jnp.zeros(shape, dtype) for _ in range(L))
        return pk, pv, None, None

    # -- sharding seams (overridden by serve.sharded.ShardedSlotDecoder) ----

    def _refresh_params(self):
        """Hot-swap seam: re-read decoder params when the source block's
        weights changed (cheap id-fingerprint walk). The sharded engine
        overrides this to re-place refreshed params onto its mesh —
        every program entry point routes through here, so a weight swap
        lands without draining the engine."""
        self._dec._auto_refresh()

    def _constrain_pools(self, pk, pv, sk, sv):
        """Traced seam at the tail of every pool-updating program: the
        base engine is layout-free (identity), the sharded engine pins
        each updated pool leaf to its input sharding so XLA's donation
        map still aliases all ``2L`` leaves in place."""
        return pk, pv, sk, sv

    def _shardcheck_specs(self):
        """Per-argument shardcheck spec entries for ``(params, *pools)``,
        or None (unconstrained — the single-chip default). The sharded
        engine returns its `ServeLayout`-derived entries so SC001 sees
        every ≥1 MiB leaf explicitly placed."""
        return None

    def _shardcheck_out_specs(self):
        """Spec entries for the builders' ``(pk, pv[, sk, sv], tok)``
        outputs, or None. The sharded engine pins the pool outputs so
        the SC004 donation audit sees matching in/out placements."""
        return None

    def _ensure_pool(self):
        if self._pk is not None:
            return
        self._pk, self._pv, self._sk, self._sv = self._make_pools(self._dec)
        if self._draft_dec is not None:
            (self._dpk, self._dpv,
             self._dsk, self._dsv) = self._make_pools(self._draft_dec)
        self._register_hbm_owners()

    def _register_hbm_owners(self):
        """Attribute this engine's device memory to named HBM-census
        owners (`telemetry.hbm`): the KV pool (+ page table, with the
        prefix cache's share as derived page math — cached pages live
        inside the pool arrays) and the decoder params. Probes hold a
        weakref so a released engine silently drops out of the census."""
        ref = weakref.ref(self)

        def _pool_probe():
            eng = ref()
            if eng is None or eng._pk is None:
                return None
            arrays = []
            for leaves in (eng._pk, eng._pv, eng._sk, eng._sv,
                           eng._dpk, eng._dpv, eng._dsk, eng._dsv):
                if leaves is not None:
                    arrays.extend(leaves)
            arrays.append(eng._table_dev)
            page_bytes = eng.cache_bytes / eng.n_pages if eng.n_pages else 0
            cached = eng.prefix_cache.cached_pages
            return {
                "arrays": [a for a in arrays if a is not None],
                "detail": {"kv_dtype": eng.kv_dtype,
                           "n_pages": eng.n_pages,
                           "pages_used": eng.allocator.used_pages,
                           "prefix_cached_pages": cached},
                "derived": {"prefix_cache": int(cached * page_bytes)},
            }

        def _params_probe():
            eng = ref()
            if eng is None:
                return None
            import jax.tree_util as jtu

            return {"arrays": jtu.tree_leaves(eng._dec._params)}

        _hbm.register_owner(f"{self.census_name}.kv_pool", _pool_probe)
        _hbm.register_owner(f"{self.census_name}.params", _params_probe)

    def release(self):
        """Drop the device pool (shutdown); the next prefill reallocates."""
        self._pk = self._pv = self._sk = self._sv = None
        self._dpk = self._dpv = self._dsk = self._dsv = None
        self._table_dev = None
        self._table_dirty = True

    @property
    def cache_bytes(self):
        """Device bytes held by the persistent KV pools — target and
        (when a model draft is armed) draft — 0 if released."""
        if self._pk is None:
            return 0
        n = 0
        for leaves in (self._pk, self._pv, self._sk, self._sv,
                       self._dpk, self._dpv, self._dsk, self._dsv):
            if leaves is not None:
                n += sum(a.size * a.dtype.itemsize for a in leaves)
        return n

    @property
    def kv_bytes_per_slot(self):
        """Resident pool bytes per decode slot — the HBM cost a slot
        actually pays under paging (int8 halves it)."""
        if self._pk is None:
            return 0
        return self.cache_bytes / self.max_slots

    @property
    def page_bytes(self):
        """Bytes one pool page holds across all L layers (K + V, plus
        the int8 scale planes) — the migration accounting unit: the
        disaggregation plane's ``mx_serve_page_migration_bytes_total``
        is exactly pages-moved × this. Derived from shapes, so it needs
        no allocated pool."""
        dec = self._dec
        layers = dec._params["layers"]
        L = int(layers["ln1_g"].shape[0])
        H = dec._n_heads
        d = dec._units // H
        if self._int8:
            # int8 K + V page slabs plus two f32 per-(page, H) scales
            per_layer = 2 * H * self.page_tokens * d + 2 * H * 4
        else:
            itemsize = onp.dtype(layers["qkv_w"].dtype).itemsize
            per_layer = 2 * H * self.page_tokens * d * itemsize
        return L * per_layer

    # -- page migration (the disaggregation transfer seam) -------------------

    def copy_pages_out(self, pages):
        """Snapshot pool pages `pages` to host — the export half of the
        disagg KV handoff (`serve/disagg.py` is the only caller; lint
        FL021 fences everything else off). Returns an opaque payload for
        a same-shape peer's `copy_pages_in`.

        Pages are gathered ONE at a time with the page index as a traced
        device scalar: every dispatch reuses a single cached executable
        per layer shape regardless of how many pages a request spans, so
        steady-state migration compiles nothing new (the instrumented
        prefill/decode families are untouched either way)."""
        jnp = _j().numpy
        self._ensure_pool()
        payload = {}
        for name, leaves in (("k", self._pk), ("v", self._pv),
                             ("sk", self._sk), ("sv", self._sv)):
            if leaves is None:
                continue
            payload[name] = [
                [onp.asarray(jnp.take(pool_l, jnp.asarray(p, jnp.int32),
                                      axis=0))
                 for p in pages]
                for pool_l in leaves]
        return payload

    def copy_pages_in(self, pages, payload):
        """Write a peer engine's `copy_pages_out` payload into this pool
        at `pages` (import half of the disagg handoff; same whole-page
        granularity, so the bytes land bit-identical). Like the export
        side, one traced-index scatter per page keeps every executable
        shape-stable across migrations."""
        jnp = _j().numpy
        self._ensure_pool()
        for name, attr in (("k", "_pk"), ("v", "_pv"),
                           ("sk", "_sk"), ("sv", "_sv")):
            leaves = getattr(self, attr)
            if leaves is None:
                if payload.get(name):
                    raise ValueError(
                        f"payload carries {name!r} planes but this engine "
                        f"has none (kv_dtype mismatch across replicas?)")
                continue
            blocks = payload[name]
            new = []
            for pool_l, per_page in zip(leaves, blocks):
                for p, blk in zip(pages, per_page):
                    pool_l = pool_l.at[jnp.asarray(p, jnp.int32)].set(
                        jnp.asarray(blk))
                new.append(pool_l)
            setattr(self, attr, self._place_migrated(tuple(new), name))

    def _place_migrated(self, leaves, name):  # noqa: ARG002
        """Placement seam after a migration write: the base engine keeps
        the eager scatter results as-is; the sharded engine re-pins them
        to the pool layout so donation aliasing still matches."""
        return leaves

    # -- shared attention helpers (traced) ----------------------------------

    def _dequant_view(self, pool_l, scale_l, idx):
        """Gather pages `idx` from one layer's pool and return the real-
        valued view ``(..., n_idx * page_tokens, d)`` (leading dims follow
        `idx`'s shape). fp pools gather straight through."""
        jnp = _j().numpy
        v = jnp.take(pool_l, idx, axis=0)
        if self._int8:
            sc = jnp.take(scale_l, idx, axis=0)
            v = v.astype(jnp.float32) * sc[..., None, None]
        return v

    # -- chunked prefill ----------------------------------------------------

    def _build_prefill(self, dec=None, kind="prefill"):
        """Chunked-prefill program family for `dec` (default the target;
        the draft model gets its own family writing its own pools)."""
        jax = _j()
        jnp = jax.numpy
        lax = jax.lax
        dec = self._dec if dec is None else dec
        H = dec._n_heads
        pt = self.page_tokens
        int8 = self._int8

        from ..contrib.quantization import quantize_symmetric
        from ..models.decoding import _dense, _ln, _split_qkv

        def to_pages(t):
            # (1, H, C, d) -> (C//pt pages, H, pt, d)
            _, _, C, d = t.shape
            return jnp.transpose(
                t[0].transpose(1, 0, 2).reshape(C // pt, pt, H, d),
                (0, 2, 1, 3))

        def run(params, pk, pv, sk, sv, tokens, pages_row, chunk_pages,
                t_start, t_len, key, temperature, top_k, do_sample):
            C = tokens.shape[1]
            PT = pages_row.shape[0] * pt
            pos_tab = params["pos"]
            pos_idx = jnp.clip(t_start + jnp.arange(C), 0,
                               pos_tab.shape[0] - 1)
            x = params["embed"][tokens] + pos_tab[pos_idx]
            qpos = t_start + jnp.arange(C)
            # causal-with-offset validity: key position j is visible to
            # chunk row i iff j <= t_start + i — this covers BOTH the
            # prefix pages (j < t_start) and in-chunk causality, and
            # masks stale/trash/padding pages in one stroke
            mask = jnp.arange(PT)[None, :] <= qpos[:, None]
            sm_scale = 1.0 / math.sqrt(dec._units // H)
            d = dec._units // H

            # Python-unrolled over layers: each iteration reads/writes
            # ITS OWN donated pool leaf, so XLA's donation map aliases
            # every leaf in place (a scan over a stacked pool re-stacks
            # the whole pool per call — the O(L × n_pages) rewrite this
            # layout exists to remove)
            L = len(pk)
            pk, pv = list(pk), list(pv)
            sk = list(sk) if int8 else [None] * L
            sv = list(sv) if int8 else [None] * L
            for li in range(L):
                lp = {n: a[li] for n, a in params["layers"].items()}
                pk_l, pv_l = pk[li], pv[li]
                sk_l, sv_l = sk[li], sv[li]
                h = _ln(x, lp["ln1_g"], lp["ln1_b"])
                q, k, v = _split_qkv(_dense(h, lp["qkv_w"], lp["qkv_b"]), H)
                kp, vp = to_pages(k), to_pages(v)
                if int8:
                    kq, ks = quantize_symmetric(kp, axes=(2, 3))
                    vq, vs = quantize_symmetric(vp, axes=(2, 3))
                    pk_l = pk_l.at[chunk_pages].set(kq)
                    pv_l = pv_l.at[chunk_pages].set(vq)
                    sk_l = sk_l.at[chunk_pages].set(ks[:, :, 0, 0])
                    sv_l = sv_l.at[chunk_pages].set(vs[:, :, 0, 0])
                else:
                    pk_l = pk_l.at[chunk_pages].set(kp.astype(pk_l.dtype))
                    pv_l = pv_l.at[chunk_pages].set(vp.astype(pv_l.dtype))
                # slot view: (P, H, pt, d) -> (1, H, P*pt, d)
                vk = self._dequant_view(pk_l, sk_l, pages_row)
                vv = self._dequant_view(pv_l, sv_l, pages_row)
                vk = jnp.transpose(vk, (1, 0, 2, 3)).reshape(H, PT, d)[None]
                vv = jnp.transpose(vv, (1, 0, 2, 3)).reshape(H, PT, d)[None]
                if int8:
                    # the chunk attends to its OWN K/V exactly (pre-
                    # quantization) — only the prefix pays quantization
                    vk = lax.dynamic_update_slice(vk, k.astype(vk.dtype),
                                                  (0, 0, t_start, 0))
                    vv = lax.dynamic_update_slice(vv, v.astype(vv.dtype),
                                                  (0, 0, t_start, 0))
                # mirror ops/flash_attention._xla_attention exactly (the
                # impl the unpaged GPTDecoder prefill resolves to at
                # serving sizes) so paged output stays bit-identical
                s = jnp.einsum("bhqd,bhkd->bhqk", q, vk) * sm_scale
                neg = jnp.asarray(jnp.finfo(s.dtype).min / 2, s.dtype)
                s = jnp.where(mask[None, None], s, neg)
                p = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("bhqk,bhkd->bhqd", p, vv)
                o = jnp.transpose(o, (0, 2, 1, 3)).reshape(1, C, H * d)
                x = x + _dense(o, lp["proj_w"], lp["proj_b"])
                h = _ln(x, lp["ln2_g"], lp["ln2_b"])
                ffn = _dense(
                    jax.nn.gelu(_dense(h, lp["ffn1_w"], lp["ffn1_b"])),
                    lp["ffn2_w"], lp["ffn2_b"])
                x = x + ffn
                pk[li], pv[li] = pk_l, pv_l
                sk[li], sv[li] = sk_l, sv_l
            pk, pv = tuple(pk), tuple(pv)
            sk = tuple(sk) if int8 else None
            sv = tuple(sv) if int8 else None
            # the chunk's last REAL row (padding beyond t_len is causally
            # downstream of it and cannot touch it)
            h_last = lax.dynamic_slice_in_dim(x, t_len - 1, 1,
                                              axis=1)[:, 0]
            logits = dec._logits(params, h_last)               # (1, V)
            first = dec._sample(logits, key, temperature, top_k, do_sample)
            pk, pv, sk, sv = self._constrain_pools(pk, pv, sk, sv)
            return pk, pv, sk, sv, first[0]

        # the int8 pools carry per-page scale planes as extra donated
        # state; the fp signature omits them entirely (donating an
        # unused placeholder would invalidate its buffer)
        if int8:
            def prefill(params, pk, pv, sk, sv, tokens, pages_row,
                        chunk_pages, t_start, t_len, key, temperature, *,
                        top_k, do_sample):
                return run(params, pk, pv, sk, sv, tokens, pages_row,
                           chunk_pages, t_start, t_len, key, temperature,
                           top_k, do_sample)

            return self._observed(
                jax.jit(prefill, static_argnames=("top_k", "do_sample"),
                        donate_argnums=(1, 2, 3, 4)),
                kind, donate=(1, 2, 3, 4), tokens_idx=5)

        def prefill(params, pk, pv, tokens, pages_row, chunk_pages,
                    t_start, t_len, key, temperature, *, top_k, do_sample):
            pk, pv, _, _, first = run(params, pk, pv, None, None, tokens,
                                      pages_row, chunk_pages, t_start,
                                      t_len, key, temperature, top_k,
                                      do_sample)
            return pk, pv, first

        return self._observed(
            jax.jit(prefill, static_argnames=("top_k", "do_sample"),
                    donate_argnums=(1, 2)),
            kind, donate=(1, 2), tokens_idx=3)

    def _observed(self, fn, kind, donate, tokens_idx=None):
        """Compile-observatory wrapper for a program family: recompiles
        past the first get forensics, and bucketed prefill growth (a new
        chunk bucket seen at `tokens_idx`) is classified `new_bucket`.
        `instrument_jit` passes `_cache_size` through, so
        `xla_program_count` and the shardcheck pre-flight see the raw
        jitted object's introspection surface."""
        bucket = None
        if tokens_idx is not None:
            def bucket(args, kwargs, _i=tokens_idx):  # noqa: ARG001
                return int(args[_i].shape[1])
        return _compiles.instrument_jit(
            fn, f"{self.census_name}.{kind}", bucket=bucket, donate=donate)

    def prefill_chunk_step(self, slot, chunk_tokens, t_start, key,
                           temperature=1.0):
        """Run ONE page-aligned prefill chunk for `slot`.

        `chunk_tokens` is the 1D host slice ``prompt[t_start:t_start+n]``
        with ``t_start`` page-aligned (0 or a multiple of `page_tokens`,
        e.g. the shared-prefix boundary). Returns ``(first_token, bucket,
        pad)`` — the sampled token is meaningful only when this was the
        prompt's final chunk; `bucket`/`pad` feed the caller's span
        annotations.
        """
        jnp = _j().numpy
        self._refresh_params()
        self._ensure_pool()
        if self._prefill_jit is None:
            self._prefill_jit = self._build_prefill()
        pt = self.page_tokens
        if t_start % pt:
            raise ValueError(
                f"chunk start {t_start} is not page-aligned (page_tokens="
                f"{pt})")
        chunk = onp.asarray(chunk_tokens, onp.int32).reshape(-1)
        n = chunk.size
        bucket = bucket_chunk(n, self.chunk_buckets)
        pad = bucket - n
        if pad:
            chunk = onp.pad(chunk, (0, pad))
            PAD_TOKENS.inc(pad)
        # the chunk's pages, padded with the trash page where the bucket
        # overshoots the slot's mapped range (pad-token K/V is discarded)
        first_page = t_start // pt
        row = self._table[slot]
        cp = bucket // pt
        chunk_pages = onp.zeros(cp, onp.int32)
        avail = row[first_page:first_page + cp]
        chunk_pages[:avail.size] = avail
        args = (jnp.asarray(chunk)[None, :], jnp.asarray(row),
                jnp.asarray(chunk_pages), jnp.int32(t_start), jnp.int32(n),
                key, jnp.float32(max(float(temperature), 1e-6)))
        if self._int8:
            (self._pk, self._pv, self._sk, self._sv,
             first) = self._prefill_jit(
                self._dec._params, self._pk, self._pv, self._sk, self._sv,
                *args, top_k=self._top_k, do_sample=self._do_sample)
        else:
            self._pk, self._pv, first = self._prefill_jit(
                self._dec._params, self._pk, self._pv, *args,
                top_k=self._top_k, do_sample=self._do_sample)
        if self._draft_dec is not None:
            # the draft model prefills the SAME chunk into its own
            # pools (same pages — table/allocator are shared), so spec
            # drafting starts from a warm draft KV for every request
            self._draft_dec._auto_refresh()
            if self._draft_prefill_jit is None:
                self._draft_prefill_jit = self._build_prefill(
                    self._draft_dec, "draft_prefill")
            if self._int8:
                (self._dpk, self._dpv, self._dsk, self._dsv,
                 _) = self._draft_prefill_jit(
                    self._draft_dec._params, self._dpk, self._dpv,
                    self._dsk, self._dsv, *args, top_k=self._top_k,
                    do_sample=self._do_sample)
            else:
                self._dpk, self._dpv, _ = self._draft_prefill_jit(
                    self._draft_dec._params, self._dpk, self._dpv, *args,
                    top_k=self._top_k, do_sample=self._do_sample)
        return int(first), bucket, pad

    # -- decode -------------------------------------------------------------

    def _sample_slots(self, logits, key, temperature, top_k, do_sample):
        """`GPTDecoder._sample` with a PER-SLOT temperature vector."""
        jax = _j()
        jnp = jax.numpy
        if not do_sample:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits.astype(jnp.float32) / temperature[:, None]
        if top_k is not None:
            vals, idx = jax.lax.top_k(logits, top_k)
            choice = jax.random.categorical(key, vals, axis=-1)
            return jnp.take_along_axis(
                idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    def _make_write_token(self):
        """Traced helper shared by the decode/verify/draft programs:
        scatter one token's K or V ``(S, H, d)`` at each slot's write
        page/offset; int8 re-quantizes just the written page under a
        grow-only scale."""
        jnp = _j().numpy
        int8 = self._int8
        S = self.max_slots

        from ..contrib.quantization import quantize_symmetric

        def write_token(pool_l, scale_l, wpage, woff, t):
            if not int8:
                return pool_l.at[wpage, :, woff].set(
                    t.astype(pool_l.dtype)), scale_l
            old = jnp.take(scale_l, wpage, axis=0)             # (S, H)
            amax = jnp.max(jnp.abs(t), axis=-1)                # (S, H)
            new = jnp.maximum(old, jnp.maximum(amax, 1e-8) / 127.0)
            page = jnp.take(pool_l, wpage, axis=0)             # (S,H,pt,d)
            page = jnp.clip(
                jnp.round(page.astype(jnp.float32)
                          * (old / new)[:, :, None, None]),
                -127, 127)
            tq, _ = quantize_symmetric(t, axes=(), scale=new[:, :, None])
            page = page.at[jnp.arange(S), :, woff].set(tq)
            pool_l = pool_l.at[wpage].set(page.astype(jnp.int8))
            scale_l = scale_l.at[wpage].set(new)
            return pool_l, scale_l

        return write_token

    def _decode_layer_step(self, dec, lp, x, pools, table, wpage, woff,
                           mask, write_token):
        """One layer of the single-token decode body — shared verbatim
        by the decode program and each unrolled step of the draft
        program so all three stay bit-identical. `pools` is the layer's
        ``(pk_l, pv_l, sk_l, sv_l)``; returns updated ``(x, pools)``."""
        jax = _j()
        jnp = jax.numpy
        from ..models.decoding import _dense, _ln, _split_qkv

        H = dec._n_heads
        d = dec._units // H
        S = self.max_slots
        PT = table.shape[1] * self.page_tokens
        pk_l, pv_l, sk_l, sv_l = pools
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q, k, v = _split_qkv(_dense(h, lp["qkv_w"], lp["qkv_b"]), H)
        pk_l, sk_l = write_token(pk_l, sk_l, wpage, woff, k[:, :, 0])
        pv_l, sv_l = write_token(pv_l, sv_l, wpage, woff, v[:, :, 0])
        # per-slot logical view via the page table: one gather,
        # static index shape (S, P)
        vk = self._dequant_view(pk_l, sk_l, table)
        vv = self._dequant_view(pv_l, sv_l, table)
        vk = jnp.transpose(vk, (0, 2, 1, 3, 4)).reshape(S, H, PT, d)
        vv = jnp.transpose(vv, (0, 2, 1, 3, 4)).reshape(S, H, PT, d)
        s = jnp.einsum("shqd,shkd->shqk", q, vk,
                       preferred_element_type=jnp.float32)
        s = s / math.sqrt(d)
        s = jnp.where(mask[:, None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(vv.dtype)
        o = jnp.einsum("shqk,shkd->shqd", p, vv)
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(S, 1, H * d)
        x = x + _dense(o, lp["proj_w"], lp["proj_b"])
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        ffn = _dense(
            jax.nn.gelu(_dense(h, lp["ffn1_w"], lp["ffn1_b"])),
            lp["ffn2_w"], lp["ffn2_b"])
        return x + ffn, (pk_l, pv_l, sk_l, sv_l)

    def _build_decode(self):
        jax = _j()
        jnp = jax.numpy
        dec = self._dec
        pt = self.page_tokens
        int8 = self._int8
        S = self.max_slots
        write_token = self._make_write_token()

        def run(params, pk, pv, sk, sv, table, last_tok, pos, active,
                key, temperature, top_k, do_sample):
            PT = table.shape[1] * pt
            x = (params["embed"][last_tok][:, None, :]
                 + params["pos"][pos][:, None, :])              # (S, 1, C)
            # each slot writes at its own page/offset; slots that are
            # free or still prefilling are redirected to the trash page
            wpage = table[jnp.arange(S), pos // pt]
            wpage = jnp.where(active, wpage, 0)
            woff = pos % pt
            mask = jnp.arange(PT)[None, :] <= pos[:, None]

            # unrolled over layers — each pool leaf aliases its donated
            # input (see _make_pools)
            L = len(pk)
            pk, pv = list(pk), list(pv)
            sk = list(sk) if int8 else [None] * L
            sv = list(sv) if int8 else [None] * L
            for li in range(L):
                lp = {n: a[li] for n, a in params["layers"].items()}
                x, (pk[li], pv[li], sk[li], sv[li]) = \
                    self._decode_layer_step(
                        dec, lp, x, (pk[li], pv[li], sk[li], sv[li]),
                        table, wpage, woff, mask, write_token)
            pk, pv = tuple(pk), tuple(pv)
            sk = tuple(sk) if int8 else None
            sv = tuple(sv) if int8 else None
            logits = dec._logits(params, x[:, 0])               # (S, V)
            nxt = self._sample_slots(logits, key, temperature, top_k,
                                     do_sample)
            # free/prefilling slots carry their last token forward — the
            # host never reads them, but a defined value keeps the
            # program deterministic
            nxt = jnp.where(active, nxt, last_tok)
            pk, pv, sk, sv = self._constrain_pools(pk, pv, sk, sv)
            return pk, pv, sk, sv, nxt

        if int8:
            def decode(params, pk, pv, sk, sv, table, last_tok, pos,
                       active, key, temperature, *, top_k, do_sample):
                return run(params, pk, pv, sk, sv, table, last_tok, pos,
                           active, key, temperature, top_k, do_sample)

            return self._observed(
                jax.jit(decode, static_argnames=("top_k", "do_sample"),
                        donate_argnums=(1, 2, 3, 4)),
                "decode", donate=(1, 2, 3, 4))

        def decode(params, pk, pv, table, last_tok, pos, active, key,
                   temperature, *, top_k, do_sample):
            pk, pv, _, _, nxt = run(params, pk, pv, None, None, table,
                                    last_tok, pos, active, key,
                                    temperature, top_k, do_sample)
            return pk, pv, nxt

        return self._observed(
            jax.jit(decode, static_argnames=("top_k", "do_sample"),
                    donate_argnums=(1, 2)),
            "decode", donate=(1, 2))

    def decode_step(self, last_tok, pos, active, key, temperature):
        """One decode step for every DECODE-ACTIVE slot. `last_tok` /
        `pos` / `active` / `temperature` are HOST arrays (shape
        ``(max_slots,)``) owned by the scheduler — the step loop never
        branches on device values. Slots still mid-prefill must have
        ``active=False`` (their writes are redirected to the trash page).
        Returns the next token per slot as host numpy (the one host sync
        per step)."""
        jnp = _j().numpy
        self._refresh_params()
        self._ensure_pool()
        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
        args = (self._table_device(),
                jnp.asarray(last_tok, jnp.int32),
                jnp.asarray(pos, jnp.int32),
                jnp.asarray(active, bool),
                key,
                jnp.asarray(temperature, jnp.float32))
        if self._int8:
            (self._pk, self._pv, self._sk, self._sv,
             nxt) = self._decode_jit(
                self._dec._params, self._pk, self._pv, self._sk, self._sv,
                *args, top_k=self._top_k, do_sample=self._do_sample)
        else:
            self._pk, self._pv, nxt = self._decode_jit(
                self._dec._params, self._pk, self._pv, *args,
                top_k=self._top_k, do_sample=self._do_sample)
        return onp.asarray(nxt)

    # -- speculative decoding ----------------------------------------------

    def _build_verify(self):
        """ONE batched target program: consume ``[last, d_1..d_k]`` per
        slot (k+1 rows at positions ``pos..pos+k``), write each row's
        K/V to the slot's pages, and emit the greedy next token per row.
        Row ``i`` attends only positions ``<= pos + i``, so the batch is
        mathematically identical to k+1 sequential decode steps — the
        identity that makes greedy spec decode bit-equal to plain
        decode. Rows past a slot's mapped pages (``p > limit``) are
        redirected to the trash page; the scheduler never commits their
        outputs."""
        jax = _j()
        jnp = jax.numpy
        dec = self._dec
        H = dec._n_heads
        pt = self.page_tokens
        int8 = self._int8
        S = self.max_slots
        K1 = self.spec_k + 1
        write_token = self._make_write_token()

        from ..models.decoding import _dense, _ln, _split_qkv

        def run(params, pk, pv, sk, sv, table, toks, pos, active, limit):
            P = table.shape[1]
            PT = P * pt
            d = dec._units // H
            offs = jnp.arange(K1)
            p_abs = pos[:, None] + offs[None, :]               # (S, K1)
            pmax = params["pos"].shape[0]
            x = (params["embed"][toks]
                 + params["pos"][jnp.clip(p_abs, 0, pmax - 1)])
            writable = active[:, None] & (p_abs <= limit[:, None])
            wpage = jnp.take_along_axis(
                table, jnp.clip(p_abs // pt, 0, P - 1), axis=1)
            wpage = jnp.where(writable, wpage, 0)
            woff = p_abs % pt
            # (S, K1, PT) causal-per-row validity
            mask = jnp.arange(PT)[None, None, :] <= p_abs[:, :, None]

            L = len(pk)
            pk, pv = list(pk), list(pv)
            sk = list(sk) if int8 else [None] * L
            sv = list(sv) if int8 else [None] * L
            for li in range(L):
                lp = {n: a[li] for n, a in params["layers"].items()}
                pk_l, pv_l = pk[li], pv[li]
                sk_l, sv_l = sk[li], sv[li]
                h = _ln(x, lp["ln1_g"], lp["ln1_b"])
                q, k, v = _split_qkv(
                    _dense(h, lp["qkv_w"], lp["qkv_b"]), H)    # (S,H,K1,d)
                kt = jnp.transpose(k, (0, 2, 1, 3))            # (S,K1,H,d)
                vt = jnp.transpose(v, (0, 2, 1, 3))
                # column-at-a-time writes reuse the decode write_token
                # exactly (int8 grow-only rescale order preserved)
                for i in range(K1):
                    pk_l, sk_l = write_token(pk_l, sk_l, wpage[:, i],
                                             woff[:, i], kt[:, i])
                    pv_l, sv_l = write_token(pv_l, sv_l, wpage[:, i],
                                             woff[:, i], vt[:, i])
                vk = self._dequant_view(pk_l, sk_l, table)
                vv = self._dequant_view(pv_l, sv_l, table)
                vk = jnp.transpose(vk, (0, 2, 1, 3, 4)).reshape(S, H, PT, d)
                vv = jnp.transpose(vv, (0, 2, 1, 3, 4)).reshape(S, H, PT, d)
                s = jnp.einsum("shqd,shkd->shqk", q, vk,
                               preferred_element_type=jnp.float32)
                s = s / math.sqrt(d)
                s = jnp.where(mask[:, None, :, :], s, -jnp.inf)
                p = jax.nn.softmax(s, axis=-1).astype(vv.dtype)
                o = jnp.einsum("shqk,shkd->shqd", p, vv)
                o = jnp.transpose(o, (0, 2, 1, 3)).reshape(S, K1, H * d)
                x = x + _dense(o, lp["proj_w"], lp["proj_b"])
                h = _ln(x, lp["ln2_g"], lp["ln2_b"])
                ffn = _dense(
                    jax.nn.gelu(_dense(h, lp["ffn1_w"], lp["ffn1_b"])),
                    lp["ffn2_w"], lp["ffn2_b"])
                x = x + ffn
                pk[li], pv[li] = pk_l, pv_l
                sk[li], sv[li] = sk_l, sv_l
            pk, pv = tuple(pk), tuple(pv)
            sk = tuple(sk) if int8 else None
            sv = tuple(sv) if int8 else None
            logits = dec._logits(
                params, x.reshape(S * K1, -1)).reshape(S, K1, -1)
            tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            tgt = jnp.where(active[:, None], tgt, toks)
            pk, pv, sk, sv = self._constrain_pools(pk, pv, sk, sv)
            return pk, pv, sk, sv, tgt

        if int8:
            def verify(params, pk, pv, sk, sv, table, toks, pos, active,
                       limit):
                return run(params, pk, pv, sk, sv, table, toks, pos,
                           active, limit)

            return self._observed(
                jax.jit(verify, donate_argnums=(1, 2, 3, 4)),
                "verify", donate=(1, 2, 3, 4))

        def verify(params, pk, pv, table, toks, pos, active, limit):
            pk, pv, _, _, tgt = run(params, pk, pv, None, None, table,
                                    toks, pos, active, limit)
            return pk, pv, tgt

        return self._observed(
            jax.jit(verify, donate_argnums=(1, 2)),
            "verify", donate=(1, 2))

    def _build_draft(self):
        """ONE draft-model program: k unrolled greedy decode steps
        (each step identical in structure to the decode program, against
        the draft's own per-layer pools) — k drafted tokens per launch,
        feeding the target's verify program."""
        jax = _j()
        jnp = jax.numpy
        dec = self._draft_dec
        pt = self.page_tokens
        int8 = self._int8
        S = self.max_slots
        K = self.spec_k
        write_token = self._make_write_token()

        def run(params, pk, pv, sk, sv, table, last_tok, pos, active,
                limit):
            P = table.shape[1]
            PT = P * pt
            pmax = params["pos"].shape[0]
            L = len(pk)
            pk, pv = list(pk), list(pv)
            sk = list(sk) if int8 else [None] * L
            sv = list(sv) if int8 else [None] * L
            cur = last_tok
            outs = []
            for i in range(K):
                p_i = pos + i
                wpage = table[jnp.arange(S), jnp.clip(p_i // pt, 0, P - 1)]
                wpage = jnp.where(active & (p_i <= limit), wpage, 0)
                woff = p_i % pt
                mask = jnp.arange(PT)[None, :] <= p_i[:, None]
                x = (params["embed"][cur][:, None, :]
                     + params["pos"][jnp.clip(p_i, 0, pmax - 1)][:, None, :])
                for li in range(L):
                    lp = {n: a[li] for n, a in params["layers"].items()}
                    x, (pk[li], pv[li], sk[li], sv[li]) = \
                        self._decode_layer_step(
                            dec, lp, x, (pk[li], pv[li], sk[li], sv[li]),
                            table, wpage, woff, mask, write_token)
                logits = dec._logits(params, x[:, 0])
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                cur = jnp.where(active, nxt, cur)
                outs.append(cur)
            pk, pv = tuple(pk), tuple(pv)
            sk = tuple(sk) if int8 else None
            sv = tuple(sv) if int8 else None
            pk, pv, sk, sv = self._constrain_pools(pk, pv, sk, sv)
            return pk, pv, sk, sv, jnp.stack(outs, axis=1)      # (S, K)

        if int8:
            def draft(params, pk, pv, sk, sv, table, last_tok, pos,
                      active, limit):
                return run(params, pk, pv, sk, sv, table, last_tok, pos,
                           active, limit)

            return self._observed(
                jax.jit(draft, donate_argnums=(1, 2, 3, 4)),
                "draft", donate=(1, 2, 3, 4))

        def draft(params, pk, pv, table, last_tok, pos, active, limit):
            pk, pv, _, _, toks = run(params, pk, pv, None, None, table,
                                     last_tok, pos, active, limit)
            return pk, pv, toks

        return self._observed(
            jax.jit(draft, donate_argnums=(1, 2)),
            "draft", donate=(1, 2))

    def spec_propose(self, seqs):
        """Host n-gram drafts: `seqs` is a per-slot list (None for
        slots not decoding) of 1-D prompt+generated token arrays.
        Returns ``(max_slots, spec_k)`` int32 host numpy. No device
        program — the ngram draft's entire cost is this call."""
        out = onp.zeros((self.max_slots, self.spec_k), onp.int32)
        for s, seq in enumerate(seqs):
            if seq is not None:
                out[s] = self._ngram.propose(seq)
        return out

    def spec_draft_step(self, last_tok, pos, active, limit):
        """Run the draft model's k-step program; returns drafted tokens
        ``(max_slots, spec_k)`` as host numpy."""
        jnp = _j().numpy
        self._draft_dec._auto_refresh()
        self._ensure_pool()
        if self._draft_jit is None:
            self._draft_jit = self._build_draft()
        args = (self._table_device(),
                jnp.asarray(last_tok, jnp.int32),
                jnp.asarray(pos, jnp.int32),
                jnp.asarray(active, bool),
                jnp.asarray(limit, jnp.int32))
        if self._int8:
            (self._dpk, self._dpv, self._dsk, self._dsv,
             toks) = self._draft_jit(
                self._draft_dec._params, self._dpk, self._dpv,
                self._dsk, self._dsv, *args)
        else:
            self._dpk, self._dpv, toks = self._draft_jit(
                self._draft_dec._params, self._dpk, self._dpv, *args)
        return onp.asarray(toks)

    def spec_verify_step(self, last_tok, drafts, pos, active, limit):
        """Verify ``drafts`` (host ``(max_slots, spec_k)``) for every
        decoding slot in ONE batched target program. Returns the greedy
        target token per row as host numpy ``(max_slots, spec_k + 1)``:
        row ``i`` is the token the target emits after consuming
        ``[last, d_1..d_i]`` — the scheduler accepts the longest drafted
        prefix matching rows ``0..m-1`` plus row ``m`` as the bonus
        token (>= 1 token of guaranteed progress per round)."""
        jnp = _j().numpy
        self._refresh_params()
        self._ensure_pool()
        if self._verify_jit is None:
            self._verify_jit = self._build_verify()
        if not self._spec_gauge:
            self._register_spec_gauge()
        toks = onp.concatenate(
            [onp.asarray(last_tok, onp.int32)[:, None],
             onp.asarray(drafts, onp.int32)], axis=1)
        args = (self._table_device(),
                jnp.asarray(toks),
                jnp.asarray(pos, jnp.int32),
                jnp.asarray(active, bool),
                jnp.asarray(limit, jnp.int32))
        if self._int8:
            (self._pk, self._pv, self._sk, self._sv,
             tgt) = self._verify_jit(
                self._dec._params, self._pk, self._pv, self._sk,
                self._sv, *args)
        else:
            self._pk, self._pv, tgt = self._verify_jit(
                self._dec._params, self._pk, self._pv, *args)
        return onp.asarray(tgt)

    def spec_count(self, drafted, accepted):
        """Scheduler callback: fold one slot-round's drafted/accepted
        token counts into the engine's lifetime acceptance stats."""
        self._spec_drafted += int(drafted)
        self._spec_accepted += int(accepted)

    def spec_stats(self):
        """Lifetime speculative-decoding stats for this engine —
        surfaced per model in the gateway flight-recorder context."""
        drafted = self._spec_drafted
        return {"k": self.spec_k, "draft": self.draft_kind,
                "drafted": drafted, "accepted": self._spec_accepted,
                "accept_rate": (self._spec_accepted / drafted)
                if drafted else None}

    def _register_spec_gauge(self):
        """Per-model pull gauge for the lifetime acceptance rate;
        registered on first verify so the gateway's census_name
        override has already landed. Weakref probe, like the HBM
        owners."""
        self._spec_gauge = True
        ref = weakref.ref(self)

        def probe():
            eng = ref()
            if eng is None or not eng._spec_drafted:
                return None
            return eng._spec_accepted / eng._spec_drafted

        registry.register_pull_gauge(
            "mx_serve_spec_accept_rate", probe,
            "accepted draft tokens / drafted tokens since engine start "
            "[0, 1] (speculative decoding)",
            labels={"model": self.census_name})

    # -- debug / tests ------------------------------------------------------

    def slot_kv(self, slot, n_tokens):
        """Host copy of a slot's first `n_tokens` of K and V (dequantized
        under int8) — parity/tolerance checks in tests, not a hot path."""
        jnp = _j().numpy
        self._ensure_pool()
        idx = jnp.asarray(self._table[slot])
        outs = []
        for pool, scale in ((self._pk, self._sk), (self._pv, self._sv)):
            views = []
            L = len(pool)
            for layer in range(L):
                v = self._dequant_view(pool[layer],
                                       None if scale is None
                                       else scale[layer], idx)
                P, H, pt, d = v.shape
                views.append(jnp.transpose(v, (1, 0, 2, 3))
                             .reshape(H, P * pt, d)[:, :n_tokens])
            outs.append(onp.asarray(jnp.stack(views), onp.float32))
        return outs[0], outs[1]

    def xla_program_count(self):
        """Number of compiled programs across every family this engine
        owns: chunk-prefill (one per chunk bucket actually seen), decode,
        and — with spec decode armed — verify, draft, and draft-prefill.
        The recompile-count gate of `tests/test_serve.py` asserts this
        stays constant in steady state."""
        n = 0
        for f in (self._prefill_jit, self._decode_jit, self._verify_jit,
                  self._draft_jit, self._draft_prefill_jit):
            if f is None:
                continue
            size = getattr(f, "_cache_size", None)
            if size is not None:
                n += int(size())
        return n

    def shardcheck_report(self, mesh=None, hbm_budget_gb=None,
                          bucket=None):
        """Static sharding pre-flight (`mx.analysis.shardcheck`) over the
        engine's two compiled program families: the chunked-prefill jit
        (analyzed at `bucket`, default the largest chunk bucket) and the
        decode jit, which is audited as a latency hot path.

        The engine runs single-chip today, so with the default
        ``mesh=None`` this is a per-device byte budget (SC006) plus the
        donation audit (SC004); pass a mesh once pod-scale serving lands
        and the same call re-validates the layout against it. Returns
        ``{"prefill": ShardReport, "decode": ShardReport}``.
        """
        import functools

        from ..analysis.shardcheck import shardcheck
        from ..random import next_key

        jax = _j()
        sds = jax.ShapeDtypeStruct
        self._refresh_params()
        self._ensure_pool()
        if self._prefill_jit is None:
            self._prefill_jit = self._build_prefill()
        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
        params = self._dec._params
        pools = (self._pk, self._pv) + ((self._sk, self._sv)
                                        if self._int8 else ())
        donate = (1, 2, 3, 4) if self._int8 else (1, 2)
        S = self.max_slots
        key = next_key()
        i32, f32 = _j().numpy.int32, _j().numpy.float32
        statics = {"top_k": self._top_k, "do_sample": self._do_sample}

        bucket = int(bucket) if bucket is not None else self.chunk_buckets[-1]
        head_specs = self._shardcheck_specs()
        out_specs = self._shardcheck_out_specs()
        prefill_args = (params,) + pools + (
            sds((1, bucket), i32),                      # tokens
            sds((self.pages_per_slot,), i32),           # pages_row
            sds((bucket // self.page_tokens,), i32),    # chunk_pages
            sds((), i32), sds((), i32),                 # t_start, t_len
            key, sds((), f32))                          # key, temperature
        pf_specs = None if head_specs is None else head_specs + (
            (None,) * (len(prefill_args) - len(head_specs)))
        prefill = shardcheck(
            functools.partial(self._prefill_jit, **statics), *prefill_args,
            mesh=mesh, specs=pf_specs, out_specs=out_specs,
            donate_argnums=donate, hbm_budget_gb=hbm_budget_gb,
            name=f"SlotDecoder.prefill[b{bucket}]")

        decode_args = (params,) + pools + (
            sds((S, self.pages_per_slot), i32),         # page table
            sds((S,), i32), sds((S,), i32),             # last_tok, pos
            sds((S,), bool),                            # active
            key, sds((S,), f32))                        # key, temperature
        dc_specs = None if head_specs is None else head_specs + (
            (None,) * (len(decode_args) - len(head_specs)))
        decode = shardcheck(
            functools.partial(self._decode_jit, **statics), *decode_args,
            mesh=mesh, specs=dc_specs, out_specs=out_specs,
            donate_argnums=donate, hbm_budget_gb=hbm_budget_gb,
            hot_path=True, name="SlotDecoder.decode")
        return {"prefill": prefill, "decode": decode}

    def hbm_crosscheck(self, mesh=None):
        """Runtime-vs-static HBM accounting: compare the live-buffer
        census bytes attributed to THIS engine (KV pool + params owners)
        against shardcheck's SC006 per-device estimate for the decode
        program. The two are independent derivations — census sweeps
        ``jax.live_arrays()``, SC006 sums abstract avals — so agreement
        (the acceptance gate asks within 15%) validates both. Returns
        ``{"census_bytes", "sc006_bytes", "ratio", "owners"}``."""
        report = self.shardcheck_report(mesh=mesh)
        sc006 = int(report["decode"].per_device_bytes)
        c = _hbm.census(top_k=0)
        mine = {k: v for k, v in c["owners"].items()
                if k.startswith(f"{self.census_name}.")}
        total = sum(mine.values())
        return {"census_bytes": total, "sc006_bytes": sc006,
                "ratio": (total / sc006) if sc006 else None,
                "owners": mine}


def _occupancy_probe(allocator):
    """Weakly-bound pull probe for the page-occupancy gauge (engines come
    and go in tests; a dead allocator must not pin memory or poison the
    collector)."""
    ref = weakref.ref(allocator)

    def probe():
        a = ref()
        if a is None or a.usable_pages == 0:
            return None
        return a.used_pages / a.usable_pages

    return probe
