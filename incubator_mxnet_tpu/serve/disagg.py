"""Disaggregated prefill/decode serving: heterogeneous replica roles
with KV page migration (SERVING.md §disaggregation).

Prefill and decode sit on opposite ends of the roofline — prefill is
compute-bound (big matmuls over whole prompt chunks), decode is
bandwidth-bound (one token per resident request per step, gathered
through the page table). A homogeneous pod makes every replica compile
and serve both, so every replica's HBM carries peak-prefill working
sets AND the full resident-KV population. Disaggregation (DistServe,
Zhong et al., OSDI'24; Splitwise, Patel et al., ISCA'24) splits the
pod by ROLE:

- ``role="prefill"`` replicas run ONLY chunked prefill. Their slots
  turn over per-prompt (a handoff segment frees its slot the moment
  the final chunk samples the first token) and their pool holds only
  transient prompt pages — a ~25% cut of the model's page budget
  (`ModelRegistry.rebalance_pages_disagg`).
- ``role="decode"`` replicas run ONLY the gather-by-table decode
  family (plus spec-decode verify/draft when armed). They never see a
  prompt to prefill — requests arrive ALREADY PREFILLED through
  `Scheduler.adopt` — so their compile ledger never contains a prefill
  family, and the HBM that would have funded prefill working sets
  funds pages instead: more resident decode slots per chip.
- ``role="both"`` is the homogeneous default; a model whose replicas
  are all ``"both"`` never enters this module.

THE MIGRATION PLANE (this module is the choke point — lint FL021
flags cross-replica pool access anywhere else in serve/):

1. the gateway dispatches a fresh request to a prefill replica with
   ``prefill_only=True`` (placement: least chunk-backlog,
   `ReplicaRouter.pick_prefill`);
2. when the final chunk samples the first token, the scheduler parks
   the segment in ``take_prefilled()`` — slot freed, page refs kept;
3. `pump_migrations` (called from ``Gateway._step`` under the gateway
   lock) claims the segment, picks a decode replica (free pages +
   prefix warmth, `ReplicaRouter.pick_decode`), allocates the full
   decode-side page budget up front (the same no-mid-flight-OOM rule
   as admission), and copies the prompt's pages whole —
   `SlotDecoder.copy_pages_out` → `copy_pages_in`, whole-page byte
   copies, so the decode side is BIT-IDENTICAL to having prefilled
   locally (trailing garbage in a partial last page is masked by
   position exactly like locally-prefilled padding);
4. the moved pages become a content-addressed `PrefixCache` fill on
   the decode side — the same blake2b page-boundary digests now
   resolve there, so a follow-up request with the same prompt prefix
   warms against the DECODE replica (and the migration itself is
   idempotent against re-sends);
5. refcounts hand off: the source replica's request refs drop (its
   prefix cache keeps the prompt warm for future prefills), the
   destination request owns fresh refs, and the cache fill increfs the
   aligned pages — audited by ``mx_serve_page_migration_pages_total``
   and ``mx_serve_page_migration_bytes_total{model=}`` (bytes is
   EXACTLY pages-moved × `SlotDecoder.page_bytes`);
6. `Scheduler.adopt` admits the request directly into the decoding
   state, first token seeded, positions identical to a co-located
   request — greedy output is bit-identical.

ROLLBACK: a mid-handoff fault (the ``page_migration`` chaos seam) or
a page-exhausted decode side falls back to ``role="both"``
co-location — the request is adopted on its OWN prefill replica (the
KV never left), with a gateway-queue resume as the last resort when
even that pool cannot fund the decode tail. No path leaks a page:
destination pages allocated before a failed copy are rolled back
before the fallback runs, and tests assert allocator refcounts return
to baseline.

Knobs: ``MXNET_DISAGG`` (make every ``add()`` disaggregated by
default), ``MXNET_SERVE_PREFILL_REPLICAS`` /
``MXNET_SERVE_DECODE_REPLICAS`` (role counts under that gate) —
SERVING.md has the full table.
"""
from __future__ import annotations

import time

import numpy as onp

from ..fault.injection import FaultInjected
from ..telemetry import anatomy, registry, tracing
from .engine import PagePoolExhausted
from .scheduler import _NULL

__all__ = ["MigrationAborted", "pump_migrations", "warm_decode_replica",
           "decode_prefill_families", "migration_counts"]

_WARM_STEP_GUARD = 50_000


class MigrationAborted(RuntimeError):
    """A page migration could not run (no viable decode replica, or
    its pool is exhausted). The request is NOT lost — the caller falls
    back to co-located serving on the prefill replica."""


# -- telemetry ---------------------------------------------------------------

def _pages_counter(model):
    return registry.counter(
        "mx_serve_page_migration_pages_total",
        "KV pool pages moved prefill→decode by the disagg migration "
        "plane",
        labels={"model": model})


def _bytes_counter(model):
    return registry.counter(
        "mx_serve_page_migration_bytes_total",
        "bytes of KV moved prefill→decode (exactly pages moved × "
        "per-page pool bytes)",
        labels={"model": model})


def migration_counts(model):
    """Live ``(pages, bytes)`` counter values for `model` — the byte
    audit surface for tests and benches."""
    return (int(_pages_counter(model).value),
            int(_bytes_counter(model).value))


# -- role helpers ------------------------------------------------------------

def role_of(rep):
    return getattr(rep, "role", "both")


def is_disagg(model):
    """True when any replica of `model` (a gateway `_Model`) carries a
    dedicated role — the gateway's gate for running the migration
    plane at all."""
    return any(role_of(r) != "both" for r in model.replicas)


def _can_adopt(rep, prompt_len, max_new):
    """Viability predicate for decode placement: a free slot now (adopt
    never queues) and a page budget the pool could cover after
    dropping unused cache entries."""
    if rep.draining or role_of(rep) == "prefill":
        return False
    sched = rep.sched
    if sched.free_slots <= 0:
        return False
    plan = getattr(sched, "adopt_page_plan", None)
    if plan is None:
        return False
    _content, physical, reserved = plan(prompt_len, max_new)
    alloc = rep.slots.allocator
    reclaimable = getattr(rep.slots.prefix_cache, "cached_pages", 0)
    return (physical + reserved
            <= alloc.free_pages - sched._spec_reserved_total()
            + reclaimable)


# -- the migration plane -----------------------------------------------------

def pump_migrations(gw, m, now):
    """Claim every segment whose prefill-only pass completed this step
    and move it to a decode replica (or fall back). Runs under the
    gateway lock from ``Gateway._step``; this function and its callees
    are the ONLY code that touches another replica's allocator, prefix
    cache, or pool leaves (lint FL021 enforces it)."""
    moved = 0
    for rep in list(m.replicas):
        take = getattr(rep.sched, "take_prefilled", None)
        if take is None:
            continue
        for seg in take():
            greq = next((r for r in rep.live if r._segment is seg), None)
            if greq is None:
                # orphaned segment (its gateway handle was re-owned by
                # a crash requeue): release the pages, loudly traced
                if seg.pages:
                    rep.slots.allocator.decref(seg.pages)
                seg.pages = None
                rep.sched.finish_handoff(seg)
                tracing.event("serve.disagg.orphan", request=seg.id,
                              replica=rep.label)
                continue
            # the first token reaches the tenant handle before the
            # pages move — TTFT is a prefill-side property
            gw._drain_segment(greq, seg, now)
            try:
                moved += _migrate(gw, m, rep, greq, seg, now)
            except (MigrationAborted, PagePoolExhausted,
                    FaultInjected) as e:
                _fallback_colocate(gw, rep, greq, seg, now, reason=e)
                moved += 1
    return moved


def _migrate(gw, m, src, greq, seg, now):
    prompt = seg.prompt
    p_len = int(prompt.size)
    dst = m.router.pick_decode(
        m.replicas, prompt=prompt,
        viable=lambda r: r is not src
        and _can_adopt(r, p_len, seg.max_new))
    if dst is None:
        raise MigrationAborted(
            f"no decode replica can adopt request {seg.id} "
            "(slots or pages exhausted everywhere)")
    content, physical, reserved = dst.sched.adopt_page_plan(
        p_len, seg.max_new)
    alloc = dst.slots.allocator
    need = physical + reserved
    spec_total = dst.sched._spec_reserved_total()
    if need > alloc.free_pages - spec_total:
        dst.slots.prefix_cache.evict_unused(need + spec_total)
    if need > alloc.free_pages - spec_total:
        raise MigrationAborted(
            f"decode replica {dst.label} is page-exhausted: request "
            f"{seg.id} needs {need} pages, {alloc.free_pages} free")
    # full decode budget up front — the adopted request can never hit
    # a mid-flight page OOM, same rule as local admission
    mig_t0 = time.perf_counter() if anatomy._ENABLED else None
    dst_pages = alloc.alloc(physical)
    try:
        from ..fault.injection import inject_at

        inject_at("page_migration")
        if hasattr(src.slots, "copy_pages_out") \
                and hasattr(dst.slots, "copy_pages_in"):
            payload = src.slots.copy_pages_out(seg.pages[:content])
            dst.slots.copy_pages_in(dst_pages[:content], payload)
    except BaseException:
        # rollback: the destination never saw this request
        alloc.decref(dst_pages)
        raise
    # content-addressed cache fill: the prompt's page digests now
    # resolve on the decode side (increfs the aligned pages)
    dst.slots.prefix_cache.register(prompt, dst_pages[:content])
    page_bytes = int(getattr(src.slots, "page_bytes", 0) or 0)
    _pages_counter(m.name).inc(content)
    _bytes_counter(m.name).inc(content * page_bytes)
    deadline_s = None if greq.deadline is None \
        else max(greq.deadline - now, 1e-6)
    new_seg = dst.sched.adopt(
        prompt, seg.first_token, seg.max_new, dst_pages,
        spec_reserved=reserved, temperature=greq.temperature,
        eos_id=greq.eos_id, deadline_s=deadline_s,
        parent_span=greq._spans.get("request", _NULL),
        tenant=greq.tenant)
    # refcount handoff: the request's source refs drop; the source
    # prefix cache keeps the prompt warm for future prefills there
    src.slots.allocator.decref(seg.pages)
    seg.pages = None
    src.sched.finish_handoff(seg)
    src.live.remove(greq)
    dst.live.append(greq)
    greq._segment = new_seg
    greq.replica = dst.label
    if mig_t0 is not None:
        # the copy+adopt window is migration residency on the ADOPTING
        # side (it funds the pages and runs the adopt)
        anatomy.on_migration(dst.sched, mig_t0, time.perf_counter())
    rec = greq._anatomy
    if rec is not None:
        new_seg.anatomy = rec
        rec.adopted(now, migrated=True)
    tracing.event("serve.disagg.migrate", request=greq.id,
                  src=src.label, dst=dst.label, pages=content,
                  bytes=content * page_bytes)
    return 1


def _fallback_colocate(gw, src, greq, seg, now, reason):
    """Rollback to ``role="both"`` co-location: finish the request on
    the replica that already holds its KV. Used when the handoff
    faulted mid-copy (``page_migration`` seam) or every decode replica
    is page-exhausted. Falls through to a gateway-queue resume when
    even the source pool cannot fund the decode tail — the request is
    never dropped and no page leaks on any path."""
    sched = src.sched
    alloc = src.slots.allocator
    _content, physical, reserved = sched.adopt_page_plan(
        int(seg.prompt.size), seg.max_new)
    extra = physical - len(seg.pages)
    need = extra + reserved
    ok = sched.free_slots > 0
    if ok and need > alloc.free_pages - sched._spec_reserved_total():
        src.slots.prefix_cache.evict_unused(
            need + sched._spec_reserved_total())
        ok = need <= alloc.free_pages - sched._spec_reserved_total()
    if not ok:
        _requeue(gw, src, greq, seg, now, reason)
        return
    pages = list(seg.pages) + (alloc.alloc(extra) if extra > 0 else [])
    seg.pages = None            # ownership moves to the adopted request
    deadline_s = None if greq.deadline is None \
        else max(greq.deadline - now, 1e-6)
    new_seg = sched.adopt(
        seg.prompt, seg.first_token, seg.max_new, pages,
        spec_reserved=reserved, temperature=greq.temperature,
        eos_id=greq.eos_id, deadline_s=deadline_s,
        parent_span=greq._spans.get("request", _NULL),
        tenant=greq.tenant)
    sched.finish_handoff(seg)
    greq._segment = new_seg     # stays in src.live, same replica label
    rec = greq._anatomy
    if rec is not None:
        new_seg.anatomy = rec
        rec.adopted(now, migrated=False)
    tracing.event("serve.disagg.fallback", request=greq.id,
                  replica=src.label, reason=str(reason))


def _requeue(gw, src, greq, seg, now, reason):
    """Last-resort fallback: re-enter the gateway queue as a resume —
    the preemption path, so the first token survives on the handle and
    the re-prefill lands warm (the prompt's pages are registered in
    the source replica's prefix cache)."""
    if seg.pages:
        src.slots.allocator.decref(seg.pages)
    seg.pages = None
    src.sched.finish_handoff(seg)
    src.live.remove(greq)
    greq._segment = None
    gen = onp.asarray(greq.tokens, onp.int32)
    greq._resume_prompt = onp.concatenate(
        [onp.asarray(greq.prompt, onp.int32), gen])
    greq._remaining = greq.max_new - len(greq.tokens)
    greq.preemptions += 1
    greq.state = "queued"
    greq.replica = None
    if greq._anatomy is not None:
        greq._anatomy.requeued(now, "migration_fallback")
    gw.preemptions_total += 1
    greq._spans["admit"] = tracing.open_span(
        "gateway.admit", parent=greq._spans.get("request", _NULL),
        resumed=True, migration_fallback=True)
    gw._queues[greq.priority].push(greq.tenant, greq)
    tracing.event("serve.disagg.requeue", request=greq.id,
                  replica=src.label, reason=str(reason))


# -- warm + gates ------------------------------------------------------------

def warm_decode_replica(rep, warm_lens=(8,), warm_new=2):
    """Warm ONLY the decode-side families of a decode-role replica:
    fake already-prefilled requests are adopted (page content is
    garbage — compilation depends on shapes alone) and driven to
    completion, compiling decode (and, when armed, spec verify/draft)
    while the replica is still outside the routing set. The prefill
    family is never touched, so the ledger invariant — decode replicas
    never compile a prefill program — holds from the replica's first
    live request."""
    sched = rep.sched
    alloc = rep.slots.allocator
    warm_tok = anatomy.warmup_begin(sched)
    max_len = int(getattr(rep.slots, "max_len", 1 << 30))
    warm_new = max(2, int(warm_new))    # >= 1 real decode step
    for i, L in enumerate(warm_lens):
        L = max(1, min(int(L), max_len - warm_new - 1))
        prompt = onp.full(L, i + 1, onp.int32)
        _content, physical, reserved = sched.adopt_page_plan(L, warm_new)
        pages = alloc.alloc(physical)
        seg = sched.adopt(prompt, 1, warm_new, pages,
                          spec_reserved=reserved)
        guard = 0
        while not seg.done:
            sched.step()
            guard += 1
            if guard > _WARM_STEP_GUARD:
                raise RuntimeError(
                    f"replica {rep.label}: decode warmup (len {L}) did "
                    f"not finish within {_WARM_STEP_GUARD} engine steps")
        if seg.error is not None:
            raise RuntimeError(
                f"replica {rep.label}: decode warmup (len {L}) failed: "
                f"{type(seg.error).__name__}: {seg.error}")
    anatomy.warmup_end(sched, warm_tok)


def decode_prefill_families(gw, model):
    """Prefill evidence on `model`'s decode-role replicas — MUST be
    empty; tests/bench/dryrun assert on it. Checks both the live
    program caches (``_prefill_jit`` ever built) and the instrumented
    compile ledger (any ``serve:<label>.*prefill*`` family)."""
    from ..telemetry import compiles

    m = gw._models[model]
    led = compiles.ledger()
    bad = {}
    for rep in m.replicas:
        if role_of(rep) != "decode":
            continue
        evidence = []
        for attr in ("_prefill_jit", "_draft_prefill_jit"):
            if getattr(rep.slots, attr, None) is not None:
                evidence.append(f"live:{attr}")
        prefix = f"serve:{rep.label}."
        for fam, entries in led.items():
            if fam.startswith(prefix) and "prefill" in fam and entries:
                evidence.append(f"ledger:{fam}")
        if evidence:
            bad[rep.label] = evidence
    return bad
