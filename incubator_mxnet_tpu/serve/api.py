"""`mx.serve.ServeEngine` — the thread-safe front door of the serving
subsystem.

Three ways in, one engine:

- ``generate(prompt_ids, max_new_tokens)`` — blocking; returns the full
  sequence (prompt + generated) as int32 numpy, same surface as
  `GPTDecoder.generate` for one request;
- ``submit(...)`` → handle + ``iter_tokens(handle)`` — streaming; tokens
  yield as each decode step lands them;
- ``generate_many([...])`` — batch convenience over submit+drive.

Threading model: ONE lock guards the scheduler; `step()` takes it for a
whole iteration, `submit()` only for admission. A background driver
(``start()``) can own the step loop while client threads submit and
stream — or, with no driver, whichever thread is blocked on a result
drives the engine itself (the lock makes concurrent drivers safe, just
redundant). ``shutdown(drain=True)`` stops admission, finishes the
requests already in slots, and fails the never-admitted queue — loudly.
"""
from __future__ import annotations

import queue as _queue
import threading

import numpy as onp

from ..telemetry import anatomy, tracing
from ..telemetry.locks import tracked_lock
from ..util import env_float as _env_float
from ..util import env_int as _env_int
from .engine import SlotDecoder
from .scheduler import EngineClosed, Request, Scheduler, _DONE

__all__ = ["ServeEngine"]

_IDLE_SLEEP_S = 0.002     # driver backoff when there is nothing to do
_DRIVER_MAX_CONSECUTIVE_FAILURES = 3


class ServeEngine:
    """Continuous-batching inference engine over a GPT Block (or a
    prebuilt `GPTDecoder`).

    Parameters
    ----------
    block_or_decoder : Block | GPTDecoder
        The model to serve.
    max_slots : int
        In-flight request capacity (static decode batch width).
    max_len : int, optional
        Per-slot sequence capacity; defaults to the model's position
        table length.
    page_tokens / prefill_chunk / n_pages / kv_dtype / prefix_reuse
        Paged-KV knobs, forwarded to `SlotDecoder` (defaults ride
        ``MXNET_SERVE_PAGE_TOKENS`` / ``MXNET_SERVE_PREFILL_CHUNK`` /
        ``MXNET_SERVE_KV_DTYPE``; see SERVING.md).
    policy : "fifo" | "sjf", optional
        Admission order (default ``MXNET_SERVE_POLICY`` or fifo).
    max_queue : int, optional
        Bounded admission queue depth (default ``MXNET_SERVE_MAX_QUEUE``
        or 128); a full queue raises `QueueFull` at submit.
    deadline_s : float, optional
        Default per-request deadline (``MXNET_SERVE_DEADLINE_S``;
        unset = none). Individual submits may override.
    eos_id : int, optional
        Token id that retires a request early (engine default;
        per-request override at submit).
    do_sample / top_k : static sampling mode (compiled in — per-request
        variation would recompile); `temperature` stays per-request.
    seed : int
        Base PRNG seed for sampled decode (greedy ignores it).
    spec_k : int, optional
        Speculative-decoding draft length (default
        ``MXNET_SERVE_SPEC_K`` or 0 = off). Requires greedy decoding;
        output stays token-for-token identical to ``spec_k=0``.
    draft : str | Block | GPTDecoder, optional
        Draft source when ``spec_k > 0``: ``"ngram"`` (host n-gram
        proposer, no extra device programs — the default, also via
        ``MXNET_SERVE_SPEC_DRAFT``) or a small model that shares the
        target's tokenizer/vocab.
    """

    def __init__(self, block_or_decoder, max_slots=8, max_len=None,
                 page_tokens=None, prefill_chunk=None, n_pages=None,
                 kv_dtype=None, prefix_reuse=True, policy=None,
                 max_queue=None, deadline_s=None, eos_id=None,
                 do_sample=False, top_k=None, temperature=1.0, seed=0,
                 spec_k=None, draft=None):
        import os

        slots = SlotDecoder(block_or_decoder, max_slots=max_slots,
                            max_len=max_len, page_tokens=page_tokens,
                            prefill_chunk=prefill_chunk, n_pages=n_pages,
                            kv_dtype=kv_dtype, prefix_reuse=prefix_reuse,
                            do_sample=do_sample, top_k=top_k,
                            spec_k=spec_k, draft=draft)
        if policy is None:
            policy = os.environ.get("MXNET_SERVE_POLICY", "fifo")
        if max_queue is None:
            max_queue = _env_int("MXNET_SERVE_MAX_QUEUE", 128)
        if deadline_s is None:
            deadline_s = _env_float("MXNET_SERVE_DEADLINE_S", None)
        self._sched = Scheduler(slots, max_queue=max_queue, policy=policy,
                                default_deadline=deadline_s, eos_id=eos_id,
                                seed=seed)
        self._default_temperature = float(temperature)
        self._lock = tracked_lock("serve.engine")
        self._driver = None
        self._stop = threading.Event()

    # -- introspection ------------------------------------------------------

    @property
    def max_slots(self):
        return self._sched.slots.max_slots

    @property
    def max_len(self):
        return self._sched.slots.max_len

    @property
    def queue_depth(self):
        with self._lock:
            return self._sched.queue_depth

    @property
    def n_active(self):
        with self._lock:
            return self._sched.n_active

    @property
    def closed(self):
        return self._sched.closed

    @property
    def page_occupancy(self):
        """Fraction of usable KV pool pages referenced (shared pages
        counted once)."""
        a = self._sched.slots.allocator
        return a.used_pages / a.usable_pages if a.usable_pages else 0.0

    @property
    def kv_bytes_per_slot(self):
        """Resident KV pool bytes per decode slot (0 before first use)."""
        return self._sched.slots.kv_bytes_per_slot

    def spec_stats(self):
        """Speculative-decoding counters: ``{"k", "draft", "drafted",
        "accepted", "accept_rate"}`` (all zero when ``spec_k=0``)."""
        return self._sched.slots.spec_stats()

    def xla_program_count(self):
        """Compiled XLA programs currently live (prefill buckets + the
        one decode program) — constant in steady state."""
        return self._sched.slots.xla_program_count()

    # -- submission ---------------------------------------------------------

    def submit(self, prompt_ids, max_new_tokens, temperature=None,
               eos_id=None, deadline_s=None):
        """Enqueue one request; returns its handle (a `Request`).

        Raises `QueueFull` when the admission queue is at capacity and
        `EngineClosed` after shutdown — backpressure is the caller's
        signal, never a silent drop."""
        if temperature is None:
            temperature = self._default_temperature
        with self._lock:
            req = self._sched.submit(prompt_ids, max_new_tokens,
                                     temperature=temperature,
                                     eos_id=eos_id, deadline_s=deadline_s)
            # standalone-engine anatomy: request == segment here, so the
            # engine owns the record end to end (the gateway attaches
            # its own records to segments AFTER its dispatch instead)
            rec = anatomy.begin(
                req.id, req.tenant, self._sched.capacity_model,
                "normal", req.submit_t, deadline=req.deadline)
            if rec is not None:
                rec.owner = "engine"
                req.anatomy = rec
            return req

    # -- driving ------------------------------------------------------------

    def step(self):
        """One scheduling iteration (admit + one decode step for every
        occupied slot). Returns True if progress was made.

        A crash (including an injected ``serve_step`` fault) leaves a
        flight-recorder dump behind — the postmortem carries the active
        requests' spans — and then propagates unchanged."""
        try:
            with self._lock:
                return self._sched.step()
        except Exception as e:
            from ..telemetry import hbm

            # RESOURCE_EXHAUSTED gets the OOM post-mortem (census +
            # compile ledger in the dump context); the generic dump is
            # skipped when the post-mortem already wrote one
            if hbm.maybe_oom_postmortem("serve_step", e) is None:
                tracing.maybe_flight_dump("serve_step", e)
            raise

    def _driver_running(self):
        d = self._driver
        return d is not None and d.is_alive()

    def _drive_until(self, reqs, timeout=None):
        """Make `reqs` finish: wait on the driver if one is running,
        otherwise step the engine from this thread."""
        import time

        t_end = None if timeout is None else time.monotonic() + timeout
        for req in reqs:
            while not req.done:
                if t_end is not None and time.monotonic() > t_end:
                    raise TimeoutError(
                        f"request {req.id} still {req.state} after "
                        f"{timeout}s")
                if self._driver_running():
                    req.wait(0.05)
                else:
                    progressed = self.step()
                    if not progressed and not req.done:
                        raise RuntimeError(
                            f"serve engine stalled: request {req.id} is "
                            f"{req.state} but the scheduler is idle "
                            "(this is a bug — please report)")

    def generate(self, prompt_ids, max_new_tokens, temperature=None,
                 eos_id=None, deadline_s=None, timeout=None):
        """Blocking single-request generation. Returns the FULL sequence
        (prompt + generated tokens) as a 1D int32 numpy array — the
        per-request view of what `GPTDecoder.generate` returns for a
        batch."""
        req = self.submit(prompt_ids, max_new_tokens,
                          temperature=temperature, eos_id=eos_id,
                          deadline_s=deadline_s)
        self._drive_until([req], timeout=timeout)
        toks = req.result()               # raises on failure
        return onp.concatenate([onp.asarray(req.prompt, onp.int32),
                                onp.asarray(toks, onp.int32)])

    def generate_many(self, prompts, max_new_tokens, temperature=None,
                      eos_id=None, deadline_s=None, timeout=None):
        """Batch convenience: submit every prompt, drive to completion,
        return the list of full sequences (prompt order preserved even
        when completion is out of order)."""
        reqs = [self.submit(p, max_new_tokens, temperature=temperature,
                            eos_id=eos_id, deadline_s=deadline_s)
                for p in prompts]
        self._drive_until(reqs, timeout=timeout)
        outs = []
        for req in reqs:
            toks = req.result()
            outs.append(onp.concatenate([onp.asarray(req.prompt, onp.int32),
                                         onp.asarray(toks, onp.int32)]))
        return outs

    def iter_tokens(self, handle: Request, timeout=30.0):
        """Stream `handle`'s tokens as the engine produces them.

        With a background driver running, this just blocks on the
        stream; without one, the consuming thread steps the engine
        itself. Raises the request's error (deadline, shutdown) at the
        point of failure; `timeout` bounds the wait for any single
        token."""
        while True:
            try:
                item = handle._stream.get_nowait()
            except _queue.Empty:
                if self._driver_running() or handle.done:
                    try:
                        item = handle._stream.get(timeout=timeout)
                    except _queue.Empty:
                        raise TimeoutError(
                            f"no token from request {handle.id} in "
                            f"{timeout}s (state={handle.state})") from None
                else:
                    self.step()
                    continue
            if item is _DONE:
                if handle.error is not None:
                    raise handle.error
                return
            yield item

    # -- driver thread ------------------------------------------------------

    def start(self):
        """Start the background driver thread: it owns the step loop so
        client threads only submit/stream. Idempotent."""
        import time

        if self._driver_running():
            return self
        self._stop.clear()

        def _loop():
            import logging

            log = logging.getLogger("incubator_mxnet_tpu.serve")
            failures = 0
            while not self._stop.is_set():
                try:
                    progressed = self.step()
                    failures = 0
                except Exception as e:
                    # step() already flight-dumped; a transient fault
                    # (chaos seam, retryable fabric error) must not
                    # silently kill the driver thread — but a
                    # deterministic bug must not spin it forever either
                    failures += 1
                    log.error(
                        "serve driver: step failed (%d consecutive): "
                        "%s: %s", failures, type(e).__name__, e)
                    if failures >= _DRIVER_MAX_CONSECUTIVE_FAILURES:
                        log.error(
                            "serve driver: stopping after %d consecutive "
                            "step failures — in-flight requests need a "
                            "manual step()/start() after the cause is "
                            "fixed", failures)
                        break
                    time.sleep(_IDLE_SLEEP_S)
                    continue
                if not progressed:
                    # nothing queued, nothing running — idle backoff
                    time.sleep(_IDLE_SLEEP_S)

        self._driver = threading.Thread(target=_loop, name="mx-serve-driver",
                                        daemon=True)
        self._driver.start()
        return self

    def stop(self):
        """Stop the driver thread (requests stay queued/running; call
        `step()` manually or `start()` again to resume)."""
        self._stop.set()
        d = self._driver
        if d is not None:
            d.join(timeout=5.0)
        self._driver = None

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self, drain=True, timeout=None):
        """Stop the engine. ``drain=True`` finishes the requests already
        occupying slots (new work and the never-admitted queue are
        rejected with `EngineClosed`); ``drain=False`` fails everything
        immediately. Releases the device KV cache."""
        import time

        with self._lock:
            self._sched.close(drain=drain)
            running = [r for r in self._sched._in_slot if r is not None]
        if drain and running:
            t_end = None if timeout is None else time.monotonic() + timeout
            while True:
                with self._lock:
                    if self._sched.n_active == 0:
                        break
                if t_end is not None and time.monotonic() > t_end:
                    raise TimeoutError(
                        f"drain did not finish in {timeout}s "
                        f"({self._sched.n_active} slots still busy)")
                if not self._driver_running():
                    self.step()
                else:
                    time.sleep(0.01)
        self.stop()
        with self._lock:
            # drop the prefix cache's page references before the pool
            # itself: a clean shutdown leaves the allocator empty
            self._sched.slots.prefix_cache.clear()
            self._sched.slots.release()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
