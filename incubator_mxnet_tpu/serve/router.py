"""Replica routing for pod-scale serving: which of a model's N engines
gets the next request.

One model behind the gateway may be served by N independent replicas
(`ModelRegistry.add(..., replicas=N, mesh=...)`) — each its own
`SlotDecoder` (possibly a mesh-sharded `serve.sharded.ShardedSlotDecoder`
on a disjoint device slice), its own page pool, prefix cache, scheduler,
and two compiled program families. This module decides placement:

- **least-loaded** — replicas are ranked by headroom: the free fraction
  of their KV page pool minus a queue-depth penalty
  (``w_pages * free_page_frac − w_queue * queue_depth``). Pages are the
  scarce serving resource (a deep queue with free pages drains faster
  than a shallow queue on a full pool), so pages carry the larger
  weight.

- **session affinity** (``MXNET_SERVE_AFFINITY``) — ``prefix`` (default)
  probes each replica's prefix cache with the request's prompt
  (`PrefixCache.shared_tokens`, a read-only host-side digest walk) and
  prefers the replicas holding the longest warm page-aligned prefix: a
  tenant's shared-system-prompt burst lands where its KV pages already
  live instead of re-prefilling on a cold replica. ``tenant`` pins each
  tenant to a stable hash-preferred replica (useful when prompts do not
  share pages but per-tenant batching locality matters). ``off`` is
  pure least-loaded.

Affinity never overrides viability: a warm replica with no capacity is
skipped (the gateway may then preempt on the chosen replica, not the
warm one). Ties inside the warm set fall back to least-loaded.

`replica_meshes` carves one host's device list into disjoint per-replica
mesh slices — the 2-replica × 4-way-TP pod layout on 8 devices is
``replica_meshes("tp=4", 2)``.
"""
from __future__ import annotations

import hashlib
import os

__all__ = ["ReplicaRouter", "replica_meshes"]

_AFFINITY_MODES = ("prefix", "tenant", "off")


def replica_meshes(spec, n_replicas, devices=None):
    """N disjoint serving meshes of ``prod(spec)`` devices each, carved
    consecutively from `devices` (default: all local devices). Raises
    when the host cannot seat ``n_replicas × prod(spec)`` devices."""
    from .sharded import parse_mesh_spec, serve_mesh

    axes = parse_mesh_spec(spec)
    per = 1
    for v in axes.values():
        per *= int(v)
    if devices is None:
        import jax

        devices = jax.devices()
    devices = list(devices)
    need = per * int(n_replicas)
    if len(devices) < need:
        raise ValueError(
            f"replica_meshes: {n_replicas} replicas of {axes} need "
            f"{need} devices, have {len(devices)}")
    return [serve_mesh(axes, devices=devices[i * per:(i + 1) * per])
            for i in range(int(n_replicas))]


def _tenant_hash(tenant, n):
    """Stable (process-independent) tenant → replica-index hash."""
    h = hashlib.blake2b(str(tenant).encode(), digest_size=4)
    return int.from_bytes(h.digest(), "big") % max(int(n), 1)


class ReplicaRouter:
    """Pick a replica for one request: affinity first, least-loaded
    within the affinity set. Stateless between calls — every decision
    reads the replicas' live allocator/scheduler counters, so the
    router never drifts from reality."""

    def __init__(self, affinity=None, w_pages=1.0, w_queue=0.25):
        if affinity is None:
            affinity = os.environ.get("MXNET_SERVE_AFFINITY", "") \
                or "prefix"
        affinity = str(affinity).lower()
        if affinity in ("0", "none", "false"):
            affinity = "off"
        if affinity not in _AFFINITY_MODES:
            raise ValueError(
                f"unknown affinity mode {affinity!r} (one of "
                f"{', '.join(_AFFINITY_MODES)}; knob MXNET_SERVE_AFFINITY)")
        self.affinity = affinity
        self.w_pages = float(w_pages)
        self.w_queue = float(w_queue)

    # -- scoring ------------------------------------------------------------

    def load_score(self, replica):
        """Headroom score: higher = better target. Free-page fraction
        of the pool minus a queue-depth penalty (pool pressure is the
        scarcer resource; see module docstring)."""
        slots = replica.slots
        alloc = getattr(slots, "allocator", None)
        if alloc is not None and getattr(alloc, "usable_pages", 0):
            free_frac = alloc.free_pages / alloc.usable_pages
        else:
            free_frac = 1.0
        return (self.w_pages * free_frac
                - self.w_queue * replica.sched.queue_depth)

    def warm_tokens(self, replica, prompt):
        """Tokens of `prompt` already resident in the replica's prefix
        cache (0 when it has none, e.g. test stubs)."""
        cache = getattr(replica.slots, "prefix_cache", None)
        if cache is None or prompt is None:
            return 0
        try:
            return int(cache.shared_tokens(prompt))
        except Exception:
            return 0

    # -- selection ----------------------------------------------------------

    def pick(self, replicas, prompt=None, tenant=None, viable=None):
        """The replica to dispatch to, or None when `replicas` is empty
        / nothing passes `viable`. `viable` is the gateway's capacity
        (or capacity-after-preemption) predicate. A replica marked
        ``draining`` by the elastic controller is never picked, even
        for callers routing without a viability predicate."""
        cands = [r for r in replicas
                 if not getattr(r, "draining", False)
                 and (viable is None or viable(r))]
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        if self.affinity == "prefix":
            warm = [(self.warm_tokens(r, prompt), r) for r in cands]
            best = max(w for w, _ in warm)
            if best > 0:
                cands = [r for w, r in warm if w == best]
        elif self.affinity == "tenant" and tenant is not None:
            idx = _tenant_hash(tenant, len(replicas))
            preferred = replicas[idx]
            if any(r is preferred for r in cands):
                return preferred
        return max(cands, key=self.load_score)

    # -- two-stage disaggregated dispatch (serve/disagg.py) -----------------

    def pick_prefill(self, replicas, viable=None):
        """Stage 1 of disaggregated dispatch: the prefill-capable
        replica (role ``prefill`` or ``both``) with the SHALLOWEST
        chunk backlog — prefill replicas are compute-bound, so queued
        prompt chunks (not pages) are the contended resource. Ties
        break by load score."""
        cands = [r for r in replicas
                 if not getattr(r, "draining", False)
                 and getattr(r, "role", "both") != "decode"
                 and (viable is None or viable(r))]
        if not cands:
            return None
        def backlog(r):
            return int(getattr(r.sched, "prefill_backlog",
                               r.sched.queue_depth))
        return min(cands, key=lambda r: (backlog(r),
                                         -self.load_score(r)))

    def pick_decode(self, replicas, prompt=None, viable=None):
        """Stage 2 of disaggregated dispatch: the decode-capable
        replica (role ``decode`` or ``both``) a prefilled request's
        pages migrate to. Prefix warmth first — a replica already
        holding this prompt's page-aligned digests adopts the request
        with fewer (or zero) pages to copy — then free pages, the
        decode-side scarce resource."""
        cands = [r for r in replicas
                 if not getattr(r, "draining", False)
                 and getattr(r, "role", "both") != "prefill"
                 and (viable is None or viable(r))]
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        if self.affinity == "prefix":
            warm = [(self.warm_tokens(r, prompt), r) for r in cands]
            best = max(w for w, _ in warm)
            if best > 0:
                cands = [r for w, r in warm if w == best]
        return max(cands, key=self.load_score)
