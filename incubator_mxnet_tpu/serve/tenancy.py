"""Multi-tenant serving primitives: priority tiers, token-rate quotas,
and weighted deficit-round-robin (WDRR) fairness.

Pure host-side machinery for `serve.gateway` — nothing here touches jax
or the device. Three pieces:

- **priority tiers** — an ordered tuple of tier names, highest first
  (default ``("high", "normal", "low")``; override via
  ``MXNET_SERVE_PRIORITY_TIERS=a,b,c``). The gateway keeps one WDRR
  queue per tier and always drains higher tiers first; a higher-tier
  arrival may PREEMPT a lower-tier running slot (gateway.py).

- :class:`TokenBucket` — the per-tenant token-rate quota. Capacity
  refills continuously at ``rate`` tokens/s up to ``burst``; a request
  is dispatched only when the bucket covers its estimated cost
  (prompt + max_new tokens), and the UNUSED part of the estimate is
  credited back at completion, so quotas meter real token work, not
  worst-case reservations. ``rate=None`` = unmetered (the default
  tenant profile unless ``MXNET_SERVE_TENANT_QUOTA`` says otherwise).

- :class:`WDRRQueue` — deficit round robin with per-tenant weights
  (Shreedhar & Varghese, SIGCOMM '95) over heterogeneous request costs:
  each visit grants a tenant ``quantum * weight`` deficit; its head
  request dispatches only when the accumulated deficit covers the
  request's cost. A tenant whose queue empties forfeits its deficit
  (no banking), so long-idle tenants cannot burst past the weights.

All clocks are explicit ``now`` parameters (monotonic seconds) — the
tests drive virtual time, the gateway passes ``time.monotonic()``.
"""
from __future__ import annotations

import collections

__all__ = ["DEFAULT_TIERS", "parse_tiers", "parse_quota", "TokenBucket",
           "Tenant", "WDRRQueue"]

DEFAULT_TIERS = ("high", "normal", "low")


def parse_tiers(spec=None):
    """Tier names from a ``MXNET_SERVE_PRIORITY_TIERS``-style spec
    (comma-separated, highest priority first). None/"" → the default
    three tiers. Duplicates and empty names are loud errors."""
    if spec is None or not str(spec).strip():
        return DEFAULT_TIERS
    names = tuple(s.strip() for s in str(spec).split(","))
    if any(not n for n in names):
        raise ValueError(
            f"empty tier name in priority-tier spec {spec!r}")
    if len(set(names)) != len(names):
        raise ValueError(
            f"duplicate tier name in priority-tier spec {spec!r}")
    return names


def parse_quota(spec=None):
    """Default per-tenant token-rate quota from a
    ``MXNET_SERVE_TENANT_QUOTA``-style spec: tokens/second as a float,
    with an optional ``:burst`` suffix. ``None``/""/"0" → unmetered
    (returns ``(None, None)``)."""
    if spec is None or not str(spec).strip():
        return None, None
    parts = str(spec).split(":")
    rate = float(parts[0])
    if rate <= 0:
        return None, None
    burst = float(parts[1]) if len(parts) > 1 else 4.0 * rate
    return rate, burst


class TokenBucket:
    """Continuous-refill token bucket (``rate`` tokens/s, ``burst``
    cap). ``rate=None`` disables metering — every debit succeeds."""

    __slots__ = ("rate", "burst", "_level", "_t")

    def __init__(self, rate=None, burst=None):
        self.rate = None if rate is None else float(rate)
        if self.rate is not None and self.rate <= 0:
            raise ValueError(f"quota rate must be > 0, got {rate}")
        self.burst = (None if self.rate is None
                      else float(burst if burst is not None
                                 else 4.0 * self.rate))
        self._level = self.burst
        self._t = None

    def _refill(self, now):
        if self._t is not None and now > self._t:
            self._level = min(self.burst,
                              self._level + (now - self._t) * self.rate)
        self._t = now

    def level(self, now):
        """Current token level (None = unmetered)."""
        if self.rate is None:
            return None
        self._refill(now)
        return self._level

    def try_debit(self, n, now):
        """Take `n` tokens if the bucket covers them; False otherwise
        (the caller keeps the request queued — quotas defer, they never
        drop)."""
        if self.rate is None:
            return True
        self._refill(now)
        if self._level >= n:
            self._level -= n
            return True
        return False

    def credit(self, n):
        """Refund unused estimate (request finished short of max_new)."""
        if self.rate is not None and n > 0:
            self._level = min(self.burst, self._level + n)


class Tenant:
    """Per-tenant accounting record: fairness weight, quota bucket, and
    lifetime token counters (the gateway labels its metric series off
    these names)."""

    __slots__ = ("name", "weight", "bucket", "tokens_out", "dispatched",
                 "preempted")

    def __init__(self, name, weight=1.0, rate=None, burst=None):
        self.name = str(name)
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError(
                f"tenant {name!r}: weight must be > 0, got {weight}")
        self.bucket = TokenBucket(rate, burst)
        self.tokens_out = 0
        self.dispatched = 0
        self.preempted = 0


class WDRRQueue:
    """Weighted deficit round robin over per-tenant FIFO queues (one
    instance per priority tier).

    ``pop_next`` pops the next dispatchable item, visiting tenants in
    rotation: every visit grants ``quantum * weight`` deficit, the head
    item pops once the deficit covers its cost. Costs are token
    estimates, so a tenant sending few huge requests and one sending
    many small ones converge to the same weighted token share."""

    def __init__(self, quantum=256):
        self.quantum = float(quantum)
        if self.quantum <= 0:
            raise ValueError(f"quantum must be > 0, got {quantum}")
        # bounded by the gateway's max_queue admission check (QueueFull
        # at submit); maxlen would silently drop — wrong semantics
        self._q = collections.OrderedDict()   # tenant -> deque # noqa: FL011
        self._deficit = {}

    def __len__(self):
        return sum(len(d) for d in self._q.values())

    def push(self, tenant, item):
        if tenant not in self._q:
            # noqa: FL011 — bounded by the gateway admission check
            self._q[tenant] = collections.deque()  # noqa: FL011
            self._deficit[tenant] = 0.0
        self._q[tenant].append(item)

    def items(self):
        """Every queued item, tenant-grouped (expiry scans, flight
        recorder)."""
        out = []
        for d in self._q.values():
            out.extend(d)
        return out

    def remove(self, item):
        """Drop one queued item (deadline expiry); False if absent."""
        for t, d in self._q.items():
            try:
                d.remove(item)
            except ValueError:
                continue
            if not d:
                self._drop_tenant(t)
            return True
        return False

    def _drop_tenant(self, tenant):
        # an emptied tenant forfeits its deficit: no banking while idle
        del self._q[tenant]
        del self._deficit[tenant]

    def pop_next(self, weights, cost_fn, can_dispatch):
        """The next item to dispatch under WDRR, or None.

        ``weights``: tenant name → weight (missing = 1.0).
        ``cost_fn(item)``: token cost estimate.
        ``can_dispatch(item)``: False defers the tenant this call (quota
        exhausted, model backlogged) without burning its deficit.

        Each call performs at most two rotation sweeps: one where every
        visited tenant earns a quantum grant, and a bounded continuation
        so a lone tenant with an outsized head request accumulates
        enough deficit to make progress instead of starving."""
        if not self._q:
            return None
        # cost of the cheapest dispatchable head bounds how many grants
        # a full sweep must accumulate before SOMETHING pops
        sweeps = 0
        while sweeps < 2:
            sweeps += 1
            progressed = False
            for tenant in list(self._q.keys()):
                d = self._q.get(tenant)
                if not d:
                    continue
                head = d[0]
                if not can_dispatch(head):
                    continue
                w = float(weights.get(tenant, 1.0))
                self._deficit[tenant] += self.quantum * w
                cost = float(cost_fn(head))
                if self._deficit[tenant] < cost:
                    progressed = True      # earned deficit: retry sweep
                    continue
                self._deficit[tenant] -= cost
                d.popleft()
                # rotate the tenant to the back so the next pop starts
                # from its successor (round robin between pops)
                self._q.move_to_end(tenant)
                if not d:
                    self._drop_tenant(tenant)
                return head
            if not progressed:
                return None                # nothing dispatchable at all
        # dispatchable heads exist but none affordable in two sweeps:
        # grant the single neediest head outright (bounded unfairness
        # beats starvation — its tenant pays by going deeply negative)
        best, best_gap = None, None
        for tenant, d in self._q.items():
            if not d or not can_dispatch(d[0]):
                continue
            gap = float(cost_fn(d[0])) - self._deficit[tenant]
            if best_gap is None or gap < best_gap:
                best, best_gap = tenant, gap
        if best is None:
            return None
        d = self._q[best]
        head = d.popleft()
        self._deficit[best] -= float(cost_fn(head))
        self._q.move_to_end(best)
        if not d:
            self._drop_tenant(best)
        return head
