"""Elastic replica-set control plane: act on the advisor, survive
replica death (SERVING.md §elastic replicas, RESILIENCE.md §8).

PR 14 gave a model N independent replica engines behind the router; the
capacity observatory (PR 16) added an `AutoscaleAdvisor` that only
*recommends*. This module closes the loop: `ReplicaSetController`
consumes those recommendations and resizes the LIVE replica set through
the existing seams, so a pod rides a diurnal load curve without static
peak provisioning and treats a dead replica as a routine membership
event.

State machine, per tick (`Gateway.step` → `tick`, under the gateway
lock)::

    reap crashed ──▶ finish drains ──▶ heal to min ──▶ act on advice
    (replica_crash    (draining ∧ idle   (spawn while    (scale_up →
     seam → replace)   → retire+free)     < min)          spawn/undrain,
                                                          scale_down →
                                                          drain one)

Invariants the controller owns:

- **one choke point** — every mutation of a model's replica list
  happens in THIS module under one ``tracked_lock`` (lint rule FL020
  flags ``.replicas`` mutations anywhere else in serve/);
- **warm before dispatch** — a spawned replica has BOTH program
  families (prefill chunks + decode) driven through it while it is
  still outside the routing set, so scale-up causes zero cold compiles
  on the request path (the compile-ledger gate in
  `bench.bench_gpt_serve_elastic` proves it);
- **funded before built** — `ModelRegistry.rebalance_pages` recomputes
  the per-replica page cut for the NEW count first and raises
  `PagePoolExhausted` LOUDLY when the budget cannot pay (never a
  silent over-commit);
- **failed-spawn rollback** — an exception anywhere between engine
  construction and publication (the ``replica_spawn`` chaos seam
  fires exactly there) releases the partial engine and leaves the
  fleet at N: no half-registered replica;
- **zero lost work** — a replica killed by the ``replica_crash`` seam
  is removed from the routing set first, its queued + running requests
  are re-owned by the gateway (tokens generated so far survive on the
  handle; the remainder re-dispatches to a surviving replica exactly
  like a preemption resume), and a replacement is spawned;
- **floors and ceilings** — scale-down drains (router stops
  dispatching, in-flight slots finish, pages + prefix refs freed at
  retire) and never drops below ``min_replicas``; scale-up and healing
  never exceed ``max_replicas``.

Knobs: ``MXNET_ELASTIC_SERVE`` (arms the controller at Gateway
construction), ``MXNET_ELASTIC_MIN_REPLICAS`` /
``MXNET_ELASTIC_MAX_REPLICAS`` (defaults 1 / 8). Telemetry:
``mx_elastic_scale_events_total{direction=}`` and the
``mx_serve_replicas{model=}`` pull gauge (TELEMETRY.md).
"""
from __future__ import annotations

import logging
import time

import numpy as onp

from ..telemetry import registry, tracing
from ..telemetry.locks import tracked_lock
from ..util import env_int as _env_int
from .scheduler import _NULL, Scheduler

__all__ = ["ReplicaSetController", "ReplicaScaleError"]

_LOG = logging.getLogger("incubator_mxnet_tpu.serve")

_WARM_STEP_GUARD = 50_000     # scheduler steps before a warmup is "stalled"


def _scale_event(direction):
    return registry.counter(
        "mx_elastic_scale_events_total",
        "committed elastic scale events by direction",
        labels={"direction": direction})


class ReplicaScaleError(RuntimeError):
    """A replica-set mutation could not complete (spawn failed, warmup
    stalled, no idle mesh slice, ...). The fleet is unchanged — the
    failed replica was rolled back before registration."""


class ReplicaSetController:
    """Closed-loop replica-set sizing for one `serve.Gateway`.

    The gateway ticks the controller from every `step()` (under the
    gateway lock); all replica-list mutations additionally serialize on
    the controller's own ``tracked_lock`` — THE choke point (FL020).

    Parameters
    ----------
    gateway : serve.Gateway
        The fleet to control.
    min_replicas / max_replicas : int, optional
        Floor/ceiling per model (``MXNET_ELASTIC_MIN_REPLICAS`` /
        ``MXNET_ELASTIC_MAX_REPLICAS``, defaults 1 / 8).
    factories : {model: callable}, optional
        ``factory(n_pages) -> engine`` per model — required for models
        registered with pre-built decoders (tests, stubs), optional
        otherwise (the registry spec is the default recipe).
    warm_lens : sequence of int, optional
        Prompt lengths driven through a fresh replica before it may
        receive traffic (cover every prefill bucket the live traffic
        touches; default ``(8,)``).
    warm_new : int
        Decode tokens per warmup request (default 2).
    """

    def __init__(self, gateway, min_replicas=None, max_replicas=None,
                 factories=None, warm_lens=None, warm_new=2):
        self._gw = gateway
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else _env_int("MXNET_ELASTIC_MIN_REPLICAS", 1))
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else _env_int("MXNET_ELASTIC_MAX_REPLICAS", 8))
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        self._factories = dict(factories or {})
        self.warm_lens = tuple(warm_lens) if warm_lens else (8,)
        self.warm_new = max(1, int(warm_new))
        # THE replica-set choke point (lint rule FL020): every mutation
        # of a model's replica list happens under this lock, in this
        # module
        self._lock = tracked_lock("serve.elastic")
        self._consumed_t = {}     # model -> newest advisor t acted on
        self._next_index = {}     # model -> next replica index (never reused)
        self._heal_logged = set()
        self.events = []          # scale-event journal (bench integrates it)
        self.warm_programs = {}   # label -> program count at publication

    # -- introspection -------------------------------------------------------

    def replica_count(self, model, live_only=False):
        m = self._gw._models[model]
        if live_only:
            return sum(1 for r in m.replicas if not r.draining)
        return len(m.replicas)

    def scale_log(self, tail=None):
        """The scale-event journal (time-ordered dicts)."""
        return list(self.events) if tail is None \
            else list(self.events)[-int(tail):]

    # -- the tick ------------------------------------------------------------

    def tick(self, now=None):
        """One control iteration (the gateway calls this from `_step`,
        already holding the gateway lock). Returns the number of
        replica-set mutations performed."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            n = self._reap_crashed(now)
            n += self._finish_drains(now)
            n += self._heal(now)
            n += self._consume_advice(now)
        return n

    # -- public scaling surface (tests / operators) --------------------------

    def scale_up(self, model, n=1):
        """Spawn up to `n` replicas for `model` (ceiling-clamped).
        Raises `PagePoolExhausted` / `ReplicaScaleError` on a replica
        the budget or the spawn path cannot deliver — the fleet stays
        at its current size. Returns the replicas added."""
        with self._gw._lock, self._lock:
            return self._scale_up(self._gw._models[model], n,
                                  time.monotonic(), reason="manual")

    def scale_down(self, model, n=1):
        """Mark up to `n` replicas of `model` draining (floor-clamped;
        they retire once idle). Returns the number marked."""
        with self._gw._lock, self._lock:
            return self._scale_down(self._gw._models[model], n,
                                    time.monotonic(), reason="manual")

    # -- crash detection + replacement ---------------------------------------

    def _reap_crashed(self, now):
        from ..fault.injection import FaultInjected, inject_at

        gw = self._gw
        n = 0
        for m in list(gw._models.values()):
            for rep in list(m.replicas):
                try:
                    # the liveness probe doubles as the chaos seam:
                    # @N targets the replica INDEX, not the process rank
                    inject_at("replica_crash", index=rep.index)
                except FaultInjected as e:
                    self._replace_dead(m, rep, now, reason=str(e))
                    n += 1
        return n

    def _replace_dead(self, m, rep, now, reason):
        """A replica died: out of the routing set first, then re-own
        its work (zero requests fail), then free its host state, then
        spawn the replacement (healed next tick if the spawn fails)."""
        gw = self._gw
        m.replicas.remove(rep)
        requeued = 0
        for req in list(rep.live):
            rep.live.remove(req)
            # the engine segment died with the replica; forward what it
            # already produced into the gateway handle, then the
            # remainder re-dispatches like a preemption resume
            if req._segment is not None:
                gw._drain_segment(req, req._segment, now)
            req._segment = None
            gen = onp.asarray(req.tokens, onp.int32)
            req._resume_prompt = onp.concatenate(
                [onp.asarray(req.prompt, onp.int32), gen])
            req._remaining = req.max_new - len(req.tokens)
            req.state = "queued"
            req.replica = None
            if req._anatomy is not None:
                req._anatomy.requeued(now, "crash_resume")
            req._spans["admit"] = tracing.open_span(
                "gateway.admit", parent=req._spans.get("request", _NULL),
                resumed=True, crash=rep.label)
            gw._queues[req.priority].push(req.tenant, req)
            requeued += 1
        self._release(rep)
        _scale_event("replace").inc()
        self._journal(now, m, "replace", rep.label,
                      f"{reason}; requeued {requeued} request(s)")
        _LOG.warning(
            "serve.elastic: replica %s died (%s) — removed from the "
            "routing set, %d live request(s) re-queued", rep.label,
            reason, requeued)
        try:
            # role-aware replacement: a dead decode replica is replaced
            # by a decode replica — the pod's role split survives crashes
            self._spawn(m, now, reason=f"replace {rep.label}",
                        role=getattr(rep, "role", "both"))
        except Exception as e:   # noqa: FL006 - degraded fleet beats a dead step loop
            _LOG.error(
                "serve.elastic: replacement spawn for %s failed (%s: %s)"
                " — fleet degraded to %d replica(s); healing retries "
                "next tick", rep.label, type(e).__name__, e,
                len(m.replicas))

    def _release(self, rep):
        """Free a retired/dead replica's host state: scheduler book-
        keeping, prefix refs, page pool."""
        from ..fault.retry import suppressed

        try:
            if rep.live or rep.sched.n_active:
                rep.sched.abandon()       # dead engine: nothing to drain
            else:
                rep.sched.close(drain=False)
        except Exception as e:
            suppressed("serve.elastic.release", e)
        for fn in (lambda: rep.slots.prefix_cache.clear(),
                   lambda: rep.slots.release()):
            try:
                fn()
            except Exception as e:
                suppressed("serve.elastic.release", e)

    # -- drains --------------------------------------------------------------

    def _finish_drains(self, now):
        n = 0
        for m in list(self._gw._models.values()):
            for rep in [r for r in m.replicas if r.draining]:
                if rep.live or not rep.sched.idle:
                    continue              # in-flight slots still finishing
                m.replicas.remove(rep)
                self._release(rep)
                _scale_event("down").inc()
                self._journal(now, m, "down", rep.label, "drain complete")
                _LOG.info("serve.elastic: replica %s drained and retired "
                          "(%d left)", rep.label, len(m.replicas))
                n += 1
        return n

    def _scale_down(self, m, n, now, reason):
        marked = 0
        for _ in range(int(n)):
            alive = [r for r in m.replicas if not r.draining]
            if len(alive) <= self.min_replicas:
                break
            if m.disagg:
                # a disaggregated pod must keep >= 1 live replica of
                # each role: no prefill replica means no admission, no
                # decode replica means every migration falls back
                from collections import Counter

                by_role = Counter(getattr(r, "role", "both")
                                  for r in alive)
                alive = [r for r in alive
                         if by_role[getattr(r, "role", "both")] > 1]
                if not alive:
                    break
            # retire the least-loaded, newest replica first
            rep = min(alive, key=lambda r: (len(r.live)
                                            + r.sched.queue_depth,
                                            -r.index))
            rep.draining = True
            tracing.event("serve.elastic.drain_start", replica=rep.label,
                          reason=str(reason))
            marked += 1
        return marked

    # -- healing + advice ----------------------------------------------------

    def _heal(self, now):
        """Spawn while a model is below ``min_replicas`` (a crash whose
        replacement spawn failed leaves a deficit; this retries every
        tick until the fleet is whole)."""
        n = 0
        for m in list(self._gw._models.values()):
            while sum(1 for r in m.replicas if not r.draining) \
                    < self.min_replicas:
                try:
                    self._spawn(m, now, reason="heal")
                    self._heal_logged.discard(m.name)
                    n += 1
                except Exception as e:   # noqa: FL006 - keep the step loop alive; retried next tick
                    if m.name not in self._heal_logged:
                        self._heal_logged.add(m.name)
                        _LOG.error(
                            "serve.elastic: heal spawn for %s failed "
                            "(%s: %s) — retrying every tick", m.name,
                            type(e).__name__, e)
                    break
        return n

    def _consume_advice(self, now):
        gw = self._gw
        n = 0
        for name, adv in list(gw._advisors.items()):
            m = gw._models.get(name)
            if m is None:
                continue
            rec = adv.pending_action(self._consumed_t.get(name))
            if rec is None:
                continue
            self._consumed_t[name] = rec["t"]
            want = max(1, int(rec.get("n", 1)))
            act = rec["action"]
            if act in ("scale_up", "scale_up_prefill", "scale_up_decode"):
                # role-aware advice (anatomy residency evidence) pins
                # the new replicas' disaggregation role
                role = {"scale_up_prefill": "prefill",
                        "scale_up_decode": "decode"}.get(act)
                n += self._scale_up(m, want, now,
                                    reason=rec.get("reason", "advisor"),
                                    best_effort=True, role=role)
            elif act == "scale_down":
                n += self._scale_down(m, want, now,
                                      reason=rec.get("reason", "advisor"))
        return n

    # -- scale-up ------------------------------------------------------------

    def _scale_up(self, m, n, now, reason, best_effort=False, role=None):
        added = []
        for _ in range(int(n)):
            # cheapest capacity first: cancel a drain in progress (of
            # the requested role, when the advice is role-aware)
            draining = [r for r in m.replicas if r.draining
                        and (role is None
                             or getattr(r, "role", "both") == role)]
            if draining:
                rep = max(draining, key=lambda r: r.index)
                rep.draining = False
                _scale_event("up").inc()
                self._journal(now, m, "up", rep.label, "drain cancelled")
                added.append(rep)
                continue
            if len(m.replicas) >= self.max_replicas:
                break
            try:
                added.append(self._spawn(m, now, reason=reason,
                                         role=role))
            except Exception as e:
                if not best_effort:
                    raise
                _LOG.warning(
                    "serve.elastic: advisor scale-up for %s stopped at "
                    "%d replica(s): %s: %s", m.name, len(m.replicas),
                    type(e).__name__, e)
                break
        return added if not best_effort else len(added)

    def _spawn(self, m, now, reason, role=None):
        """Build → load weights → warm → publish, with rollback: an
        exception ANYWHERE before publication (the ``replica_spawn``
        chaos seam included) releases the partial engine and leaves the
        fleet exactly as it was. `role` pins the new replica's
        disaggregation role (crash replacement preserves it); a
        disaggregated pod scales up on the DECODE side by default —
        resident decode slots, not prefill throughput, are what
        saturates first."""
        from ..fault.injection import inject_at
        from ..fault.retry import suppressed
        from .gateway import _Replica

        gw = self._gw
        name = m.name
        if role is None:
            role = "decode" if m.disagg else "both"
        # funded before built: the per-replica cut for the NEW count —
        # raises PagePoolExhausted loudly when the budget can't pay
        if m.disagg or role != "both":
            n_p = sum(1 for r in m.replicas
                      if getattr(r, "role", "both") == "prefill")
            n_d = sum(1 for r in m.replicas
                      if getattr(r, "role", "both") == "decode")
            if role == "prefill":
                n_p += 1
            else:
                n_d += 1
            per_p, per_d = gw._registry.rebalance_pages_disagg(
                name, max(1, n_p), max(1, n_d))
            n_pages = per_p if role == "prefill" else per_d
        else:
            n_pages = gw._registry.rebalance_pages(name,
                                                   len(m.replicas) + 1)
        j = self._next_index.get(name)
        if j is None:
            j = max((r.index for r in m.replicas), default=-1) + 1
        label = f"{name}#{j}"
        slots = sched = None
        try:
            factory = self._factories.get(name)
            if factory is not None:
                slots = factory(n_pages)
            else:
                slots = gw._registry.build_engine(
                    name, mesh=self._spawn_mesh(m, j), n_pages=n_pages)
            # the PR 14 hot-swap path: the engine read the shared
            # block's CURRENT params at construction; refresh makes the
            # load explicit (and re-places sharded weights)
            if hasattr(slots, "_refresh_params"):
                slots._refresh_params()
            if hasattr(slots, "census_name"):
                slots.census_name = f"serve:{label}"
            inject_at("replica_spawn")    # chaos: mid-spawn, pre-publication
            bp = gw._build_params
            i = list(gw._models).index(name)
            sched = Scheduler(slots, max_queue=bp["max_queue"],
                              policy=bp["policy"],
                              default_deadline=bp["default_deadline"],
                              eos_id=bp["eos_id"],
                              seed=bp["seed"] + i + 997 * j)
            sched.capacity_model = name
            rep = _Replica(name, j, label, slots, sched, role=role)
            self._warm(rep)
        except Exception:
            # failed-spawn rollback: nothing was published; the fleet
            # stays at N and the partial engine is released
            if sched is not None:
                try:
                    sched.abandon()
                except Exception as e:
                    suppressed("serve.elastic.spawn_rollback", e)
            if slots is not None:
                try:
                    slots.release()
                except Exception as e:
                    suppressed("serve.elastic.spawn_rollback", e)
            raise
        # publication: the ONE place a replica enters the routing set
        self._next_index[name] = j + 1
        self.warm_programs[label] = int(slots.xla_program_count()) \
            if hasattr(slots, "xla_program_count") else None
        m.replicas.append(rep)
        gw._arm_replica_probe(rep)
        _scale_event("up").inc()
        self._journal(now, m, "up", label, reason)
        _LOG.info("serve.elastic: replica %s spawned, warmed and "
                  "published (%d live): %s", label, len(m.replicas),
                  reason)
        return rep

    def _spawn_mesh(self, m, j):
        """The idle mesh slice for replica index `j`: registered
        mesh-list models reserve their unused tail for scale-up;
        non-mesh models return None. A spec-carved mesh model cannot be
        re-carved while its siblings hold their slices — that needs a
        factory."""
        spec_mesh = self._gw._registry._specs[m.name][4]
        if spec_mesh is None:
            return None
        if isinstance(spec_mesh, (list, tuple)):
            if j < len(spec_mesh):
                return spec_mesh[j]
            raise ReplicaScaleError(
                f"model {m.name!r}: no idle mesh slice for replica "
                f"#{j} — only {len(spec_mesh)} were registered")
        raise ReplicaScaleError(
            f"model {m.name!r} carves its replica meshes from a spec; "
            "scaling it up needs factories={...} (re-carving would "
            "move the live replicas' devices)")

    def _warm(self, rep):
        """Drive a fresh replica's program families while it is still
        outside the routing set — zero cold compiles on the request
        path. Role-aware: a decode-role replica warms via adopted
        segments (`serve.disagg.warm_decode_replica`) so its ledger
        never grows a prefill family; everything else warms BOTH
        families through ordinary submits."""
        if getattr(rep, "role", "both") == "decode":
            from . import disagg

            try:
                disagg.warm_decode_replica(rep, self.warm_lens,
                                           self.warm_new)
            except ReplicaScaleError:
                raise
            except Exception as e:
                raise ReplicaScaleError(
                    f"replica {rep.label}: decode warmup failed: "
                    f"{type(e).__name__}: {e}") from e
            return
        max_len = int(getattr(rep.slots, "max_len", 1 << 30))
        for i, L in enumerate(self.warm_lens):
            L = max(1, min(int(L), max_len - self.warm_new - 1))
            # distinct constant per warm length: a shared-prefix hit
            # across warm prompts skips whole chunks and leaves a
            # prefill bucket cold for live traffic to compile on the
            # request path
            seg = rep.sched.submit(onp.full(L, i + 1, onp.int32),
                                   self.warm_new)
            guard = 0
            while not seg.done:
                try:
                    rep.sched.step()
                except Exception as e:
                    raise ReplicaScaleError(
                        f"replica {rep.label}: warmup (len {L}) failed: "
                        f"{type(e).__name__}: {e}") from e
                guard += 1
                if guard > _WARM_STEP_GUARD:
                    raise ReplicaScaleError(
                        f"replica {rep.label}: warmup (len {L}) did not "
                        f"finish within {_WARM_STEP_GUARD} engine steps")
            if seg.error is not None:
                raise ReplicaScaleError(
                    f"replica {rep.label}: warmup (len {L}) failed: "
                    f"{type(seg.error).__name__}: {seg.error}")

    def _journal(self, now, m, direction, label, reason):
        ev = {"t": float(now), "model": m.name, "direction": direction,
              "replica": label, "n": len(m.replicas),
              "reason": str(reason)}
        self.events.append(ev)
        tracing.event("serve.elastic.scale", **{k: v for k, v in
                                                ev.items() if k != "t"})
