"""Observe-only autoscale advisor over the telemetry history layer.

The ROADMAP's "SLO-driven autoscaling" item needs a controller that
reads error-budget burn plus queue/occupancy *trends* and resizes the
replica set. This module is the **decide** half, deliberately without
the actuate half: an :class:`AutoscaleAdvisor` reads ONLY public
observatory APIs — `telemetry.timeseries` windowed queries over the
occupancy/queue-depth histories and `telemetry.burnrate` alert state —
and emits timestamped, *reasoned* recommendations:

- ``scale_up(model, n)``   — a burn-rate alert is firing, or fast-window
  occupancy is pinned above ``up_occupancy`` with a non-empty queue;
- ``scale_down(model, 1)`` — slow-window occupancy below
  ``down_occupancy``, empty queue, no alerts, and no scale-up within
  ``cooldown_s`` (the anti-flap guard: a trough right after a surge
  must prove itself for a full cooldown before shedding capacity);
- ``hold``                 — anything else, including "no history yet"
  (an observatory outage must never drive scaling).

On a disaggregated pod a plain ``scale_up`` is refined with the
anatomy ledger's role-residency evidence
(``mx_replica_residency_seconds_total{replica=,role=,state=}``,
`telemetry.anatomy.residency_report`): when the model's prefill-role
replicas are markedly busier than its decode-role replicas (or vice
versa, by more than ``role_margin`` of wall), the recommendation
becomes ``scale_up_prefill`` / ``scale_up_decode`` and the ``reason``
names the residency series — the ROADMAP's "roofline-driven role-count
autoscaling" evidence plane. `serve.elastic.ReplicaSetController`
consumes the role-aware actions by pinning the spawned replica's role.

Every recommendation names its evidence (series, window, value vs
threshold) in the ``reason`` string, lands in a bounded decision log
(what the future actuating controller will replay), is published as
``mx_advisor_recommendation{action=}`` gauges (1 = current
recommendation), and emits an ``advisor.recommend`` span event on every
action CHANGE.

Determinism: `evaluate(now=...)` takes a virtual timestamp, and the
underlying history can be built with ``timeseries.sample_now(now=...)``
— the committed diurnal-trace test (trough → steady → surge → flash
burst) asserts the exact recommendation sequence with zero flaps on
the steady segment, wall-clock-free.

The gateway arms one advisor per model under ``MXNET_ADVISOR`` (``1`` =
evaluate every 5 s on the driver thread; a float = that period in
seconds); `Gateway.advisor_log()` tails the merged decision log.
"""
from __future__ import annotations

import collections
import time

from ..telemetry import burnrate, registry, timeseries, tracing

__all__ = ["AutoscaleAdvisor", "ACTIONS"]

ACTIONS = ("scale_up", "scale_up_prefill", "scale_up_decode",
           "scale_down", "hold")

OCCUPANCY_SERIES = "mx_serve_slot_occupancy"
QUEUE_PREFIX = "mx_gateway_queue_depth"
RESIDENCY_SERIES = "mx_replica_residency_seconds_total"


class AutoscaleAdvisor:
    """Observe-only replica-count advisor for one gateway model."""

    def __init__(self, model, up_occupancy=0.85, down_occupancy=0.25,
                 fast_window_s=60.0, slow_window_s=300.0,
                 cooldown_s=120.0, burst_queue=16,
                 occupancy_series=OCCUPANCY_SERIES,
                 queue_prefix=QUEUE_PREFIX, log_len=256,
                 role_margin=0.1):
        self.model = str(model)
        self.up_occupancy = float(up_occupancy)
        self.down_occupancy = float(down_occupancy)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.cooldown_s = float(cooldown_s)
        self.burst_queue = int(burst_queue)
        self.occupancy_series = occupancy_series
        self.queue_prefix = queue_prefix
        self.role_margin = float(role_margin)
        self._log = collections.deque(maxlen=int(log_len))
        self._last_action = None
        self._last_scale_up_t = None

    # -- signal reads (public timeseries/burnrate APIs only) ---------------

    def _queue_avg(self, window_s, now):
        names = timeseries.series_names(prefix=self.queue_prefix)
        if not names:
            return None
        vals = [timeseries.avg_over_time(n, window_s, now=now)
                for n in names]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    def _role_refine(self, now):
        """Residency evidence for a role-aware scale-up: mean busy
        fraction (1 - idle share) of this model's prefill-role vs
        decode-role replicas from the anatomy ledger. Returns
        ``(action, busy_hot, busy_cold)`` when one role is busier by
        more than ``role_margin`` of wall, else None (homogeneous pods
        have no dedicated roles, so they always return None)."""
        from ..telemetry import anatomy

        busy = {"prefill": [], "decode": []}
        for label, row in anatomy.residency_report(now=now).items():
            if label.split("#", 1)[0] != self.model:
                continue
            role = row.get("role")
            if role in busy:
                busy[role].append(1.0 - row["frac"].get("idle", 0.0))
        if not busy["prefill"] or not busy["decode"]:
            return None
        bp = sum(busy["prefill"]) / len(busy["prefill"])
        bd = sum(busy["decode"]) / len(busy["decode"])
        if bp >= bd + self.role_margin:
            return "scale_up_prefill", bp, bd
        if bd >= bp + self.role_margin:
            return "scale_up_decode", bd, bp
        return None

    def _publish(self, action):
        for a in ACTIONS:
            registry.gauge(
                "mx_advisor_recommendation",
                "1 for the advisor's current recommendation per action",
                labels={"action": a}).set(1 if a == action else 0)

    # -- the decision ------------------------------------------------------

    def evaluate(self, now=None):
        """One recommendation: ``{"t", "action", "model", "n",
        "reason", "evidence"}`` (also appended to the decision log)."""
        if now is None:
            now = time.monotonic()
        fast_w, slow_w = self.fast_window_s, self.slow_window_s
        occ_fast = timeseries.avg_over_time(self.occupancy_series,
                                            fast_w, now=now)
        occ_slow = timeseries.avg_over_time(self.occupancy_series,
                                            slow_w, now=now)
        queue_fast = self._queue_avg(fast_w, now)
        alerts = burnrate.firing()
        evidence = {
            f"{self.occupancy_series} avg {fast_w:g}s": occ_fast,
            f"{self.occupancy_series} avg {slow_w:g}s": occ_slow,
            f"{self.queue_prefix}{{*}} sum-avg {fast_w:g}s": queue_fast,
            "alerts_firing": alerts,
        }
        action, n, reason = "hold", 0, "signals nominal"
        if occ_fast is None and not alerts:
            reason = (f"no history yet for {self.occupancy_series} — "
                      "an observatory outage never drives scaling")
        elif alerts:
            action, n = "scale_up", 1
            reason = (f"burn-rate alert(s) {', '.join(alerts)} firing "
                      f"(multi-window burn over mx_slo_error_budget_burn)")
        elif occ_fast >= self.up_occupancy \
                and (queue_fast or 0) > 0:
            action = "scale_up"
            n = 2 if (queue_fast or 0) >= self.burst_queue else 1
            reason = (f"{self.occupancy_series} avg over {fast_w:g}s = "
                      f"{occ_fast:.2f} >= {self.up_occupancy:g} with "
                      f"{self.queue_prefix} sum-avg {queue_fast:.1f} > 0 "
                      f"over {fast_w:g}s")
        elif occ_slow is not None and occ_slow <= self.down_occupancy \
                and not (queue_fast or 0) > 0:
            if self._last_scale_up_t is not None \
                    and now - self._last_scale_up_t < self.cooldown_s:
                reason = (f"{self.occupancy_series} avg over {slow_w:g}s "
                          f"= {occ_slow:.2f} <= {self.down_occupancy:g} "
                          f"but within {self.cooldown_s:g}s scale-up "
                          "cooldown — holding (anti-flap)")
            else:
                action, n = "scale_down", 1
                reason = (f"{self.occupancy_series} avg over {slow_w:g}s "
                          f"= {occ_slow:.2f} <= {self.down_occupancy:g} "
                          f"with empty queue over {fast_w:g}s and no "
                          "burn alerts")
        if action == "scale_up":
            refined = self._role_refine(now)
            if refined is not None:
                action, hot, cold = refined
                role = ("prefill" if action == "scale_up_prefill"
                        else "decode")
                evidence[f"{RESIDENCY_SERIES} busy[{role}]"] = hot
                evidence[f"{RESIDENCY_SERIES} busy[other]"] = cold
                reason += (
                    f"; {RESIDENCY_SERIES} shows {role}-role replicas "
                    f"{hot:.0%} busy vs {cold:.0%} for the other role — "
                    f"scale the {role} side")
        if action.startswith("scale_up"):
            self._last_scale_up_t = now
        rec = {"t": now, "action": action, "model": self.model, "n": n,
               "reason": reason, "evidence": evidence}
        self._log.append(rec)
        self._publish(action)
        if action != self._last_action:
            tracing.event("advisor.recommend", model=self.model,
                          action=action, n=n, reason=reason)
            self._last_action = action
        return rec

    # -- reading -----------------------------------------------------------

    def decision_log(self, tail=None):
        """The bounded recommendation history, oldest→newest."""
        log = list(self._log)
        return log if tail is None else log[-int(tail):]

    def recommendations(self, tail=None):
        """Action sequence (deduplicated runs collapse to one entry) —
        what the diurnal acceptance gate asserts."""
        seq = []
        for rec in self.decision_log(tail=tail):
            if not seq or seq[-1] != rec["action"]:
                seq.append(rec["action"])
        return seq

    def pending_action(self, since_t=None):
        """The newest non-hold recommendation STRICTLY newer than
        ``since_t`` — the `serve.elastic.ReplicaSetController` consume
        surface (the controller remembers the timestamp it acted on, so
        one recommendation is never acted on twice). Returns the
        decision dict or None."""
        for rec in reversed(self._log):
            if since_t is not None and rec["t"] <= since_t:
                return None
            if rec["action"] != "hold":
                return rec
        return None
