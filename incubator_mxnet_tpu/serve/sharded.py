"""Pod-scale sharded serving: mesh-placed `SlotDecoder` replicas.

The single-chip serving engine (`serve.engine.SlotDecoder`) compiles two
program families (chunked prefill + decode) over per-layer paged KV
pools. This module scales one replica *within* a host by tensor
parallelism: a :class:`ServeLayout` of partition rules places every
param and pool leaf onto a device mesh, and :class:`ShardedSlotDecoder`
threads those placements through the inherited program families via the
three seams the base engine exposes (`_refresh_params`,
`_constrain_pools`, `_shardcheck_specs`) — the programs themselves are
untouched, so every single-chip invariant survives sharding:

- exactly two compiled program families per replica (prefill growth by
  chunk bucket only), gated by the compile ledger;
- all ``2L`` per-layer pool leaves donated AND aliased — the output
  pools are pinned to their input shardings with
  ``with_sharding_constraint`` so XLA's donation map still holds;
- prefix cache + int8 KV are orthogonal (host-side token matching and
  in-program quantization never see the mesh);
- on a 1-device mesh the placements are no-ops and greedy output is
  bit-identical to the unsharded engine.

Layout (the `ServeLayout` defaults, after SNIPPETS.md [2] fmengine
``match_partition_rules`` and [3] fsdp×tp ``SpecLayout``):

- attention K/V pools ``(n_pages, H, page_tokens, d)`` →
  ``P(None, tp, None, None)``: heads-sharded, so each device holds its
  heads' pages for the WHOLE pool — per-device KV HBM drops by the TP
  degree (int8 scale planes ``(n_pages, H)`` shard the same way);
- matmuls Megatron-style with one deliberate twist: ffn1 is
  column-parallel / ffn2 row-parallel (the classic pair, one
  all-reduce), but the FUSED qkv matmul runs row-parallel rather than
  column-parallel — its output axis is ``[q|k|v]``-contiguous and the
  gluon ``(3, H, d)`` split can never align with a contiguous tp
  sharding of ``3C``, so sharding it would buy an all-gather on the
  decode hot path (shardcheck SC005 catches exactly this). Row-parallel
  qkv keeps q/k/v replicated (tiny at decode shapes) while the heavy
  state — weights and KV pools — stays fully sharded; proj is
  row-parallel over the head-sharded attention context. The ``fsdp``
  axis rides the complementary dim for pod layouts;
- embeddings / positional tables / norms / page tables replicated —
  explicitly (``P()``), so shardcheck's SC001 "silently replicated
  ≥1 MiB leaf" rule stays meaningful for everything else.

Every leaf MUST match a rule: an unmatched leaf raises instead of
falling back to replication (lint FL017 enforces the same discipline
statically — serve/ code may not hand bare ``PartitionSpec`` /
``NamedSharding`` literals to placement calls; specs flow from layout
rules).

Scaling *across* hosts is replication: `serve.router.ReplicaRouter`
plus the gateway's ``replicas=N`` front N independent engines (each its
own mesh slice, prefix cache, and page pool) behind least-loaded +
prefix-affinity dispatch. See SERVING.md §"Pod-scale sharded serving".
"""
from __future__ import annotations

import os
import re

from ..parallel.mesh import make_mesh
from .engine import SlotDecoder

__all__ = ["ServeLayout", "ShardedSlotDecoder", "parse_mesh_spec",
           "serve_mesh"]


def _j():
    import jax

    return jax


def parse_mesh_spec(spec):
    """Parse a mesh spec into ``{"axis": size}``.

    Accepts a dict (returned as-is), an int / numeric string ``"4"``
    (tensor-parallel degree), or ``"tp=4"`` / ``"fsdp=2,tp=4"`` — the
    grammar of the ``MXNET_SERVE_MESH`` env knob."""
    if isinstance(spec, dict):
        return dict(spec)
    if isinstance(spec, int):
        return {"tp": int(spec)}
    s = str(spec).strip()
    if not s:
        return {"tp": 1}
    if s.isdigit():
        return {"tp": int(s)}
    axes = {}
    for part in s.split(","):
        if "=" not in part:
            raise ValueError(
                f"bad mesh spec {spec!r}: expected 'axis=size[,..]' "
                f"(e.g. 'tp=4' or 'fsdp=2,tp=4')")
        k, v = part.split("=", 1)
        axes[k.strip()] = int(v)
    return axes


def serve_mesh(spec=None, devices=None):
    """Build a serving mesh from `spec` (default: the
    ``MXNET_SERVE_MESH`` env knob, else ``tp=1``). Unlike
    `parallel.make_mesh` alone, this takes the FIRST ``prod(sizes)``
    devices instead of requiring the spec to cover every device — a
    replica's mesh is a slice of the host, not the host."""
    if spec is None:
        spec = os.environ.get("MXNET_SERVE_MESH", "") or {"tp": 1}
    axes = parse_mesh_spec(spec)
    need = 1
    for v in axes.values():
        need *= int(v)
    if devices is None:
        devices = _j().devices()
    if len(devices) < need:
        raise ValueError(
            f"serve_mesh: spec {axes} needs {need} devices, have "
            f"{len(devices)}")
    return make_mesh(axes, devices=list(devices)[:need])


def _path_str(path):
    """'layers/qkv_w'-style rule key for one pytree leaf path."""
    parts = []
    for p in path:
        for attr in ("key", "name", "idx"):
            v = getattr(p, attr, None)
            if v is not None:
                parts.append(str(v))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


class ServeLayout:
    """Partition rules mapping every serving param/pool leaf to a
    `PartitionSpec` on `mesh`.

    ``rules`` is an ordered ``(regex, spec)`` sequence matched (first
    hit wins, `re.search`) against the '/'-joined pytree path of each
    param leaf — the fmengine ``match_partition_rules`` idiom. A leaf no
    rule matches raises `ValueError`: silent replication of an unplaced
    leaf is exactly the failure mode shardcheck SC001 exists to catch,
    so the layout refuses to manufacture it."""

    def __init__(self, mesh, rules=None, tp_axis="tp", fsdp_axis="fsdp"):
        self.mesh = mesh
        self.tp_axis = tp_axis
        axes = set(dict(mesh.shape))
        if tp_axis not in axes:
            raise ValueError(
                f"ServeLayout: mesh axes {sorted(axes)} lack "
                f"{tp_axis!r} (build the mesh with serve_mesh)")
        # pure-tp serving meshes (the replica_meshes default) simply
        # leave the fsdp dim unsharded
        self.fsdp_axis = fsdp_axis if fsdp_axis in axes else None
        self.rules = tuple(rules) if rules is not None \
            else self._default_rules()
        self._compiled = tuple((re.compile(rx), spec)
                               for rx, spec in self.rules)

    # -- rule table ---------------------------------------------------------

    def _default_rules(self):
        P = _j().sharding.PartitionSpec
        tp, fs = self.tp_axis, self.fsdp_axis
        # Weights are stored (L, out, in) and applied as ``y = x @ w.T``
        # (`models.decoding._dense`), so "row-parallel" = tp on the LAST
        # dim (input features) and "column-parallel" = tp on the middle
        # dim (output features).
        return (
            # attention: the fused qkv output axis is [q|k|v]-contiguous
            # and `_split_qkv` reshapes it to (3, H, d) — a contiguous
            # tp-sharding of 3C can never align with heads, so qkv runs
            # ROW-parallel (contract over tp-sharded input features,
            # one all-reduce, replicated q/k/v — tiny at decode shapes)
            # and its bias stays replicated with the output. proj is
            # row-parallel too: its input is the attention context,
            # which lands head-sharded (= feature-sharded once
            # flattened) straight out of the H-sharded KV pools.
            (r"layers/qkv_w$", P(None, fs, tp)),
            (r"layers/qkv_b$", P(None)),
            (r"layers/proj_w$", P(None, fs, tp)),
            (r"layers/proj_b$", P(None)),
            # MLP: the classic Megatron pair — ffn1 column-parallel
            # (output features on tp, bias sharded along), gelu local,
            # ffn2 row-parallel (all-reduce back to replicated)
            (r"layers/ffn1_w$", P(None, tp, fs)),
            (r"layers/ffn1_b$", P(None, tp)),
            (r"layers/ffn2_w$", P(None, fs, tp)),
            (r"layers/ffn2_b$", P(None)),
            # small per-layer norm vectors: replicated, explicitly
            (r"layers/ln[0-9]+_[gb]$", P(None)),
            # embeddings / positional / final norm / untied head:
            # replicated (page tables ride along as plain host arrays)
            (r"^embed$", P()),
            (r"^pos$", P()),
            (r"^lnf_[gb]$", P()),
            (r"^head_w$", P()),
        )

    def pool_spec(self):
        """K/V pool leaves ``(n_pages, H, page_tokens, d)``: heads on
        the TP axis."""
        P = _j().sharding.PartitionSpec
        return P(None, self.tp_axis, None, None)

    def scale_spec(self):
        """int8 per-page scale planes ``(n_pages, H)``: same H axis."""
        P = _j().sharding.PartitionSpec
        return P(None, self.tp_axis)

    # -- matching -----------------------------------------------------------

    def spec_for(self, path):
        for rx, spec in self._compiled:
            if rx.search(path):
                return spec
        raise ValueError(
            f"ServeLayout: no partition rule matches param leaf "
            f"{path!r} — add an explicit rule (silent replicated "
            f"fallback is not allowed; see SERVING.md pod-scale notes)")

    def param_specs(self, params):
        """Spec pytree mirroring `params`; raises on any unmatched
        leaf."""
        jax = _j()
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        return jax.tree_util.tree_unflatten(
            treedef, [self.spec_for(_path_str(p)) for p, _ in flat])

    def sharding(self, spec):
        """`NamedSharding` for `spec` with trailing None dims stripped.

        The strip is load-bearing, not cosmetic: GSPMD normalizes specs
        the same way on program OUTPUTS, and the jit cache compares
        NamedShardings by spec. Placing the pools with the unnormalized
        ``P(None, tp, None, None)`` would make the first program — the
        only one ever traced against freshly `device_put` pools — carry
        a different input sharding than every later call on
        program-output pools (``P(None, tp)``), costing one spurious
        recompile per engine. The steady-state gates in
        tests/test_sharded_serve.py and bench_gpt_serve_sharded hold
        only because placement and program outputs agree exactly."""
        jax = _j()
        entries = tuple(spec)
        while entries and entries[-1] is None:
            entries = entries[:-1]
        return jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(*entries))

    # -- placement ----------------------------------------------------------

    def place_params(self, params):
        """device_put every param leaf per its matched rule (committed
        shardings — the compiled programs then see stable layouts)."""
        jax = _j()
        specs = self.param_specs(params)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, self.sharding(s)),
            params, specs)

    def place_pools(self, pk, pv, sk, sv):
        """device_put the per-layer pool (and int8 scale) leaves."""
        jax = _j()
        ps = self.sharding(self.pool_spec())
        ss = self.sharding(self.scale_spec())
        pk = tuple(jax.device_put(x, ps) for x in pk)
        pv = tuple(jax.device_put(x, ps) for x in pv)
        if sk is not None:
            sk = tuple(jax.device_put(x, ss) for x in sk)
            sv = tuple(jax.device_put(x, ss) for x in sv)
        return pk, pv, sk, sv

    def constrain_pools(self, pk, pv, sk, sv):
        """Inside a traced program: pin updated pool leaves back to the
        input placement so donation aliasing survives compilation."""
        jax = _j()
        wsc = jax.lax.with_sharding_constraint
        ps = self.sharding(self.pool_spec())
        ss = self.sharding(self.scale_spec())
        pk = tuple(wsc(x, ps) for x in pk)
        pv = tuple(wsc(x, ps) for x in pv)
        if sk is not None:
            sk = tuple(wsc(x, ss) for x in sk)
            sv = tuple(wsc(x, ss) for x in sv)
        return pk, pv, sk, sv

    def describe(self):
        """Human-readable rule table (docs/tests)."""
        return [(rx, str(spec)) for rx, spec in self.rules]


class ShardedSlotDecoder(SlotDecoder):
    """A `SlotDecoder` whose params and KV pools live on a device mesh.

    Same constructor as the base engine plus ``mesh=`` (a
    `jax.sharding.Mesh`, a mesh spec for :func:`serve_mesh`, or None to
    read ``MXNET_SERVE_MESH``) and ``layout=`` (a prebuilt
    :class:`ServeLayout`; overrides ``mesh``). All four inherited
    program families compile against the mesh; the engine's host API
    (scheduler, gateway, prefix cache) is unchanged."""

    def __init__(self, source, mesh=None, layout=None, hbm_budget_gb=None,
                 **engine_kwargs):
        if layout is None:
            if not hasattr(mesh, "shape") or not hasattr(mesh, "devices"):
                mesh = serve_mesh(mesh)
            layout = ServeLayout(mesh)
        self.layout = layout
        self.hbm_budget_gb = hbm_budget_gb
        self._placed_ids = None
        super().__init__(source, **engine_kwargs)
        self._check_divisibility()
        self._place_params()

    # -- mesh plumbing ------------------------------------------------------

    def _check_divisibility(self):
        mesh_shape = dict(self.layout.mesh.shape)
        tp = int(mesh_shape.get(self.layout.tp_axis, 1))
        H = self._dec._n_heads
        if H % tp:
            raise ValueError(
                f"ShardedSlotDecoder: n_heads={H} not divisible by "
                f"tp={tp} — the K/V pools shard on the head axis")
        layers = self._dec._params["layers"]
        # row-parallel matmuls shard input features (last dim of the
        # (L, out, in) weight); column-parallel ffn1 shards its output
        for name, dim in (("qkv_w", -1), ("proj_w", -1),
                          ("ffn1_w", 1), ("ffn2_w", -1)):
            size = int(layers[name].shape[dim])
            if size % tp:
                raise ValueError(
                    f"ShardedSlotDecoder: {name} sharded dim {size} "
                    f"not divisible by tp={tp}")

    def _place_params(self):
        """(Re-)place decoder params onto the mesh iff the source
        block's weights changed since the last placement — the
        hot-swap path: `GPTDecoder._auto_refresh` re-reads host-side
        refs, then this pins them to the layout. Replacing
        ``dec._params`` does not touch the model's own buffers, so the
        id fingerprint stays stable until the next real swap."""
        dec = self._dec
        dec._auto_refresh()
        if dec._param_ids == self._placed_ids:
            return False
        dec._params = self.layout.place_params(dec._params)
        self._placed_ids = dec._param_ids
        return True

    # -- seams the base engine routes through -------------------------------

    def _refresh_params(self):
        self._place_params()

    def _make_pools(self, dec):
        pk, pv, sk, sv = super()._make_pools(dec)
        return self.layout.place_pools(pk, pv, sk, sv)

    def _constrain_pools(self, pk, pv, sk, sv):
        return self.layout.constrain_pools(pk, pv, sk, sv)

    def _place_migrated(self, leaves, name):
        """A disagg page-migration scatter runs eagerly, so its outputs
        carry whatever sharding the eager op picked — re-pin them to the
        pool layout, or the next donated program would see mismatched
        input placements (the same trap `ServeLayout.sharding` closes
        for fresh pools)."""
        import jax

        spec = self.layout.scale_spec() if name in ("sk", "sv") \
            else self.layout.pool_spec()
        s = self.layout.sharding(spec)
        return tuple(jax.device_put(x, s) for x in leaves)

    def _shardcheck_specs(self):
        """Explicit spec entries for ``(params, *pools)`` so the
        shardcheck pre-flight judges the REAL layout (SC001 silent
        replication, SC006 per-device HBM) instead of assuming
        single-chip."""
        param_specs = self.layout.param_specs(self._dec._params)
        ps, ss = self.pool_specs()
        L = len(self._pk)
        entries = (param_specs, (ps,) * L, (ps,) * L)
        if self._int8:
            entries += ((ss,) * L, (ss,) * L)
        return entries

    def _shardcheck_out_specs(self):
        """Output-side spec entries matching the builders' return
        structure ``(pk, pv[, sk, sv], tok)`` — without them the
        donation audit (SC004) would compare the pinned input pools
        against unconstrained outputs and cry wolf."""
        ps, ss = self.pool_specs()
        L = len(self._pk)
        if self._int8:
            return ((ps,) * L, (ps,) * L, (ss,) * L, (ss,) * L, None)
        return ((ps,) * L, (ps,) * L, None)

    def pool_specs(self):
        return self.layout.pool_spec(), self.layout.scale_spec()

    def shardcheck_report(self, mesh=None, hbm_budget_gb=None, bucket=None):
        if mesh is None:
            mesh = self.layout.mesh
        if hbm_budget_gb is None:
            hbm_budget_gb = self.hbm_budget_gb
        return super().shardcheck_report(
            mesh=mesh, hbm_budget_gb=hbm_budget_gb, bucket=bucket)
