"""Gluon high-level API (reference: `python/mxnet/gluon/`)."""
from . import utils  # noqa: F401
from .block import Block, HybridBlock, SymbolBlock  # noqa: F401
from .parameter import Constant, Parameter  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import loss  # noqa: F401
from . import metric  # noqa: F401
from . import data  # noqa: F401
from . import model_zoo  # noqa: F401
from . import probability  # noqa: F401
from . import contrib  # noqa: F401
