"""Mixture-of-Experts gluon block (user-facing MoE).

Reference role: the reference has no MoE (GluonNLP-era MXNet predates
it); the TPU build adds it as a gluon layer because the sharding
machinery makes expert parallelism natural (`parallel/moe.py` — GShard
dispatch over an `ep` mesh axis). This block is the single-device /
data-parallel form: experts live in one stacked parameter, tokens
dispatch with capacity-factor top-1/top-2 gating, and the load-balance
auxiliary loss is RETURNED so callers add it to the objective (Switch
Transformer training recipe).
"""
from __future__ import annotations

from ...gluon.block import HybridBlock
from ...gluon.parameter import Parameter

__all__ = ["MoEFFN"]


class MoEFFN(HybridBlock):
    """Token-routed expert FFN layer.

    forward(x) with x (N, T, D) or (T, D) returns `(out, aux_loss)` —
    out has x's shape, aux_loss is the scalar Switch load-balance term
    (multiply by your chosen coefficient, typically 1e-2, and ADD to the
    task loss; gradients through it train the gate toward balanced
    routing).

    Parameters
    ----------
    units : int            token dim D
    hidden_size : int      per-expert FFN hidden dim H
    num_experts : int      number of experts E
    top_k : int            1 (Switch) or 2 (GShard) routing
    capacity_factor : float  slots per expert = cf * top_k * T / E
    """

    def __init__(self, units, hidden_size, num_experts, top_k=2,
                 capacity_factor=1.25):
        super().__init__()
        if top_k not in (1, 2):
            raise ValueError("top_k must be 1 or 2")
        self._units = units
        self._hidden = hidden_size
        self._experts = num_experts
        self._top_k = top_k
        self._cf = capacity_factor
        self.gate_weight = Parameter(shape=(num_experts, units),
                                     init="xavier")
        self.w1 = Parameter(shape=(num_experts, units, hidden_size),
                            init="xavier")
        self.b1 = Parameter(shape=(num_experts, hidden_size), init="zeros")
        self.w2 = Parameter(shape=(num_experts, hidden_size, units),
                            init="xavier")
        self.b2 = Parameter(shape=(num_experts, units), init="zeros")

    def forward(self, x):
        from ...ndarray.ndarray import apply_op
        from ...parallel.moe import moe_dispatch_combine, moe_ffn_apply

        top_k, cf = self._top_k, self._cf

        def f(xv, gw, w1, b1, w2, b2):
            shape = xv.shape
            tokens = xv.reshape(-1, shape[-1])             # (T, D)
            logits = tokens @ gw.T                          # (T, E)
            out, aux = moe_dispatch_combine(
                tokens, logits, moe_ffn_apply(w1, b1, w2, b2),
                capacity_factor=cf, top_k=top_k)
            return out.reshape(shape), aux

        return apply_op("moe_ffn", f,
                        (x, self.gate_weight.data(), self.w1.data(),
                         self.b1.data(), self.w2.data(), self.b2.data()),
                        n_outputs=2)

    def __repr__(self):
        return (f"MoEFFN({self._units} -> {self._hidden}, "
                f"E={self._experts}, top{self._top_k})")
