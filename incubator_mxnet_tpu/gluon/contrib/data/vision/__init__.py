"""Composable vision data loading (reference:
`python/mxnet/gluon/contrib/data/vision/dataloader.py`)."""
from .dataloader import (  # noqa: F401
    ImageDataLoader,
    create_image_augment,
)
