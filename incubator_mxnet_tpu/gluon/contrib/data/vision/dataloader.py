"""Preset image-augmentation pipelines + DataLoader facade (reference:
`python/mxnet/gluon/contrib/data/vision/dataloader.py:34`
create_image_augment and `:140` ImageDataLoader).

TPU-native: augmentation composes the gluon transforms (host-side numpy/
PIL-free ops); the loader is the ordinary multiprocess DataLoader over an
ImageRecordDataset/ImageFolderDataset, so the whole pipeline feeds async
device puts exactly like gluon.data.DataLoader."""
from __future__ import annotations

from .... import data as gdata
from ....data.vision import transforms

__all__ = ["create_image_augment", "ImageDataLoader"]


def create_image_augment(data_shape, resize=0, rand_crop=False,
                         rand_resize=False, rand_mirror=False, mean=None,
                         std=None, brightness=0, contrast=0, saturation=0,
                         hue=0, pca_noise=0, rand_gray=0,  # noqa: ARG001
                         inter_method=1, dtype="float32"):  # noqa: ARG001
    """Compose a standard augmentation stack (`dataloader.py:34`).

    Returns a `transforms.Compose`-style HybridSequential. `pca_noise`,
    `rand_gray` and custom interpolation methods are not supported on the
    host pipeline and must be 0/default (a ValueError points this out)."""
    if pca_noise or rand_gray or hue:
        raise ValueError("create_image_augment: pca_noise/rand_gray/hue "
                         "are not supported in the TPU host pipeline")
    aug = transforms.Compose()
    size = (data_shape[2], data_shape[1])  # (W, H)
    if rand_resize:
        if resize > 0:  # reference: pre-resize before the random crop
            aug.add(transforms.Resize(resize, keep_ratio=True))
        aug.add(transforms.RandomResizedCrop(size))
    elif rand_crop:
        aug.add(transforms.Resize(resize, keep_ratio=True) if resize > 0
                else transforms.Resize((size[0] * 9 // 8,
                                        size[1] * 9 // 8)))
        aug.add(transforms.RandomCrop(size))
    elif resize > 0:
        # reference semantics: shorter-edge resize then center crop
        aug.add(transforms.Resize(resize, keep_ratio=True))
        aug.add(transforms.CenterCrop(size))
    else:
        aug.add(transforms.Resize(size))
    if rand_mirror:
        aug.add(transforms.RandomFlipLeftRight())
    if brightness:
        aug.add(transforms.RandomBrightness(brightness))
    if contrast:
        aug.add(transforms.RandomContrast(contrast))
    if saturation:
        aug.add(transforms.RandomSaturation(saturation))
    aug.add(transforms.ToTensor())
    if mean is not None or std is not None:
        aug.add(transforms.Normalize(mean if mean is not None else 0.0,
                                     std if std is not None else 1.0))
    return aug


class ImageDataLoader:
    """Ready-made augmenting loader over an image RecordIO file or image
    folder (`dataloader.py:140`). Iterates (data, label) batches."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imgidx=None, root=None, shuffle=False,
                 num_workers=0, aug_list=None, last_batch="discard",
                 **augment_kwargs):
        if path_imgrec is not None:
            # path_imgidx accepted for API parity; the record index is
            # rebuilt/derived automatically by RecordFileDataset
            del path_imgidx
            dataset = gdata.vision.ImageRecordDataset(path_imgrec)
        elif root is not None:
            dataset = gdata.vision.ImageFolderDataset(root)
        else:
            raise ValueError("ImageDataLoader: pass path_imgrec or root")
        if aug_list is None:
            aug_list = create_image_augment(data_shape, **augment_kwargs)
        self._dataset = dataset.transform_first(aug_list)
        self._loader = gdata.DataLoader(
            self._dataset, batch_size=batch_size, shuffle=shuffle,
            num_workers=num_workers, last_batch=last_batch)

    def __iter__(self):
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)
