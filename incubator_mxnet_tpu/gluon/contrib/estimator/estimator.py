"""Gluon Estimator — batteries-included fit/evaluate loop
(reference: `python/mxnet/gluon/contrib/estimator/estimator.py:42-517`).

TPU-native: one logical device (XLA shards under the hood via
DataParallel/pjit when the user passes a sharded train step); the train
loop is the framework's standard autograd.record → backward →
Trainer.step path, so everything the funnel provides (profiler hooks, AMP,
sparse grads) applies here too.
"""
from __future__ import annotations

import logging

from ... import loss as gluon_loss
from ... import metric as metric_mod
from ...trainer import Trainer
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StepGuard,
                            StoppingHandler, TrainBegin, TrainEnd,
                            ValidationHandler, _check_event_handlers)

__all__ = ["Estimator"]


class Estimator:
    """Train and evaluate a gluon net with event handlers
    (reference: estimator.py:42)."""

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 initializer=None, trainer=None, context=None, device=None,
                 evaluation_loss=None, val_loss=None, val_net=None,
                 batch_processor=None):
        if batch_processor is not None:
            # the reference splits the train/eval step into a swappable
            # BatchProcessor; this build doesn't implement that seam yet.
            # Fail loudly rather than silently ignoring the argument
            # (VERDICT r5 Missing #5): reference scripts relying on a
            # custom processor would otherwise train with the default
            # step and look like they worked.
            raise ValueError(
                "batch_processor is not supported by this build: override "
                "Estimator.fit_batch/evaluate_batch or use event handlers "
                "(gluon.contrib.estimator.event_handler) to customize the "
                "train/eval step")
        self.net = net
        self.loss = self._check_loss(loss)
        self._train_metrics = _as_list(train_metrics)
        self._val_metrics = _as_list(val_metrics)
        self.evaluation_loss = self._check_loss(
            evaluation_loss or val_loss or loss)
        self.val_net = val_net or net
        self.logger = logging.getLogger("incubator_mxnet_tpu.estimator")
        if not self.logger.handlers:
            self.logger.addHandler(logging.StreamHandler())
            self.logger.setLevel(logging.INFO)
        self.device = device or context
        self._initialize(initializer)
        self.trainer = self._check_trainer(trainer)
        self.stop_training = False
        self.max_epoch = None
        self.max_batch = None
        self._add_default_training_metrics()
        self._add_validation_metrics()

    # -- setup ---------------------------------------------------------------
    def _check_loss(self, loss):
        if loss is None:
            return None
        if not isinstance(loss, gluon_loss.Loss):
            raise ValueError("loss must be a gluon.loss.Loss instance")
        return loss

    def _initialize(self, initializer):
        params = self.net.collect_params()
        uninitialized = any(p._data is None and p._deferred_init is None
                            for p in params.values())
        if uninitialized:
            self.net.initialize(init=initializer, device=self.device)
        elif initializer is not None:
            self.logger.warning(
                "Network already initialized; ignoring initializer")

    def _check_trainer(self, trainer):
        if trainer is None:
            self.logger.warning(
                "No trainer specified; using sgd with learning_rate=0.001")
            trainer = Trainer(self.net.collect_params(), "sgd",
                              {"learning_rate": 1e-3})
        elif not isinstance(trainer, Trainer):
            raise ValueError("trainer must be a gluon.Trainer instance")
        return trainer

    def _add_default_training_metrics(self):
        import copy

        if not self._train_metrics:
            self._train_metrics = [metric_mod.Accuracy()]
        # deep-copy so caller-owned metric objects are not renamed in place
        # (and reuse across Estimators doesn't double-prefix)
        self._train_metrics = [copy.deepcopy(m) for m in self._train_metrics]
        for m in self._train_metrics:
            m.name = "training " + m.name
        self._train_metrics.append(
            metric_mod.Loss("training " + type(self.loss).__name__.lower()))

    def _add_validation_metrics(self):
        import copy

        if not self._val_metrics:
            # deep-copy (not type(m)()) so metric config — top_k, feval,
            # thresholds — carries over to validation
            self._val_metrics = [copy.deepcopy(m)
                                 for m in self._train_metrics[:-1]]
            for m in self._val_metrics:
                m.name = m.name.removeprefix("training ")
                m.reset()
        else:
            self._val_metrics = [copy.deepcopy(m) for m in self._val_metrics]
        for m in self._val_metrics:
            m.name = "validation " + m.name
        self._val_metrics.append(metric_mod.Loss(
            "validation " + type(self.evaluation_loss).__name__.lower()))

    @property
    def train_metrics(self):
        return self._train_metrics

    @property
    def val_metrics(self):
        return self._val_metrics

    # -- data ----------------------------------------------------------------
    @staticmethod
    def _get_data_and_label(batch, batch_axis=0):  # noqa: ARG004
        return batch[0], batch[1]

    # -- evaluate ------------------------------------------------------------
    def evaluate_batch(self, val_batch, val_metrics, batch_axis=0):
        data, label = self._get_data_and_label(val_batch, batch_axis)
        pred = self.val_net(data)
        loss = self.evaluation_loss(pred, label)
        from ...metric import Loss as LossMetric

        for m in val_metrics:
            if isinstance(m, LossMetric):
                m.update(0, loss)
            else:
                m.update(label, pred)

    def evaluate(self, val_data, val_metrics=None, batch_axis=0,
                 event_handlers=None):
        """Run one pass over val_data updating val_metrics; fires
        epoch/batch hooks on any handlers passed
        (reference: estimator.py:279)."""
        val_metrics = val_metrics or self._val_metrics
        for m in val_metrics:
            m.reset()
        event_handlers = _check_event_handlers(event_handlers)
        _, epoch_begin, batch_begin, batch_end, epoch_end, _ = \
            self._categorize_handlers(event_handlers)
        for handler in epoch_begin:
            handler.epoch_begin(self)
        for batch in val_data:
            for handler in batch_begin:
                handler.batch_begin(self, batch=batch)
            self.evaluate_batch(batch, val_metrics, batch_axis)
            for handler in batch_end:
                handler.batch_end(self, batch=batch)
        for handler in epoch_end:
            handler.epoch_end(self)
        return {name: value
                for name, value in (m.get() for m in val_metrics)}

    # -- fit -----------------------------------------------------------------
    def fit_batch(self, train_batch, batch_axis=0):
        from .... import autograd

        data, label = self._get_data_and_label(train_batch, batch_axis)
        with autograd.record():
            pred = self.net(data)
            loss = self.loss(pred, label)
        loss.backward()
        return data, label, pred, loss

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        """Train the net (reference: estimator.py:333). Pass `epochs` or
        `batches` (mutually exclusive semantics: whichever hits first)."""
        if not epochs and not batches:
            raise ValueError("pass `epochs` and/or `batches`")
        self.max_epoch = epochs
        self.max_batch = batches
        self.stop_training = False

        event_handlers = self._prepare_default_handlers(
            val_data, event_handlers)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize_handlers(event_handlers)
        step_guards = [h for h in event_handlers if isinstance(h, StepGuard)]
        from ....fault.injection import inject_at
        from ....telemetry import goodput, tracing

        for handler in train_begin:
            handler.train_begin(self)

        epoch = 0
        while not self.stop_training:
            with tracing.span("estimator.epoch", epoch=epoch):
                for handler in epoch_begin:
                    handler.epoch_begin(self)
                n_batches = 0
                for batch in train_data:
                    n_batches += 1
                    for handler in batch_begin:
                        handler.batch_begin(self, batch=batch)
                    # the step body is the self-healing boundary (fault
                    # subsystem): StepGuards may veto the optimizer update
                    # (non-finite loss) or absorb a mid-step crash after
                    # restoring a consistent state (ResilienceHandler
                    # resumes from the last good checkpoint); without a
                    # guard, every exception propagates exactly as before
                    try:
                        with tracing.span("estimator.step",
                                          batch=n_batches), \
                                goodput.lease("compute"):
                            inject_at("estimator_step")   # chaos seam
                            data, label, pred, loss = self.fit_batch(
                                batch, batch_axis)
                            n = data.shape[batch_axis] \
                                if hasattr(data, "shape") else 1
                            if any(g.pre_step(self, loss, batch)
                                   for g in step_guards):
                                # vetoed (e.g. non-finite loss): neither
                                # the update nor the batch_end metrics see
                                # the poisoned batch
                                continue
                            self.trainer.step(n)
                    except Exception as e:
                        if not any(g.on_crash(self, e)
                                   for g in step_guards):
                            raise
                        continue            # recovered: next batch
                    for handler in batch_end:
                        handler.batch_end(self, batch=batch, pred=pred,
                                          label=label, loss=loss)
                    if self.stop_training:
                        break
                if n_batches == 0:
                    raise ValueError(
                        "Estimator.fit: train_data yielded no batches "
                        "(an empty loader would loop forever)")
                for handler in epoch_end:
                    handler.epoch_end(self)
            epoch += 1

        for handler in train_end:
            handler.train_end(self)

    # -- handler plumbing ----------------------------------------------------
    def _prepare_default_handlers(self, val_data, event_handlers):
        event_handlers = _check_event_handlers(event_handlers)
        added_default = []
        if not any(isinstance(h, StoppingHandler) for h in event_handlers):
            event_handlers.append(StoppingHandler(self.max_epoch,
                                                  self.max_batch))
            added_default.append("StoppingHandler")
        if not any(isinstance(h, MetricHandler) for h in event_handlers):
            event_handlers.append(MetricHandler(self._train_metrics))
            added_default.append("MetricHandler")
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in event_handlers):
            event_handlers.append(ValidationHandler(
                val_data=val_data, eval_fn=self.evaluate))
            added_default.append("ValidationHandler")
        if not any(isinstance(h, LoggingHandler) for h in event_handlers):
            event_handlers.append(LoggingHandler(
                metrics=self._train_metrics + self._val_metrics))
            added_default.append("LoggingHandler")
        if added_default:
            self.logger.info("added default handlers: %s",
                             ", ".join(added_default))
        event_handlers.sort(key=lambda h: getattr(h, "priority", 0))
        return event_handlers

    @staticmethod
    def _categorize_handlers(event_handlers):
        train_begin, epoch_begin, batch_begin = [], [], []
        batch_end, epoch_end, train_end = [], [], []
        for h in event_handlers:
            if isinstance(h, TrainBegin):
                train_begin.append(h)
            if isinstance(h, EpochBegin):
                epoch_begin.append(h)
            if isinstance(h, BatchBegin):
                batch_begin.append(h)
            if isinstance(h, BatchEnd):
                batch_end.append(h)
            if isinstance(h, EpochEnd):
                epoch_end.append(h)
            if isinstance(h, TrainEnd):
                train_end.append(h)
        return (train_begin, epoch_begin, batch_begin, batch_end, epoch_end,
                train_end)


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]
