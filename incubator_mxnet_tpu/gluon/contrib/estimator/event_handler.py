"""Estimator event handlers
(reference: `python/mxnet/gluon/contrib/estimator/event_handler.py:37-746`).

Handlers are mixin classes keyed by lifecycle hook; the Estimator sorts them
by priority and invokes each hook across the train/eval loop. TPU-native
notes: checkpointing goes through `Block.save_parameters` (npz) and
`HybridBlock.export` (StableHLO artifact) rather than symbol JSON.
"""
from __future__ import annotations

import logging
import os
import time
import warnings

import numpy as onp

__all__ = ["EventHandler", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StepGuard", "StoppingHandler",
           "MetricHandler", "ValidationHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler"]


class EventHandler:
    pass


def _check_event_handlers(handlers):
    if isinstance(handlers, EventHandler):
        handlers = [handlers]
    else:
        handlers = handlers or []
        if not all(isinstance(h, EventHandler) for h in handlers):
            raise ValueError("event_handlers must be EventHandler instances")
    return handlers


class TrainBegin(EventHandler):
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd(EventHandler):
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin(EventHandler):
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd(EventHandler):
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin(EventHandler):
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd(EventHandler):
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StepGuard(EventHandler):
    """Handlers that sit INSIDE the train-step body (this build's
    fault-tolerance seam; no reference analogue — the reference's loop has
    no recovery story beyond checkpoint-restart). `pre_step` runs between
    backward and `trainer.step` and may veto the parameter update (return
    True to SKIP — e.g. a non-finite loss); `on_crash` sees any exception
    the step body raised and may absorb it (return True after restoring a
    consistent training state — e.g. `fault.ResilienceHandler` reloading
    the last good checkpoint)."""

    def pre_step(self, estimator, loss, batch):  # noqa: ARG002
        return False

    def on_crash(self, estimator, exc):  # noqa: ARG002
        return False


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch epochs or max_batch batches
    (reference: event_handler.py:82)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.max_epoch = estimator.max_epoch
        self.max_batch = estimator.max_batch
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch == self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch == self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics at epoch begin, update at batch end
    (reference: event_handler.py:122)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs["pred"]
        label = kwargs["label"]
        loss = kwargs["loss"]
        from ....gluon.metric import Loss

        for metric in self.metrics:
            if isinstance(metric, Loss):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation every `epoch_period` epochs / `batch_period` batches
    (reference: event_handler.py:160)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000, event_handlers=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.event_handlers = event_handlers
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data,
                         event_handlers=self.event_handlers)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data,
                         event_handlers=self.event_handlers)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Log training progress (reference: event_handler.py:226)."""

    def __init__(self, log_interval="epoch", metrics=None, priority=-1000):
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        # log_interval: "epoch" → epoch-level logs only; int N ≥ 1 → a log
        # line every N batches (N=1 logs every batch)
        if log_interval == "epoch":
            self.log_interval = None
        elif isinstance(log_interval, int) and log_interval >= 1:
            self.log_interval = log_interval
        else:
            raise ValueError("log_interval must be 'epoch' or a positive int")
        self.log_interval_time = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        estimator.logger.info(
            "Training begin: using optimizer %s with current learning rate"
            " %.4f", type(estimator.trainer.optimizer).__name__,
            estimator.trainer.learning_rate)
        if estimator.max_epoch:
            estimator.logger.info("Train for %d epochs.", estimator.max_epoch)
        else:
            estimator.logger.info("Train for %d batches.", estimator.max_batch)

    def train_end(self, estimator, *args, **kwargs):
        train_time = time.time() - self.train_start
        msg = f"Train finished using total {train_time:.0f}s at epoch " \
              f"{self.current_epoch}. "
        for m in self.metrics:
            name, value = m.get()
            msg += f"{name}: {_fmt(value)}, "
        estimator.logger.info(msg.rstrip(", "))

    def batch_begin(self, estimator, *args, **kwargs):
        if self.log_interval is not None:
            self.batch_start = time.time()

    def batch_end(self, estimator, *args, **kwargs):
        if self.log_interval is None:
            return
        batch_time = time.time() - self.batch_start
        batch = kwargs["batch"]
        self.batch_index += 1
        self.processed_samples += len(batch[0]) if isinstance(
            batch, (list, tuple)) else len(batch)
        self.log_interval_time += batch_time
        if self.batch_index % self.log_interval == 0:
            msg = f"[Epoch {self.current_epoch}][Batch {self.batch_index}]" \
                  f"[Samples {self.processed_samples}] " \
                  f"time/interval: {self.log_interval_time:.3f}s "
            self.log_interval_time = 0
            for m in self.metrics:
                name, value = m.get()
                msg += f"{name}: {_fmt(value)}, "
            estimator.logger.info(msg.rstrip(", "))

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        estimator.logger.info("[Epoch %d] Begin, current learning rate: "
                              "%.4f", self.current_epoch,
                              estimator.trainer.learning_rate)

    def epoch_end(self, estimator, *args, **kwargs):
        epoch_time = time.time() - self.epoch_start
        msg = f"[Epoch {self.current_epoch}] Finished in {epoch_time:.3f}s, "
        for m in self.metrics:
            name, value = m.get()
            msg += f"{name}: {_fmt(value)}, "
        estimator.logger.info(msg.rstrip(", "))
        self.current_epoch += 1
        self.batch_index = 0


def _fmt(value):
    return f"{value:.4f}" if isinstance(value, float) else str(value)


class CheckpointHandler(TrainBegin, TrainEnd, BatchEnd, EpochEnd):
    """Save params (+trainer states) periodically; keep best by monitored
    metric (reference: event_handler.py:336)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5, resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.verbose = verbose
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.resume_from_checkpoint = resume_from_checkpoint
        self.saved_checkpoints: list[str] = []
        if self.save_best and monitor is None:
            raise ValueError("save_best requires a monitor metric")
        self.current_batch = 0
        self.current_epoch = 0
        if mode not in ("auto", "min", "max"):
            warnings.warn(f"unknown mode {mode}; fallback to auto")
            mode = "auto"
        if mode == "min":
            self.monitor_op = onp.less
        elif mode == "max":
            self.monitor_op = onp.greater
        else:
            name = monitor.get()[0] if monitor is not None else ""
            self.monitor_op = (onp.greater if "acc" in name or "f1" in name
                               else onp.less)
        self.best = (onp.inf if self.monitor_op == onp.less else -onp.inf)

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0
        os.makedirs(self.model_dir, exist_ok=True)
        if self.resume_from_checkpoint:
            self._resume_from_checkpoint(estimator)
        # SIGTERM (TPU maintenance / preemption) saves immediately, so a
        # `resume_from_checkpoint=True` restart loses at most one batch
        from .... import preemption

        self._preemption_hook = lambda: self._save_checkpoint(estimator)
        preemption.on_preemption(self._preemption_hook)

    def train_end(self, estimator, *args, **kwargs):
        from .... import preemption

        hook = getattr(self, "_preemption_hook", None)
        if hook is not None:
            preemption.remove_preemption_hook(hook)
            self._preemption_hook = None

    def _resume_from_checkpoint(self, estimator):
        """Reload the newest matching checkpoint's params (+trainer states),
        continue the epoch/batch counters, re-seed the rotation window with
        ALL on-disk checkpoints, and restore the best-monitor value
        (reference: event_handler.py:542)."""
        import json
        import re

        pat = re.compile(
            rf"^{re.escape(self.model_prefix)}-epoch(\d+)batch(\d+)\.params$")
        found = []
        for f in os.listdir(self.model_dir):
            m = pat.match(f)
            if m:
                found.append(((int(m.group(1)), int(m.group(2))), f))
        if not found:
            estimator.logger.info(
                "CheckpointHandler: no checkpoint found in %s to resume from",
                self.model_dir)
            return
        found.sort()
        (epoch, batch), fname = found[-1]
        estimator.net.load_parameters(os.path.join(self.model_dir, fname))
        states = os.path.join(self.model_dir, fname[:-7] + ".states")
        if estimator.trainer is not None and os.path.exists(states):
            estimator.trainer.load_states(states)
        self.current_epoch = epoch
        self.current_batch = batch
        # oldest-first so the max_checkpoints rotation keeps deleting the
        # right files across crash/resume cycles
        for _, f in found:
            prefix = f[:-7]
            if prefix not in self.saved_checkpoints:
                self.saved_checkpoints.append(prefix)
        best_info = os.path.join(self.model_dir,
                                 f"{self.model_prefix}-best.info")
        if self.save_best and os.path.exists(best_info):
            with open(best_info) as f:
                self.best = json.load(f)["best"]
        estimator.logger.info(
            "CheckpointHandler: resumed from %s (epoch %d, batch %d)",
            fname, epoch, batch)

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save_checkpoint(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save_checkpoint(estimator)

    def _save_checkpoint(self, estimator):
        prefix = (f"{self.model_prefix}-epoch{self.current_epoch}"
                  f"batch{self.current_batch}")
        self._save_params_and_trainer(estimator, prefix)
        self.saved_checkpoints.append(prefix)
        while len(self.saved_checkpoints) > self.max_checkpoints:
            old = self.saved_checkpoints.pop(0)
            for suffix in (".params", ".states"):
                p = os.path.join(self.model_dir, old + suffix)
                if os.path.exists(p):
                    os.remove(p)
        if self.save_best:
            name, value = self.monitor.get()
            if self.monitor_op(value, self.best):
                self.best = value
                self._save_params_and_trainer(
                    estimator, f"{self.model_prefix}-best")
                import json

                with open(os.path.join(self.model_dir,
                                       f"{self.model_prefix}-best.info"),
                          "w") as f:
                    json.dump({"best": float(value), "metric": name}, f)
                if self.verbose > 0:
                    estimator.logger.info(
                        "[Epoch %d] %s improved to %.5f; saving best model",
                        self.current_epoch, name, value)

    def _save_params_and_trainer(self, estimator, file_prefix):
        param_file = os.path.join(self.model_dir, file_prefix + ".params")
        estimator.net.save_parameters(param_file)
        if estimator.trainer is not None:
            estimator.trainer.save_states(
                os.path.join(self.model_dir, file_prefix + ".states"))


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop training when the monitored metric stops improving
    (reference: event_handler.py:614)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        if mode not in ("auto", "min", "max"):
            warnings.warn(f"unknown mode {mode}; fallback to auto")
            mode = "auto"
        if mode == "min":
            self.monitor_op = onp.less
        elif mode == "max":
            self.monitor_op = onp.greater
        else:
            name = monitor.get()[0]
            self.monitor_op = (onp.greater if "acc" in name or "f1" in name
                               else onp.less)
        if self.monitor_op == onp.greater:
            self.min_delta *= 1
        else:
            self.min_delta *= -1

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        if self.baseline is not None:
            self.best = self.baseline
        else:
            self.best = (onp.inf if self.monitor_op == onp.less else -onp.inf)

    def epoch_end(self, estimator, *args, **kwargs):
        _, current = self.monitor.get()
        if current is None or (isinstance(current, float)
                               and onp.isnan(current)):
            return
        if self.monitor_op(current - self.min_delta, self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                estimator.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            estimator.logger.info(
                "[Epoch %d] EarlyStoppingHandler: early stopping due to %s "
                "not improving", self.stopped_epoch, self.monitor.get()[0])


_DEFAULT_LOGGER = logging.getLogger("incubator_mxnet_tpu.estimator")
