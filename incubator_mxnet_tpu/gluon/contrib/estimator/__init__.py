"""Gluon Estimator — high-level train/eval loop
(reference: `python/mxnet/gluon/contrib/estimator/__init__.py`)."""
from .estimator import Estimator
from .event_handler import (BatchBegin, BatchEnd, CheckpointHandler,
                            EarlyStoppingHandler, EpochBegin, EpochEnd,
                            EventHandler, LoggingHandler, MetricHandler,
                            StoppingHandler, TrainBegin, TrainEnd,
                            ValidationHandler)

__all__ = ["Estimator", "EventHandler", "TrainBegin", "TrainEnd",
           "EpochBegin", "EpochEnd", "BatchBegin", "BatchEnd",
           "StoppingHandler", "MetricHandler", "ValidationHandler",
           "LoggingHandler", "CheckpointHandler", "EarlyStoppingHandler"]
