"""gluon.contrib (reference: `python/mxnet/gluon/contrib/__init__.py`)."""
from . import estimator

__all__ = ["estimator"]
