"""gluon.contrib (reference: `python/mxnet/gluon/contrib/__init__.py`)."""
from . import data, estimator
from .moe import MoEFFN

__all__ = ["estimator", "data", "MoEFFN"]
