"""gluon.contrib (reference: `python/mxnet/gluon/contrib/__init__.py`)."""
from . import data, estimator

__all__ = ["estimator", "data"]
