"""Convolutional recurrent cells (reference:
`python/mxnet/gluon/rnn/conv_rnn_cell.py` — ConvRNNCell/ConvLSTMCell/
ConvGRUCell over 2-D feature maps, Shi et al. "Convolutional LSTM").

TPU-native: gates are two NCHW convolutions (input→gates, hidden→gates)
over `lax.conv_general_dilated` — both land on the MXU; the whole
per-step cell fuses under hybridize (and `npx.foreach`, which lowers the
time loop to lax.scan).
"""
from __future__ import annotations

from ... import numpy as np
from ... import numpy_extension as npx
from ..parameter import Parameter
from .rnn_cell import RecurrentCell

__all__ = ["ConvRNNCell", "ConvLSTMCell", "ConvGRUCell"]


class _ConvCellBase(RecurrentCell):
    """Shared conv-gate machinery: state (B, hidden, H, W); input
    (B, C, H, W); i2h and h2h are same-padded convs producing
    ngates*hidden channels."""

    def __init__(self, hidden_channels, ngates, kernel_size=(3, 3),
                 input_shape=None, dtype="float32",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dims=2):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * dims
        self._hidden = hidden_channels
        self._ngates = ngates
        self._kernel = tuple(kernel_size)
        # spatial dims from input_shape=(C, *spatial) if given (1-3D),
        # else learned on the first forward
        self._spatial = (tuple(input_shape[1:])
                         if input_shape is not None and len(input_shape) >= 2
                         else None)
        in_ch = 0 if input_shape is None else input_shape[0]
        k = self._kernel
        self.i2h_weight = Parameter(
            shape=(ngates * hidden_channels, in_ch) + k, dtype=dtype,
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = Parameter(
            shape=(ngates * hidden_channels, hidden_channels) + k,
            dtype=dtype, init=h2h_weight_initializer)
        self.i2h_bias = Parameter(shape=(ngates * hidden_channels,),
                                  dtype=dtype, init=i2h_bias_initializer)
        self.h2h_bias = Parameter(shape=(ngates * hidden_channels,),
                                  dtype=dtype, init=h2h_bias_initializer)

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._ngates * self._hidden,
                                 x.shape[1]) + self._kernel
        self._spatial = tuple(x.shape[2:])

    def state_info(self, batch_size=0):
        spatial = self._spatial or (0,) * len(self._kernel)
        return [{"shape": (batch_size, self._hidden) + spatial}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        if self._spatial is None:
            raise ValueError(
                "conv cell spatial dims unknown — construct with "
                "input_shape=(C, H, W) or run one forward first")
        return super().begin_state(batch_size, func, **kwargs)

    def _gates(self, x, h):
        pad = tuple(k // 2 for k in self._kernel)
        n = self._ngates * self._hidden
        i2h = npx.convolution(x, self.i2h_weight.data(),
                              self.i2h_bias.data(), kernel=self._kernel,
                              num_filter=n, pad=pad)
        h2h = npx.convolution(h, self.h2h_weight.data(),
                              self.h2h_bias.data(), kernel=self._kernel,
                              num_filter=n, pad=pad)
        return i2h + h2h


class ConvRNNCell(_ConvCellBase):
    """tanh conv-RNN cell (reference: conv_rnn_cell.py ConvRNNCell)."""

    def __init__(self, hidden_channels, kernel_size=(3, 3),
                 activation="tanh", **kwargs):
        super().__init__(hidden_channels, 1, kernel_size, **kwargs)
        self._activation = activation

    def forward(self, x, states):
        if self._spatial is None:
            self._spatial = tuple(x.shape[2:])
        out = npx.activation(self._gates(x, states[0]),
                             act_type=self._activation)
        return out, [out]


class ConvLSTMCell(_ConvCellBase):
    """Convolutional LSTM (reference: conv_rnn_cell.py ConvLSTMCell)."""

    def __init__(self, hidden_channels, kernel_size=(3, 3), **kwargs):
        super().__init__(hidden_channels, 4, kernel_size, **kwargs)

    def state_info(self, batch_size=0):
        spatial = self._spatial or (0,) * len(self._kernel)
        shape = (batch_size, self._hidden) + spatial
        return [{"shape": shape}, {"shape": shape}]

    def forward(self, x, states):
        if self._spatial is None:
            self._spatial = tuple(x.shape[2:])
        h, c = states
        gates = self._gates(x, h)
        hc = self._hidden
        i = npx.sigmoid(gates[:, :hc])
        f = npx.sigmoid(gates[:, hc:2 * hc])
        g = np.tanh(gates[:, 2 * hc:3 * hc])
        o = npx.sigmoid(gates[:, 3 * hc:])
        c_new = f * c + i * g
        h_new = o * np.tanh(c_new)
        return h_new, [h_new, c_new]


class ConvGRUCell(_ConvCellBase):
    """Convolutional GRU (reference: conv_rnn_cell.py ConvGRUCell)."""

    def __init__(self, hidden_channels, kernel_size=(3, 3), **kwargs):
        super().__init__(hidden_channels, 3, kernel_size, **kwargs)

    def forward(self, x, states):
        if self._spatial is None:
            self._spatial = tuple(x.shape[2:])
        h = states[0]
        # GRU needs i2h/h2h separately (reset gate multiplies h2h only),
        # so it can't use _gates; padding generalizes over 1-3D kernels
        pad = tuple(k // 2 for k in self._kernel)
        n = self._ngates * self._hidden
        i2h = npx.convolution(x, self.i2h_weight.data(),
                              self.i2h_bias.data(), kernel=self._kernel,
                              num_filter=n, pad=pad)
        h2h = npx.convolution(h, self.h2h_weight.data(),
                              self.h2h_bias.data(), kernel=self._kernel,
                              num_filter=n, pad=pad)
        hc = self._hidden
        r = npx.sigmoid(i2h[:, :hc] + h2h[:, :hc])
        z = npx.sigmoid(i2h[:, hc:2 * hc] + h2h[:, hc:2 * hc])
        nvl = np.tanh(i2h[:, 2 * hc:] + r * h2h[:, 2 * hc:])
        h_new = (1 - z) * nvl + z * h
        return h_new, [h_new]

# Dimensional variants (reference: conv_rnn_cell.py Conv{1,2,3}D{RNN,LSTM,
# GRU}Cell): the generic cells above are N-d; these fix `dims` and the
# default kernel so signatures match the reference layer-per-rank classes.
def _dim_variant(base, dims, name, default_kernel):
    def __init__(self, hidden_channels, kernel_size=default_kernel,
                 **kwargs):  # noqa: N807
        kwargs.setdefault("dims", dims)
        base.__init__(self, hidden_channels, kernel_size=kernel_size,
                      **kwargs)

    return type(name, (base,), {"__init__": __init__,
                                "__doc__": f"{dims}-D {base.__name__} "
                                           f"(reference conv_rnn_cell.py)"})


Conv1DRNNCell = _dim_variant(ConvRNNCell, 1, "Conv1DRNNCell", (3,))
Conv2DRNNCell = _dim_variant(ConvRNNCell, 2, "Conv2DRNNCell", (3, 3))
Conv3DRNNCell = _dim_variant(ConvRNNCell, 3, "Conv3DRNNCell", (3, 3, 3))
Conv1DLSTMCell = _dim_variant(ConvLSTMCell, 1, "Conv1DLSTMCell", (3,))
Conv2DLSTMCell = _dim_variant(ConvLSTMCell, 2, "Conv2DLSTMCell", (3, 3))
Conv3DLSTMCell = _dim_variant(ConvLSTMCell, 3, "Conv3DLSTMCell", (3, 3, 3))
Conv1DGRUCell = _dim_variant(ConvGRUCell, 1, "Conv1DGRUCell", (3,))
Conv2DGRUCell = _dim_variant(ConvGRUCell, 2, "Conv2DGRUCell", (3, 3))
Conv3DGRUCell = _dim_variant(ConvGRUCell, 3, "Conv3DGRUCell", (3, 3, 3))
