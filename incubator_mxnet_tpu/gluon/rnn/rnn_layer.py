"""Fused RNN layers (reference: `python/mxnet/gluon/rnn/rnn_layer.py` over the
fused RNN op `src/operator/rnn.cc:296`). The TPU kernel is a lax.scan in
`npx.rnn`; parameters live in the same flat cuDNN-compatible vector layout."""
from __future__ import annotations

from ... import numpy_extension as npx
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, mode, dtype="float32", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):  # noqa: ARG002
        super().__init__()
        assert layout in ("TNC", "NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self.parameters = Parameter(
            shape=(npx.rnn_param_size(mode, num_layers, input_size, hidden_size,
                                      bidirectional) if input_size else 0,),
            dtype=dtype, init=i2h_weight_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self._input_size = x.shape[-1]
        self.parameters.shape = (npx.rnn_param_size(
            self._mode, self._num_layers, self._input_size, self._hidden_size,
            self._dir == 2),)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        if self._mode == "lstm":
            return [{"shape": shape}, {"shape": shape}]
        return [{"shape": shape}]

    def begin_state(self, batch_size=0, func=None, **kwargs):  # noqa: ARG002
        import jax.numpy as jnp

        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        h = NDArray(jnp.zeros(shape))
        if self._mode == "lstm":
            return [h, NDArray(jnp.zeros(shape))]
        return [h]

    def forward(self, x, states=None):
        explicit_states = states is not None
        if states is None:
            batch = x.shape[0] if self._layout == "NTC" else x.shape[1]
            states = self.begin_state(batch)
        if isinstance(states, NDArray):
            states = [states]
        seq = x.swapaxes(0, 1) if self._layout == "NTC" else x
        out = npx.rnn(data=seq, parameters=self.parameters.data(),
                      state=states[0],
                      state_cell=states[1] if self._mode == "lstm" else None,
                      mode=self._mode, state_size=self._hidden_size,
                      num_layers=self._num_layers,
                      bidirectional=self._dir == 2, p=self._dropout,
                      state_outputs=True)
        if self._mode == "lstm":
            y, h, c = out
            new_states = [h, c]
        else:
            y, h = out
            new_states = [h]
        if self._layout == "NTC":
            y = y.swapaxes(0, 1)
        if explicit_states:
            return y, new_states
        return y

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, layout={self._layout})")


class RNN(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, mode, **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)
