"""Unrolled RNN cells (reference: `python/mxnet/gluon/rnn/rnn_cell.py`)."""
from __future__ import annotations

from ... import numpy as np
from ... import numpy_extension as npx
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


class RecurrentCell(HybridBlock):
    def __init__(self):
        super().__init__()
        self._modified = False

    def reset(self):
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):  # noqa: ARG002
        import jax.numpy as jnp

        return [NDArray(jnp.zeros(info["shape"]))
                for info in self.state_info(batch_size)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):  # noqa: ARG002
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        states = begin_state or self.begin_state(batch)
        outputs = []
        for t in range(length):
            x_t = inputs[t] if axis == 0 else inputs[:, t]
            out, states = self(x_t, states)
            outputs.append(out)
        if merge_outputs is not False:
            outputs = np.stack(outputs, axis=axis)
        return outputs, states


HybridRecurrentCell = RecurrentCell


class _BaseCell(RecurrentCell):
    def __init__(self, hidden_size, ngates, input_size=0, dtype="float32",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = Parameter(shape=(ngates * hidden_size, input_size),
                                    dtype=dtype, init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter(shape=(ngates * hidden_size, hidden_size),
                                    dtype=dtype, init=h2h_weight_initializer)
        self.i2h_bias = Parameter(shape=(ngates * hidden_size,), dtype=dtype,
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter(shape=(ngates * hidden_size,), dtype=dtype,
                                  init=h2h_bias_initializer)
        self._ngates = ngates

    def infer_shape(self, x, *args):
        self._input_size = x.shape[-1]
        self.i2h_weight.shape = (self._ngates * self._hidden_size,
                                 self._input_size)


class RNNCell(_BaseCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def forward(self, x, states):
        h = states[0]
        i2h = npx.fully_connected(x, self.i2h_weight.data(),
                                  self.i2h_bias.data(),
                                  num_hidden=self._ngates * self._hidden_size)
        h2h = npx.fully_connected(h, self.h2h_weight.data(),
                                  self.h2h_bias.data(),
                                  num_hidden=self._ngates * self._hidden_size)
        out = npx.activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def forward(self, x, states):
        h, c = states
        H = self._hidden_size
        gates = (npx.fully_connected(x, self.i2h_weight.data(),
                                     self.i2h_bias.data(), num_hidden=4 * H)
                 + npx.fully_connected(h, self.h2h_weight.data(),
                                       self.h2h_bias.data(), num_hidden=4 * H))
        i = npx.sigmoid(gates[:, :H])
        f = npx.sigmoid(gates[:, H:2 * H])
        g = np.tanh(gates[:, 2 * H:3 * H])
        o = npx.sigmoid(gates[:, 3 * H:])
        c_new = f * c + i * g
        h_new = o * np.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def forward(self, x, states):
        h = states[0]
        H = self._hidden_size
        i2h = npx.fully_connected(x, self.i2h_weight.data(),
                                  self.i2h_bias.data(), num_hidden=3 * H)
        h2h = npx.fully_connected(h, self.h2h_weight.data(),
                                  self.h2h_bias.data(), num_hidden=3 * H)
        r = npx.sigmoid(i2h[:, :H] + h2h[:, :H])
        z = npx.sigmoid(i2h[:, H:2 * H] + h2h[:, H:2 * H])
        n = np.tanh(i2h[:, 2 * H:] + r * h2h[:, 2 * H:])
        h_new = (1 - z) * n + z * h
        return h_new, [h_new]


class SequentialRNNCell(RecurrentCell):
    def __init__(self):
        super().__init__()
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return sum((c.state_info(batch_size) for c in self._cells), [])

    def begin_state(self, batch_size=0, **kwargs):
        return sum((c.begin_state(batch_size, **kwargs) for c in self._cells), [])

    def __len__(self):
        return len(self._cells)

    def __getitem__(self, i):
        return self._cells[i]

    def forward(self, x, states):
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_info())
            x, new_s = cell(x, states[pos:pos + n])
            pos += n
            next_states.extend(new_s)
        return x, next_states


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):  # noqa: ARG002
        return []

    def forward(self, x, states):
        return npx.dropout(x, p=self._rate, axes=self._axes), states


class ZoneoutCell(RecurrentCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__()
        self.base_cell = base_cell
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def forward(self, x, states):
        from ... import autograd

        out, next_states = self.base_cell(x, states)
        if autograd.is_training():
            import jax.random as jr

            from ...random import next_key

            def mask(p, new, old):
                keep = NDArray(jr.bernoulli(next_key(), 1 - p, new.shape))
                return keep * new + (1 - keep) * old

            if self._zo:
                prev = self._prev_output if self._prev_output is not None \
                    else out.zeros_like()
                out = mask(self._zo, out, prev)
            if self._zs:
                next_states = [mask(self._zs, ns, s)
                               for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class ResidualCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def forward(self, x, states):
        out, next_states = self.base_cell(x, states)
        return out + x, next_states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell):
        super().__init__()
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return (self.l_cell.state_info(batch_size)
                + self.r_cell.state_info(batch_size))

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        states = begin_state or self.begin_state(batch)
        n_l = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(length, inputs, states[:n_l],
                                             layout, True, valid_length)
        rev = np.flip(inputs, axis=axis)
        r_out, r_states = self.r_cell.unroll(length, rev, states[n_l:],
                                             layout, True, valid_length)
        r_out = np.flip(r_out, axis=axis)
        out = np.concatenate([l_out, r_out], axis=-1)
        return out, l_states + r_states

    def forward(self, x, states):
        raise NotImplementedError("BidirectionalCell supports unroll() only")
