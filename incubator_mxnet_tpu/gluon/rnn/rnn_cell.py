"""Unrolled RNN cells (reference: `python/mxnet/gluon/rnn/rnn_cell.py`)."""
from __future__ import annotations

from ... import numpy as np
from ... import numpy_extension as npx
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "BidirectionalCell",
           "ModifierCell", "VariationalDropoutCell", "LSTMPCell"]


class RecurrentCell(HybridBlock):
    def __init__(self):
        super().__init__()
        self._modified = False

    def reset(self):
        """Clear per-sequence state, recursing into child cells (the
        reference reset() walks _children so wrapped/stacked modifier
        cells resample their masks etc. each sequence)."""
        self._modified = False
        # base_cell/wrapped cells are Block attributes, so they are all
        # auto-registered in _children — one walk covers every nesting
        for child in self._children.values():
            if isinstance(child, RecurrentCell):
                child.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):  # noqa: ARG002
        import jax.numpy as jnp

        return [NDArray(jnp.zeros(info["shape"]))
                for info in self.state_info(batch_size)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):  # noqa: ARG002
        # reference RecurrentCell.unroll resets per-sequence state (e.g.
        # VariationalDropoutCell resamples its masks each sequence)
        self.reset()
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        states = begin_state or self.begin_state(batch)
        outputs = []
        for t in range(length):
            x_t = inputs[t] if axis == 0 else inputs[:, t]
            out, states = self(x_t, states)
            outputs.append(out)
        if merge_outputs is not False:
            outputs = np.stack(outputs, axis=axis)
        return outputs, states


HybridRecurrentCell = RecurrentCell


class _BaseCell(RecurrentCell):
    def __init__(self, hidden_size, ngates, input_size=0, dtype="float32",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros"):
        super().__init__()
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = Parameter(shape=(ngates * hidden_size, input_size),
                                    dtype=dtype, init=i2h_weight_initializer,
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter(shape=(ngates * hidden_size, hidden_size),
                                    dtype=dtype, init=h2h_weight_initializer)
        self.i2h_bias = Parameter(shape=(ngates * hidden_size,), dtype=dtype,
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter(shape=(ngates * hidden_size,), dtype=dtype,
                                  init=h2h_bias_initializer)
        self._ngates = ngates

    def infer_shape(self, x, *args):
        self._input_size = x.shape[-1]
        self.i2h_weight.shape = (self._ngates * self._hidden_size,
                                 self._input_size)


class RNNCell(_BaseCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def forward(self, x, states):
        h = states[0]
        i2h = npx.fully_connected(x, self.i2h_weight.data(),
                                  self.i2h_bias.data(),
                                  num_hidden=self._ngates * self._hidden_size)
        h2h = npx.fully_connected(h, self.h2h_weight.data(),
                                  self.h2h_bias.data(),
                                  num_hidden=self._ngates * self._hidden_size)
        out = npx.activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def forward(self, x, states):
        h, c = states
        H = self._hidden_size
        gates = (npx.fully_connected(x, self.i2h_weight.data(),
                                     self.i2h_bias.data(), num_hidden=4 * H)
                 + npx.fully_connected(h, self.h2h_weight.data(),
                                       self.h2h_bias.data(), num_hidden=4 * H))
        i = npx.sigmoid(gates[:, :H])
        f = npx.sigmoid(gates[:, H:2 * H])
        g = np.tanh(gates[:, 2 * H:3 * H])
        o = npx.sigmoid(gates[:, 3 * H:])
        c_new = f * c + i * g
        h_new = o * np.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def forward(self, x, states):
        h = states[0]
        H = self._hidden_size
        i2h = npx.fully_connected(x, self.i2h_weight.data(),
                                  self.i2h_bias.data(), num_hidden=3 * H)
        h2h = npx.fully_connected(h, self.h2h_weight.data(),
                                  self.h2h_bias.data(), num_hidden=3 * H)
        r = npx.sigmoid(i2h[:, :H] + h2h[:, :H])
        z = npx.sigmoid(i2h[:, H:2 * H] + h2h[:, H:2 * H])
        n = np.tanh(i2h[:, 2 * H:] + r * h2h[:, 2 * H:])
        h_new = (1 - z) * n + z * h
        return h_new, [h_new]


class SequentialRNNCell(RecurrentCell):
    def __init__(self):
        super().__init__()
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return sum((c.state_info(batch_size) for c in self._cells), [])

    def begin_state(self, batch_size=0, **kwargs):
        return sum((c.begin_state(batch_size, **kwargs) for c in self._cells), [])

    def __len__(self):
        return len(self._cells)

    def __getitem__(self, i):
        return self._cells[i]

    def forward(self, x, states):
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_info())
            x, new_s = cell(x, states[pos:pos + n])
            pos += n
            next_states.extend(new_s)
        return x, next_states


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):  # noqa: ARG002
        return []

    def forward(self, x, states):
        return npx.dropout(x, p=self._rate, axes=self._axes), states


class ZoneoutCell(RecurrentCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__()
        self.base_cell = base_cell
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None  # don't zone out toward a past sequence

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def forward(self, x, states):
        from ... import autograd

        out, next_states = self.base_cell(x, states)
        if autograd.is_training():
            import jax.random as jr

            from ...random import next_key

            def mask(p, new, old):
                keep = NDArray(jr.bernoulli(next_key(), 1 - p, new.shape))
                return keep * new + (1 - keep) * old

            if self._zo:
                prev = self._prev_output if self._prev_output is not None \
                    else out.zeros_like()
                out = mask(self._zo, out, prev)
            if self._zs:
                next_states = [mask(self._zs, ns, s)
                               for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class ResidualCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def forward(self, x, states):
        out, next_states = self.base_cell(x, states)
        return out + x, next_states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell):
        super().__init__()
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return (self.l_cell.state_info(batch_size)
                + self.r_cell.state_info(batch_size))

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        states = begin_state or self.begin_state(batch)
        n_l = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(length, inputs, states[:n_l],
                                             layout, True, valid_length)
        rev = np.flip(inputs, axis=axis)
        r_out, r_states = self.r_cell.unroll(length, rev, states[n_l:],
                                             layout, True, valid_length)
        r_out = np.flip(r_out, axis=axis)
        out = np.concatenate([l_out, r_out], axis=-1)
        return out, l_states + r_states

    def forward(self, x, states):
        raise NotImplementedError("BidirectionalCell supports unroll() only")


class ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py
    ModifierCell — Dropout/Zoneout/Residual modifiers share it)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def forward(self, x, states):
        raise NotImplementedError


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask reused across time steps (reference: rnn_cell.py
    VariationalDropoutCell / Gal & Ghahramani 2016)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self._di = drop_inputs
        self._ds = drop_states
        self._do = drop_outputs
        self._mask_i = None
        self._mask_s = None
        self._mask_o = None

    def reset(self):
        super().reset()
        self._mask_i = self._mask_s = self._mask_o = None

    def _mask(self, cached, like, rate):
        from ... import autograd as ag

        if rate == 0.0 or not ag.is_training():
            return None
        if cached is None or cached.shape != like.shape:
            keep = 1.0 - rate
            cached = (np.random.uniform(0, 1, like.shape) < keep) / keep
        return cached

    def forward(self, x, states):
        self._mask_i = self._mask(self._mask_i, x, self._di)
        if self._mask_i is not None:
            x = x * self._mask_i
        if self._ds:
            self._mask_s = self._mask(self._mask_s, states[0], self._ds)
            if self._mask_s is not None:
                states = [states[0] * self._mask_s] + list(states[1:])
        out, states = self.base_cell(x, states)
        self._mask_o = self._mask(self._mask_o, out, self._do)
        if self._mask_o is not None:
            out = out * self._mask_o
        return out, states


class LSTMPCell(_BaseCell):
    """LSTM with a hidden-state projection (reference: rnn_cell.py
    LSTMPCell / Sak et al. 2014 — h = r2h(o·tanh(c)), state h is the
    projected vector)."""

    def __init__(self, hidden_size, projection_size, input_size=0,
                 h2r_weight_initializer=None, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)
        self._projection_size = projection_size
        dtype = kwargs.get("dtype", "float32")
        # h2h operates on the PROJECTED state: rebuild the parameter with
        # the projected input width (shape is fixed at Parameter creation)
        self.h2h_weight = Parameter(
            shape=(4 * hidden_size, projection_size), dtype=dtype,
            init=kwargs.get("h2h_weight_initializer"))
        self.h2r_weight = Parameter(
            shape=(projection_size, hidden_size), dtype=dtype,
            init=h2r_weight_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def forward(self, x, states):
        h, c = states
        H = self._hidden_size
        gates = (npx.fully_connected(x, self.i2h_weight.data(),
                                     self.i2h_bias.data(), num_hidden=4 * H)
                 + npx.fully_connected(h, self.h2h_weight.data(),
                                       self.h2h_bias.data(),
                                       num_hidden=4 * H))
        i = npx.sigmoid(gates[:, :H])
        f = npx.sigmoid(gates[:, H:2 * H])
        g = np.tanh(gates[:, 2 * H:3 * H])
        o = npx.sigmoid(gates[:, 3 * H:])
        c_new = f * c + i * g
        h_full = o * np.tanh(c_new)
        h_proj = npx.fully_connected(h_full, self.h2r_weight.data(), None,
                                     num_hidden=self._projection_size,
                                     no_bias=True)
        return h_proj, [h_proj, c_new]


HybridSequentialRNNCell = SequentialRNNCell
