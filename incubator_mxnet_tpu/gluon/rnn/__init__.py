from .rnn_cell import (  # noqa: F401
    BidirectionalCell, DropoutCell, GRUCell, HybridRecurrentCell,
    HybridSequentialRNNCell, LSTMCell, LSTMPCell, ModifierCell,
    RecurrentCell, ResidualCell, RNNCell, SequentialRNNCell,
    VariationalDropoutCell, ZoneoutCell,
)
from .rnn_layer import GRU, LSTM, RNN  # noqa: F401
from .conv_rnn_cell import (  # noqa: F401
    Conv1DGRUCell, Conv1DLSTMCell, Conv1DRNNCell, Conv2DGRUCell,
    Conv2DLSTMCell, Conv2DRNNCell, Conv3DGRUCell, Conv3DLSTMCell,
    Conv3DRNNCell, ConvGRUCell, ConvLSTMCell, ConvRNNCell,
)
