from .rnn_cell import (  # noqa: F401
    BidirectionalCell, DropoutCell, GRUCell, HybridRecurrentCell, LSTMCell,
    RecurrentCell, ResidualCell, RNNCell, SequentialRNNCell, ZoneoutCell,
)
from .rnn_layer import GRU, LSTM, RNN  # noqa: F401
from .conv_rnn_cell import ConvGRUCell, ConvLSTMCell, ConvRNNCell  # noqa: F401
