"""Gluon Trainer (reference: `python/mxnet/gluon/trainer.py:32` — kvstore
setup, `step` :341 → `_allreduce_grads` :392 → `_update` :451).

TPU-native: gradient reduction goes through the KVStore facade whose
'device'/'dist' backends are ICI/DCN collectives (jax.lax.psum under
shard_map) instead of ps-lite/NCCL; on a single chip it is a no-op."""
from __future__ import annotations

from .. import optimizer as opt_mod
from ..ndarray.ndarray import NDArray

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):  # noqa: ARG002
        self._compression_params = compression_params
        if isinstance(params, (dict,)):
            param_dict = dict(params)
        else:
            param_dict = {getattr(p, "name", str(i)): p
                          for i, p in enumerate(params)}
        self._params = []
        self._params_by_name = {}
        for name, p in sorted(param_dict.items()):
            p.name = name
            self._params.append(p)
            self._params_by_name[name] = p
        self._scale = 1.0
        optimizer_params = optimizer_params or {}
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = {p.name: p for p in self._params}
        self._states = [None] * len(self._params)
        self._states_initialized = [False] * len(self._params)
        self._kvstore = None
        self._kvstore_type = kvstore
        if update_on_kvstore is None:
            # MXNET_UPDATE_ON_KVSTORE (env_var.md): default when the
            # caller leaves the choice open. Our stores run the optimizer
            # in-process either way (no server role), so this toggles
            # intent/bookkeeping, not placement.
            import os

            update_on_kvstore = \
                os.environ.get("MXNET_UPDATE_ON_KVSTORE") == "1"
        self._update_on_kvstore = bool(update_on_kvstore)
        self._kv_initialized = False

    # -- kvstore ------------------------------------------------------------
    def _init_kvstore(self):
        from .. import kvstore as kv_mod

        if self._kvstore_type is None:
            self._kvstore = None
        elif isinstance(self._kvstore_type, str):
            self._kvstore = kv_mod.create(self._kvstore_type)
        else:
            self._kvstore = self._kvstore_type
        if self._kvstore is not None and self._compression_params:
            if not hasattr(self._kvstore, "set_gradient_compression"):
                raise ValueError(
                    f"kvstore {type(self._kvstore).__name__} does not "
                    "support gradient compression")
            self._kvstore.set_gradient_compression(self._compression_params)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- step ---------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """Rescale grads by 1/batch_size, allreduce, apply optimizer."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null" and p._data is not None \
                    and p._data._grad is not None:
                grad = p.data()._grad
                # Dense grads allreduce as usual. row_sparse grads ride the
                # sparse pushpull: copies merge by gather-unique-sum and the
                # out-write stays (indices, values), so the lazy optimizer
                # update still touches only looked-up rows (reference:
                # kvstore_local.h:232 PushImpl row_sparse merge). Under a
                # dist store the cross-process allreduce densifies — the
                # RowSparse out-write then re-expresses as all-rows-stored
                # (documented divergence from the reference's sparse ZPush).
                self._kvstore.pushpull(i, grad, out=grad)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):  # noqa: ARG002
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if p._data._grad is None:
                continue
            if not self._states_initialized[i]:
                self._states[i] = self._optimizer.create_state_multi_precision(
                    i, p.data())
                self._states_initialized[i] = True
            self._optimizer.idx2name[i] = p.name
            new_state = self._optimizer.update_multi_precision(
                i, p.data(), p.data()._grad, self._states[i])
            if new_state is not None:
                self._states[i] = new_state

    # -- checkpointing (reference: trainer.py:489,518) -----------------------
    def save_states(self, fname):
        import pickle

        import numpy as onp

        payload = []
        for s in self._states:
            if s is None:
                payload.append(None)
            elif isinstance(s, list):
                payload.append([onp.asarray(x) for x in s])
            elif isinstance(s, tuple):
                payload.append(("mp", onp.asarray(s[0]),
                                [onp.asarray(x) for x in s[1]]))
            else:
                payload.append(onp.asarray(s))
        blob = {"states": payload,
                "num_update": self._optimizer.num_update,
                # per-param update counts drive Adam-family bias
                # correction: losing them resets t and inflates
                # the post-resume step size
                "index_update_count":
                    dict(self._optimizer._index_update_count)}
        # crash-safe + checksummed (fault subsystem): optimizer momenta are
        # part of the loss trajectory — a torn states file silently resets
        # Adam bias correction on resume
        from .. import preemption

        def _write(tmp):
            with open(tmp, "wb") as f:
                pickle.dump(blob, f)

        preemption.atomic_save(fname, _write)

    def load_states(self, fname):
        import pickle

        import jax.numpy as jnp

        from .. import preemption
        from ..base import MXNetError

        if preemption.verify_checkpoint(fname) is False:
            raise MXNetError(
                f"trainer state file {fname} failed checksum validation "
                "(truncated or corrupt)")
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        states = []
        for s in payload["states"]:
            if s is None:
                states.append(None)
            elif isinstance(s, list):
                states.append([jnp.asarray(x) for x in s])
            elif isinstance(s, tuple) and len(s) == 3 and s[0] == "mp":
                states.append((jnp.asarray(s[1]), [jnp.asarray(x) for x in s[2]]))
            else:
                states.append(jnp.asarray(s))
        self._states = states
        self._states_initialized = [s is not None for s in states]
        self._optimizer.num_update = payload.get("num_update", 0)
        self._optimizer._index_update_count = dict(
            payload.get("index_update_count", {}))
