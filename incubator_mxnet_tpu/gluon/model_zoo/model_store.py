"""Pretrained-weight store (reference:
`python/mxnet/gluon/model_zoo/model_store.py:31-140`).

TPU-native/no-egress design: the reference downloads .params archives from
an S3 repo and verifies sha1. This environment has zero network egress, so
the store resolves ONLY against local caches: `$MXNET_HOME/models` (or
`~/.incubator_mxnet_tpu/models`) plus any directory on
`INCUBATOR_MXNET_TPU_MODEL_PATH`. `get_model_file` verifies sha1 when a
checksum is registered; `export_to_store` registers locally-trained weights
so `get_model(..., pretrained=True)` round-trips."""
from __future__ import annotations

import hashlib
import json
import os

__all__ = ["get_model_file", "purge", "data_dir", "register_sha1",
           "export_to_store", "short_hash"]

# Each store root carries its own registry.json mapping name -> sha1 of the
# .params payload. Registries are root-scoped on disk AND in use — a sha
# registered in one root must not constrain lookups in another. (The
# reference ships a hardcoded table for its S3 assets.)


def data_dir():
    return os.environ.get(
        "MXNET_HOME",
        os.path.join(os.path.expanduser("~"), ".incubator_mxnet_tpu"))


def _registry_path(root):
    return os.path.join(root, "registry.json")


def _load_registry(root) -> dict:
    path = _registry_path(root)
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return {}


def _save_registry(root, registry):
    os.makedirs(root, exist_ok=True)
    with open(_registry_path(root), "w") as f:
        json.dump(registry, f, indent=2, sort_keys=True)


def short_hash(name, root=None):
    for r in _search_roots(root):
        reg = _load_registry(r)
        if name in reg:
            return reg[name][:8]
    raise ValueError(f"pretrained model for {name} is not available")


def _sha1(path):
    h = hashlib.sha1()  # noqa: S324 — content checksum, not security
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _search_roots(root=None):
    roots = [root] if root else []
    # MXNET_GLUON_REPO (env_var.md): override the artifact root — here a
    # local directory (no egress) searched before the default caches
    repo = os.environ.get("MXNET_GLUON_REPO", "")
    if repo and "://" not in repo:
        roots.append(repo)
    roots.append(os.path.join(data_dir(), "models"))
    extra = os.environ.get("INCUBATOR_MXNET_TPU_MODEL_PATH", "")
    roots += [p for p in extra.split(os.pathsep) if p]
    # packaged store: small trained artifacts committed WITH the framework
    # (the no-egress stand-in for the reference's S3 model repo; also the
    # cross-version load-compatibility anchor,
    # `tests/nightly/model_backwards_compatibility_check/`)
    roots.append(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "_store"))
    return roots


def get_model_file(name, root=None):
    """Locate (and checksum-verify) `<name>.params` in the local store
    (reference: model_store.py:75 downloads+verifies; here: local-only,
    no egress). Checksums apply per root: a file is verified only against
    the registry of the root it was found in."""
    for r in _search_roots(root):
        reg = _load_registry(r)
        want = reg.get(name)
        candidates = []
        if want:
            candidates.append(f"{name}-{want[:8]}.params")
        candidates.append(f"{name}.params")
        for fname in candidates:
            path = os.path.join(r, fname)
            if os.path.exists(path):
                if want and _sha1(path) != want:
                    raise ValueError(
                        f"checksum mismatch for {path}; delete the file and "
                        "re-export it")
                return path
    raise FileNotFoundError(
        f"pretrained weights for {name!r} not found in "
        f"{_search_roots(root)}; this build has no network egress — place "
        f"{name}.params there or train locally and call export_to_store")


def register_sha1(name, sha1_hash, root=None):
    """Register a checksum for `name` in `root`'s registry."""
    root = root or os.path.join(data_dir(), "models")
    registry = _load_registry(root)
    registry[name] = sha1_hash
    _save_registry(root, registry)


def export_to_store(net, name, root=None):
    """Save a trained net's parameters into the store under `name` and
    register the checksum, making `pretrained=True` loads work offline."""
    root = root or os.path.join(data_dir(), "models")
    os.makedirs(root, exist_ok=True)
    tmp = os.path.join(root, f"{name}.params.tmp")
    net.save_parameters(tmp)
    sha = _sha1(tmp)
    final = os.path.join(root, f"{name}-{sha[:8]}.params")
    os.replace(tmp, final)
    register_sha1(name, sha, root)
    return final


def purge(root=None):
    """Delete cached model files (reference: model_store.py:129)."""
    root = root or os.path.join(data_dir(), "models")
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
