"""Inception v3 (reference:
`python/mxnet/gluon/model_zoo/vision/inception.py:32-190`, Szegedy et al.
"Rethinking the Inception Architecture"). Structure matches the reference's
block composition (A/B/C/D/E mixes) so checkpoints map 1:1 by module path."""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(**kwargs):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Branches(HybridBlock):
    """Parallel branches concatenated on channels (the HybridConcurrent
    analogue; reference: gluon/contrib/nn/basic_layers.py HybridConcurrent)."""

    def __init__(self):
        super().__init__()
        self._order = []

    def add(self, block):
        name = f"b{len(self._order)}"
        self.register_block(name, block)
        self._order.append(name)

    def forward(self, x):
        from .... import numpy as mnp

        outs = [self._children[n](x) for n in self._order]
        return mnp.concatenate(outs, axis=1)


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential()
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for channels, kernel_size, strides, padding in conv_settings:
        kw = {"channels": channels, "kernel_size": kernel_size}
        if strides is not None:
            kw["strides"] = strides
        if padding is not None:
            kw["padding"] = padding
        out.add(_make_basic_conv(**kw))
    return out


def _make_A(pool_features):
    out = _Branches()
    out.add(_make_branch(None, (64, 1, None, None)))
    out.add(_make_branch(None, (48, 1, None, None), (64, 5, None, 2)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                         (96, 3, None, 1)))
    out.add(_make_branch("avg", (pool_features, 1, None, None)))
    return out


def _make_B():
    out = _Branches()
    out.add(_make_branch(None, (384, 3, 2, None)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                         (96, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


def _make_C(channels_7x7):
    out = _Branches()
    out.add(_make_branch(None, (192, 1, None, None)))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0))))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (192, (1, 7), None, (0, 3))))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


def _make_D():
    out = _Branches()
    out.add(_make_branch(None, (192, 1, None, None), (320, 3, 2, None)))
    out.add(_make_branch(None, (192, 1, None, None),
                         (192, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0)),
                         (192, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


class _SplitConcat(HybridBlock):
    """1x1 reduce then parallel (1,3)/(3,1) convs, concatenated — the E-mix
    sub-branch shape."""

    def __init__(self, reduce_settings, split_settings):
        super().__init__()
        self.reduce = (_make_branch(None, *reduce_settings)
                       if reduce_settings else None)
        self.split = _Branches()
        for setting in split_settings:
            self.split.add(_make_branch(None, setting))

    def forward(self, x):
        if self.reduce is not None:
            x = self.reduce(x)
        return self.split(x)


def _make_E():
    out = _Branches()
    out.add(_make_branch(None, (320, 1, None, None)))
    out.add(_SplitConcat([(384, 1, None, None)],
                         [(384, (1, 3), None, (0, 1)),
                          (384, (3, 1), None, (1, 0))]))
    out.add(_SplitConcat([(448, 1, None, None), (384, 3, None, 1)],
                         [(384, (1, 3), None, (0, 1)),
                          (384, (3, 1), None, (1, 0))]))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


class Inception3(HybridBlock):
    """Inception v3 (reference: inception.py:154)."""

    def __init__(self, classes=1000, **kwargs):  # noqa: ARG002
        super().__init__()
        self.features = nn.HybridSequential()
        self.features.add(_make_basic_conv(channels=32, kernel_size=3,
                                           strides=2))
        self.features.add(_make_basic_conv(channels=32, kernel_size=3))
        self.features.add(_make_basic_conv(channels=64, kernel_size=3,
                                           padding=1))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=80, kernel_size=1))
        self.features.add(_make_basic_conv(channels=192, kernel_size=3))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_make_E())
        self.features.add(_make_E())
        self.features.add(nn.AvgPool2D(pool_size=8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        x = self.output(x.reshape((x.shape[0], -1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    """Inception v3 model (reference: inception.py:193)."""
    from . import _load_pretrained, _split_store_kwargs

    store_kw, kwargs = _split_store_kwargs(kwargs)
    net = Inception3(**kwargs)
    if pretrained:
        _load_pretrained(net, "inceptionv3", store_kw)
    return net
