"""Vision model zoo (reference: `python/mxnet/gluon/model_zoo/vision/`).

Pretrained-weight download is unavailable (no egress); `pretrained=True`
resolves against the local model_store cache (see
`model_zoo/model_store.py`) or raises with instructions.
"""


def _split_store_kwargs(kwargs):
    """Split model-store kwargs (root/device/ctx) from model kwargs."""
    store_kw = {k: kwargs.pop(k) for k in ("root", "device", "ctx")
                if k in kwargs}
    return store_kw, kwargs


def _load_pretrained(net, name, store_kw):
    """Load weights for `name` from the local model_store cache
    (`model_zoo/model_store.py`: no-egress, local-first)."""
    from ..model_store import get_model_file

    net.load_parameters(get_model_file(name, root=store_kw.get("root")),
                        device=store_kw.get("device", store_kw.get("ctx")))


from .alexnet import AlexNet, alexnet  # noqa: F401,E402
from .inception import Inception3, inception_v3  # noqa: F401
from .mobilenet import (  # noqa: F401
    MobileNet, MobileNetV2, mobilenet0_25, mobilenet0_5, mobilenet0_75,
    mobilenet1_0, mobilenet_v2_0_25, mobilenet_v2_0_5, mobilenet_v2_0_75,
    mobilenet_v2_1_0,
)
from .resnet import (  # noqa: F401
    BasicBlockV1, BasicBlockV2, BottleneckV1, BottleneckV2, ResNetV1, ResNetV2,
    get_resnet, resnet18_v1, resnet18_v2, resnet34_v1, resnet34_v2,
    resnet50_v1, resnet50_v2, resnet101_v1, resnet101_v2, resnet152_v1,
    resnet152_v2,
)
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1  # noqa: F401
from .vgg import (  # noqa: F401
    VGG, vgg11, vgg11_bn, vgg13, vgg13_bn, vgg16, vgg16_bn, vgg19, vgg19_bn,
)
from .densenet import DenseNet, densenet121, densenet161, densenet169, densenet201  # noqa: F401

_models = {
    "alexnet": alexnet,
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "inceptionv3": inception_v3,
}


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_models)}")
    return _models[name](**kwargs)
