"""VGG 11/13/16/19 ± batch-norm (reference: `gluon/model_zoo/vision/vgg.py`)."""
from ... import nn
from ...block import HybridBlock

_vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False):
        super().__init__()
        self.features = nn.HybridSequential()
        for i, num in enumerate(layers):
            for _ in range(num):
                self.features.add(nn.Conv2D(filters[i], 3, padding=1))
                if batch_norm:
                    self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(2, 2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def get_vgg(num_layers, pretrained=False, batch_norm=False, **kwargs):
    from . import _load_pretrained, _split_store_kwargs

    store_kw, kwargs = _split_store_kwargs(kwargs)
    layers, filters = _vgg_spec[num_layers]
    net = VGG(layers, filters, batch_norm=batch_norm, **kwargs)
    if pretrained:
        suffix = "_bn" if batch_norm else ""
        _load_pretrained(net, f"vgg{num_layers}{suffix}", store_kw)
    return net


def vgg11(**kwargs):
    return get_vgg(11, **kwargs)


def vgg13(**kwargs):
    return get_vgg(13, **kwargs)


def vgg16(**kwargs):
    return get_vgg(16, **kwargs)


def vgg19(**kwargs):
    return get_vgg(19, **kwargs)


def vgg11_bn(**kwargs):
    return get_vgg(11, batch_norm=True, **kwargs)


def vgg13_bn(**kwargs):
    return get_vgg(13, batch_norm=True, **kwargs)


def vgg16_bn(**kwargs):
    return get_vgg(16, batch_norm=True, **kwargs)


def vgg19_bn(**kwargs):
    return get_vgg(19, batch_norm=True, **kwargs)
