"""SqueezeNet 1.0/1.1 (reference: `gluon/model_zoo/vision/squeezenet.py`)."""
from ... import nn
from ...block import HybridBlock


def _make_fire(squeeze_channels, expand1x1_channels, expand3x3_channels):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(squeeze_channels, 1, activation="relu"))
    expand = nn.HybridConcatenate(axis=1)
    left = nn.HybridSequential()
    left.add(nn.Conv2D(expand1x1_channels, 1, activation="relu"))
    right = nn.HybridSequential()
    right.add(nn.Conv2D(expand3x3_channels, 3, padding=1, activation="relu"))
    expand.add(left)
    expand.add(right)
    out.add(expand)
    return out


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000):
        super().__init__()
        assert version in ("1.0", "1.1")
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(64, 256, 256))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(64, 256, 256))
        else:
            self.features.add(nn.Conv2D(64, 3, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(_make_fire(16, 64, 64))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(_make_fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(48, 192, 192))
            self.features.add(_make_fire(64, 256, 256))
            self.features.add(_make_fire(64, 256, 256))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def _squeezenet(version, pretrained, kwargs):
    from . import _load_pretrained, _split_store_kwargs

    store_kw, kwargs = _split_store_kwargs(kwargs)
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        _load_pretrained(net, f"squeezenet{version}", store_kw)
    return net


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, kwargs)
