from . import vision  # noqa: F401
