from . import model_store  # noqa: F401
from . import vision  # noqa: F401
