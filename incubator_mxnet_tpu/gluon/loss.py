"""Loss blocks (reference: `python/mxnet/gluon/loss.py`, 1009 LoC —
L1/L2/SigmoidBCE/SoftmaxCE/KL/CTC/Huber/Hinge/Triplet/Cosine/Poisson)."""
from __future__ import annotations

from .. import numpy_extension as npx
from ..ndarray.ndarray import NDArray, apply_op
from .block import HybridBlock

__all__ = [
    "Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss",
    "SoftmaxCrossEntropyLoss", "SoftmaxCELoss", "KLDivLoss", "HuberLoss",
    "HingeLoss", "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
    "PoissonNLLLoss", "CosineEmbeddingLoss", "CTCLoss",
]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    return label.reshape(pred.shape) if label.shape != pred.shape else label


class Loss(HybridBlock):
    def __init__(self, weight=None, batch_axis=0, **kwargs):  # noqa: ARG002
        super().__init__()
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis}, w={self._weight})"


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = (pred - label) ** 2
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class L1Loss(Loss):
    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = (pred - label).abs()
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        jnp = _jnp()
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            if pos_weight is None:
                def f(p, l):
                    return jnp.maximum(p, 0) - p * l + jnp.log1p(jnp.exp(-jnp.abs(p)))

                loss = apply_op("sigmoid_bce", f, (pred, label))
            else:
                def f(p, l, pw):
                    log_wt = (pw - 1) * l + 1
                    return (1 - l) * p + log_wt * (
                        jnp.log1p(jnp.exp(-jnp.abs(p))) + jnp.maximum(-p, 0))

                loss = apply_op("sigmoid_bce", f, (pred, label, pos_weight))
        else:
            eps = 1e-12

            def f(p, l):
                w = 1.0 if pos_weight is None else None
                del w
                return -(jnp.log(p + eps) * l + jnp.log(1 - p + eps) * (1 - l))

            loss = apply_op("sigmoid_bce", f, (pred, label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


def _sparse_softmax_ce(axis):
    """Sparse-label softmax CE with a hand-written vjp.

    Why not plain autodiff: XLA differentiates take(log_softmax) into
    several full passes over the (…, vocab) logits (materialized
    softmax, then softmax-minus-scatter, then the grad scale — measured
    ~10-19 ms/step on BERT's (B, T, 30522) MLM head). The custom
    backward emits d_logits = (exp(x - lse) - onehot(l)) · g as ONE
    elementwise fusion: a single read of the logits and a single write
    of the gradient. Forward is lse - pick (never materializes
    log-probs). The reference fuses the same pair as a softmax+pick
    kernel (`src/operator/nn/softmax.cc` SoftmaxCrossEntropy)."""
    import functools

    import jax

    jnp = _jnp()

    def _clamped(l, n):
        # take_along_axis clamps out-of-range gathers; clamp explicitly so
        # forward pick and backward onehot agree on the SAME class for
        # OOB labels (e.g. a stray -1) instead of silently dropping the
        # -onehot term from the gradient
        return jnp.clip(l.astype(jnp.int32), 0, n - 1)

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def ce(x, l):
        ax = axis % x.ndim
        lse = jax.scipy.special.logsumexp(x.astype(jnp.float32), axis=axis)
        li = jnp.expand_dims(_clamped(l, x.shape[ax]), axis)
        pick = jnp.squeeze(jnp.take_along_axis(x, li, axis=axis), axis=axis)
        return lse - pick.astype(jnp.float32)

    def fwd(x, l):
        ax = axis % x.ndim
        lse = jax.scipy.special.logsumexp(x.astype(jnp.float32), axis=axis)
        li = jnp.expand_dims(_clamped(l, x.shape[ax]), axis)
        pick = jnp.squeeze(jnp.take_along_axis(x, li, axis=axis), axis=axis)
        return lse - pick.astype(jnp.float32), (x, l, lse)

    def bwd(res, g):
        x, l, lse = res
        ax = axis % x.ndim
        p = jnp.exp(x.astype(jnp.float32)
                    - jnp.expand_dims(lse, ax))
        onehot = (jax.lax.broadcasted_iota(jnp.int32, x.shape, ax)
                  == jnp.expand_dims(_clamped(l, x.shape[ax]), ax))
        dx = (p - onehot.astype(jnp.float32)) * jnp.expand_dims(g, ax)
        if jnp.issubdtype(l.dtype, jnp.integer) \
                or jnp.issubdtype(l.dtype, jnp.bool_):
            import numpy as _onp

            dl = _onp.zeros(l.shape, jax.dtypes.float0)
        else:
            dl = jnp.zeros_like(l)
        return dx.astype(x.dtype), dl

    ce.defvjp(fwd, bwd)
    return ce


class SoftmaxCrossEntropyLoss(Loss):
    """(reference: loss.py SoftmaxCrossEntropyLoss; sparse_label picks the
    label logit; fused as one XLA graph instead of the reference's
    softmax+pick kernel pair)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        import jax

        jnp = _jnp()
        axis = self._axis
        sparse = self._sparse_label
        from_logits = self._from_logits

        def f(p, l):
            if sparse and not from_logits:
                return _sparse_softmax_ce(axis)(p, l)
            logp = p if from_logits else jax.nn.log_softmax(p, axis=axis)
            if sparse:
                li = jnp.expand_dims(l.astype(jnp.int32), axis)
                pick = jnp.take_along_axis(logp, li, axis=axis)
                return -jnp.squeeze(pick, axis=axis)
            return -jnp.sum(logp * l, axis=axis)

        loss = apply_op("softmax_ce", f, (pred, label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        jnp = _jnp()
        if not self._from_logits:
            pred = npx.log_softmax(pred, axis=self._axis)

        def f(p, l):
            return l * (jnp.log(l + 1e-12) - p)

        loss = apply_op("kldiv", f, (pred, label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        jnp = _jnp()
        label = _reshape_like(pred, label)
        rho = self._rho

        def f(p, l):
            d = jnp.abs(p - l)
            return jnp.where(d > rho, d - 0.5 * rho, (0.5 / rho) * d * d)

        loss = apply_op("huber", f, (pred, label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        jnp = _jnp()
        label = _reshape_like(pred, label)
        m = self._margin
        loss = apply_op("hinge", lambda p, l: jnp.maximum(0.0, m - p * l),
                        (pred, label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class SquaredHingeLoss(HingeLoss):
    def forward(self, pred, label, sample_weight=None):
        jnp = _jnp()
        label = _reshape_like(pred, label)
        m = self._margin
        loss = apply_op("sq_hinge",
                        lambda p, l: jnp.maximum(0.0, m - p * l) ** 2,
                        (pred, label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        jnp = _jnp()
        label = _reshape_like(pred, label)
        fmt = self._label_format

        def f(p, l):
            if fmt == "binary":
                l = 2 * l - 1
            return jnp.log1p(jnp.exp(-p * l))

        loss = apply_op("logistic", f, (pred, label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        jnp = _jnp()
        m = self._margin

        def f(p, pos, neg):
            axes = tuple(range(1, p.ndim))
            d = jnp.sum((p - pos) ** 2 - (p - neg) ** 2, axis=axes)
            return jnp.maximum(d + m, 0.0)

        loss = apply_op("triplet", f, (pred, positive, negative))
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        jnp = _jnp()
        target = _reshape_like(pred, target)
        from_logits = self._from_logits
        full = self._compute_full

        def f(p, t):
            if from_logits:
                loss = jnp.exp(p) - t * p
            else:
                loss = p - t * jnp.log(p + epsilon)
            if full:
                stirling = t * jnp.log(t + 1e-12) - t + 0.5 * jnp.log(
                    2 * jnp.pi * (t + 1e-12))
                loss = loss + jnp.where(t > 1, stirling, 0.0)
            return loss

        loss = apply_op("poisson_nll", f, (pred, target))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        jnp = _jnp()
        m = self._margin

        def f(a, b, l):
            a2 = a.reshape(a.shape[0], -1)
            b2 = b.reshape(b.shape[0], -1)
            cos = jnp.sum(a2 * b2, axis=1) / (
                jnp.linalg.norm(a2, axis=1) * jnp.linalg.norm(b2, axis=1) + 1e-12)
            lf = l.reshape(-1)
            return jnp.where(lf == 1, 1 - cos, jnp.maximum(0.0, cos - m))

        loss = apply_op("cosine_embedding", f, (input1, input2, label))
        return _apply_weighting(loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist Temporal Classification (reference: loss.py CTCLoss →
    `src/operator/nn/ctc_loss.cc`). Forward algorithm implemented as a
    lax.scan dynamic program over time — compiles to one XLA while-loop."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        import jax

        jnp = _jnp()
        layout = self._layout

        def f(p, l, pl, ll):
            if layout == "TNC":
                p = jnp.moveaxis(p, 0, 1)  # -> NTC
            N, T, C = p.shape
            L = l.shape[1]
            blank = 0
            logp = jax.nn.log_softmax(p, axis=-1)
            # extended label sequence: blank, l1, blank, l2, ... blank
            S = 2 * L + 1
            lab = l.astype(jnp.int32)
            ext = jnp.full((N, S), blank, dtype=jnp.int32)
            ext = ext.at[:, 1::2].set(lab)
            pl_ = (jnp.full((N,), T, jnp.int32) if pl is None
                   else pl.astype(jnp.int32))
            ll_ = (jnp.full((N,), L, jnp.int32) if ll is None
                   else ll.astype(jnp.int32))
            S_len = 2 * ll_ + 1
            neg_inf = -1e30
            alpha0 = jnp.full((N, S), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
            alpha0 = alpha0.at[:, 1].set(
                jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0])

            same = jnp.concatenate(
                [jnp.zeros((N, 2), bool),
                 ext[:, 2:] == ext[:, :-2]], axis=1)

            def step(alpha, t):
                lp = jnp.take_along_axis(logp[:, t, :], ext, axis=1)
                a_shift1 = jnp.concatenate(
                    [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
                a_shift2 = jnp.concatenate(
                    [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
                a_shift2 = jnp.where(same, neg_inf, a_shift2)
                m = jnp.maximum(jnp.maximum(alpha, a_shift1), a_shift2)
                new = m + jnp.log(
                    jnp.exp(alpha - m) + jnp.exp(a_shift1 - m)
                    + jnp.exp(a_shift2 - m) + 1e-38) + lp
                # freeze past pl_
                new = jnp.where((t < pl_)[:, None], new, alpha)
                return new, None

            alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
            idx_last = S_len - 1
            idx_prev = jnp.maximum(S_len - 2, 0)
            a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
            a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0]
            m = jnp.maximum(a_last, a_prev)
            ll_total = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m)
                                   + 1e-38)
            return -ll_total

        loss = apply_op("ctc", f, (pred, label, pred_lengths, label_lengths))
        return _apply_weighting(loss, self._weight, sample_weight)
