"""`gluon.probability` — distributions, bijectors and stochastic blocks
(reference: `python/mxnet/gluon/probability/__init__.py`; ~5k LoC package).

TPU-native design notes:
- densities/entropies compose autograd-aware `np` ops (tape + XLA fusion);
- samplers are single fused `jax.random` kernels recorded on the tape, with
  pathwise gradients where jax supplies them (normal/uniform family via
  location-scale, gamma/beta/dirichlet via implicit reparameterization);
- everything traces under hybridize: parameters are traced arrays, PRNG keys
  come from the traced global key stack (`random.trace_key_scope`).
"""
from .distributions import *  # noqa: F401,F403
from .transformation import *  # noqa: F401,F403
from .block import *  # noqa: F401,F403

from . import distributions, transformation, block  # noqa: F401
