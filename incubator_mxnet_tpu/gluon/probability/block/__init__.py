"""Stochastic blocks (reference:
`python/mxnet/gluon/probability/block/stochastic_block.py`)."""
from .stochastic_block import StochasticBlock, StochasticSequential  # noqa: F401

__all__ = ["StochasticBlock", "StochasticSequential"]
