"""StochasticBlock — HybridBlock with forward-phase loss accumulation
(reference: `python/mxnet/gluon/probability/block/stochastic_block.py:28-135`).

Used for Bayesian networks where the objective combines a data loss with KL
terms produced inside `forward`. The decorated forward returns
`(output, collected_losses)`; `__call__` stores the losses on the block and
hands back the plain output.
"""
from __future__ import annotations

from functools import wraps

from ...block import HybridBlock

__all__ = ["StochasticBlock", "StochasticSequential"]


class StochasticBlock(HybridBlock):
    """HybridBlock that accumulates auxiliary losses during forward."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._losses = []
        self._losscache = []
        self._flag = False  # whether collectLoss ran this call

    def add_loss(self, loss):
        self._losscache.append(loss)

    @staticmethod
    def collectLoss(func):
        """Decorator for `forward`: collects losses added via `add_loss`.

        Example::

            @StochasticBlock.collectLoss
            def forward(self, loc, scale):
                qz = mgp.Normal(loc, scale)
                pz = mgp.Normal(np.zeros_like(loc), np.ones_like(scale))
                self.add_loss(mgp.kl_divergence(qz, pz))
                return qz.sample()
        """

        @wraps(func)
        def inner(self, *args, **kwargs):
            func_out = func(self, *args, **kwargs)
            collected_loss = self._losscache
            self._losscache = []
            self._flag = True
            return (func_out, collected_loss)

        return inner

    def __call__(self, *args, **kwargs):
        self._flag = False
        was_compiled = getattr(self, "_cached_graph", None) is not None
        out = super().__call__(*args, **kwargs)
        # On a compiled replay (_CachedGraph cache hit) the Python forward —
        # and hence the collectLoss decorator — does not run, so _flag stays
        # False; the (output, losses) structure is still replayed faithfully
        # by the cached graph's pytree. The decoration check applies whenever
        # the Python forward actually ran (i.e. not a compiled replay).
        if not self._flag and not was_compiled:
            raise ValueError("The forward function should be decorated by "
                             "StochasticBlock.collectLoss")
        self._losses = list(out[1])
        return out[0]

    @property
    def losses(self):
        return self._losses


class StochasticSequential(StochasticBlock):
    """Stack of blocks, propagating child StochasticBlock losses."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._layers = []

    def add(self, *blocks):
        for block in blocks:
            self._layers.append(block)
            self.register_child(block)

    @StochasticBlock.collectLoss
    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = []
            if isinstance(x, (tuple, list)):
                args = x[1:]
                x = x[0]
        if args:
            x = tuple([x] + list(args))
        for block in self._layers:
            if getattr(block, "_losses", None):
                self.add_loss(block._losses)
        return x

    def __repr__(self):
        mods = "\n".join(f"  ({k}): {b!r}" for k, b in self._children.items())
        return f"{type(self).__name__}(\n{mods}\n)"

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)()
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)
