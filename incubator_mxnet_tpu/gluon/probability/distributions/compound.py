"""Independent and TransformedDistribution (reference:
`python/mxnet/gluon/probability/distributions/independent.py:28-100`,
`transformed_distribution.py:28-102`)."""
from __future__ import annotations

from .distribution import Distribution
from .utils import sum_right_most

__all__ = ["Independent", "TransformedDistribution"]


class Independent(Distribution):
    """Reinterpret the rightmost `reinterpreted_batch_ndims` batch dims of a
    distribution as event dims (log_prob sums over them)."""

    def __init__(self, base_distribution, reinterpreted_batch_ndims,
                 validate_args=None):
        self.base_dist = base_distribution
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)
        event_dim = (base_distribution.event_dim or 0) + self.reinterpreted_batch_ndims
        super().__init__(event_dim=event_dim, validate_args=validate_args)

    @property
    def has_grad(self):
        return self.base_dist.has_grad

    @property
    def support(self):
        return self.base_dist.support

    def log_prob(self, value):
        lp = self.base_dist.log_prob(value)
        return sum_right_most(lp, self.reinterpreted_batch_ndims)

    def sample(self, size=None):
        return self.base_dist.sample(size)

    def sample_n(self, size=None):
        return self.base_dist.sample_n(size)

    def broadcast_to(self, batch_shape):
        return Independent(self.base_dist.broadcast_to(batch_shape),
                           self.reinterpreted_batch_ndims)

    @property
    def mean(self):
        return self.base_dist.mean

    @property
    def variance(self):
        return self.base_dist.variance

    def entropy(self):
        ent = self.base_dist.entropy()
        return sum_right_most(ent, self.reinterpreted_batch_ndims)

    def __repr__(self):
        return (f"Independent({self.base_dist!r}, "
                f"{self.reinterpreted_batch_ndims})")


class TransformedDistribution(Distribution):
    """Distribution of T(X) for invertible T via change of variables:
    log p_Y(y) = log p_X(T^-1(y)) - log|det J_T(T^-1(y))|."""

    def __init__(self, base_dist, transforms, validate_args=None):
        from ..transformation import Transformation

        self._base_dist = base_dist
        if isinstance(transforms, Transformation):
            transforms = [transforms]
        self._transforms = list(transforms)
        event_dim = max([base_dist.event_dim or 0]
                        + [t.event_dim for t in self._transforms])
        super().__init__(event_dim=event_dim, validate_args=validate_args)

    @property
    def has_grad(self):
        return self._base_dist.has_grad

    def sample(self, size=None):
        x = self._base_dist.sample(size)
        for t in self._transforms:
            x = t(x)
        return x

    def sample_n(self, size=None):
        x = self._base_dist.sample_n(size)
        for t in self._transforms:
            x = t(x)
        return x

    def log_prob(self, value):
        event_dim = self.event_dim or 0
        lp = 0.0
        y = value
        for t in reversed(self._transforms):
            x = t.inv(y)
            ldj = t.log_det_jacobian(x, y)
            lp = lp - sum_right_most(ldj, event_dim - t.event_dim)
            y = x
        base_ld = self._base_dist.log_prob(y)
        lp = lp + sum_right_most(
            base_ld, event_dim - (self._base_dist.event_dim or 0))
        return lp

    def cdf(self, value):
        sign = 1
        y = value
        for t in reversed(self._transforms):
            y = t.inv(y)
            s = t.sign
            sign = sign * (s if isinstance(s, (int, float)) else 1)
        c = self._base_dist.cdf(y)
        if isinstance(sign, (int, float)) and sign < 0:
            c = 1 - c
        return c

    def icdf(self, value):
        if any((isinstance(t.sign, (int, float)) and t.sign < 0)
               for t in self._transforms):
            value = 1 - value
        x = self._base_dist.icdf(value)
        for t in self._transforms:
            x = t(x)
        return x
