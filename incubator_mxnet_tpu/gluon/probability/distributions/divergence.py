"""KL divergences (reference:
`python/mxnet/gluon/probability/distributions/divergence.py:21-360`).

Closed-form KL for the same distribution pairs the reference registers, plus
`empirical_kl` Monte-Carlo fallback. Dispatch resolves the most specific
registered (type_p, type_q) pair over the MRO, so subclasses (e.g. Chi2 →
Gamma) reuse parent formulas.
"""
from __future__ import annotations

import math

import numpy as _onp

from .compound import Independent
from .continuous import (Beta, Cauchy, Dirichlet, Exponential, Gamma, Gumbel,
                         HalfNormal, Laplace, MultivariateNormal, Normal,
                         Pareto, Uniform)
from .discrete import (Bernoulli, Binomial, Categorical, Geometric,
                       OneHotCategorical, Poisson)
from .utils import digamma, gammaln, sum_right_most

__all__ = ["register_kl", "kl_divergence", "empirical_kl"]

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    """Decorator registering a KL(p||q) implementation for a class pair."""

    def decorator(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return decorator


def _dispatch_kl(type_p, type_q):
    fn = _KL_REGISTRY.get((type_p, type_q))
    if fn is not None:
        return fn
    # most-specific match over the MRO (subclass reuses parent formula)
    best = None
    for (tp, tq), cand in _KL_REGISTRY.items():
        if issubclass(type_p, tp) and issubclass(type_q, tq):
            if best is None or (issubclass(tp, best[0][0])
                                and issubclass(tq, best[0][1])):
                best = ((tp, tq), cand)
    if best is None:
        raise NotImplementedError(
            f"KL divergence between {type_p.__name__} and "
            f"{type_q.__name__} is not implemented.")
    return best[1]


def kl_divergence(p, q):
    r"""Closed-form KL(p||q) for registered distribution pairs."""
    return _dispatch_kl(type(p), type(q))(p, q)


def empirical_kl(p, q, n_samples=1):
    r"""Monte-Carlo estimate of KL(p||q): mean of log p(x) - log q(x) over
    `n_samples` draws x ~ p."""
    from .... import numpy as np

    x = p.sample_n(n_samples)
    return np.mean(p.log_prob(x) - q.log_prob(x), axis=0)


def _np():
    from .... import numpy as np

    return np


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    np = _np()
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - np.log(var_ratio))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    from .utils import clip_prob

    np = _np()
    pp, pq = clip_prob(p.prob), clip_prob(q.prob)
    return pp * np.log(pp / pq) + (1 - pp) * np.log((1 - pp) / (1 - pq))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    from .utils import log_softmax

    np = _np()
    lp = log_softmax(p.logit, axis=-1)
    lq = log_softmax(q.logit, axis=-1)
    return np.sum(np.exp(lp) * (lp - lq), axis=-1)


@register_kl(OneHotCategorical, OneHotCategorical)
def _kl_onehot_onehot(p, q):
    return _kl_categorical_categorical(p, q)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    np = _np()
    result = np.log((q.high - q.low) / (p.high - p.low))
    bad = np.logical_or(q.low > p.low, q.high < p.high)
    return np.where(bad, np.full_like(result, _onp.inf), result)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    np = _np()
    t1 = np.log((p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2)
    t2 = np.log(4 * p.scale * q.scale)
    return t1 - t2


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    np = _np()
    scale_ratio = p.scale / q.scale
    loc_abs_diff = np.abs(p.loc - q.loc)
    return (-np.log(scale_ratio) + loc_abs_diff / q.scale
            + scale_ratio * np.exp(-loc_abs_diff / p.scale) - 1)


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    np = _np()
    return p.rate * (np.log(p.rate) - np.log(q.rate)) - (p.rate - q.rate)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    np = _np()
    return -p.entropy() - np.log1p(-q.prob) / p.prob - q.logit


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    np = _np()
    scale_ratio = p.scale / q.scale
    return -np.log(scale_ratio) + scale_ratio - 1


@register_kl(Pareto, Pareto)
def _kl_pareto_pareto(p, q):
    np = _np()
    scale_ratio = p.scale / q.scale
    alpha_ratio = q.alpha / p.alpha
    result = (q.alpha * np.log(scale_ratio) - np.log(alpha_ratio)
              + alpha_ratio - 1)
    return np.where(p.scale < q.scale, np.full_like(result, _onp.nan), result)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    np = _np()
    eg = _onp.euler_gamma
    ct1 = p.scale / q.scale
    ct2 = q.loc / q.scale
    ct3 = p.loc / q.scale
    t1 = -np.log(ct1) - ct2 + ct3
    t2 = ct1 * eg
    t3 = np.exp(ct2 + gammaln(1 + ct1) - ct3)
    return t1 + t2 + t3 - (1 + eg)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    np = _np()
    return (q.shape * np.log(q.scale / p.scale)
            + gammaln(q.shape) - gammaln(p.shape)
            + (p.shape - q.shape) * digamma(p.shape)
            + (p.shape * p.scale) * (1 / q.scale - 1 / p.scale))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    sum_p = p.beta + p.alpha
    sum_q = q.beta + q.alpha
    t1 = gammaln(q.alpha) + gammaln(q.beta) + gammaln(sum_p)
    t2 = gammaln(p.alpha) + gammaln(p.beta) + gammaln(sum_q)
    t3 = (p.beta - q.beta) * digamma(p.beta)
    t4 = (p.alpha - q.alpha) * digamma(p.alpha)
    t5 = (sum_q - sum_p) * digamma(sum_p)
    return t1 - t2 + t3 + t4 + t5


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    np = _np()
    sum_p = np.sum(p.alpha, axis=-1)
    sum_q = np.sum(q.alpha, axis=-1)
    t1 = gammaln(sum_p) - gammaln(sum_q)
    t2 = np.sum(gammaln(p.alpha) - gammaln(q.alpha), axis=-1)
    t3 = p.alpha - q.alpha
    t4 = digamma(p.alpha) - np.expand_dims(digamma(sum_p), -1)
    return t1 - t2 + np.sum(t3 * t4, axis=-1)


@register_kl(HalfNormal, HalfNormal)
def _kl_halfnormal_halfnormal(p, q):
    np = _np()
    var_ratio = (p.scale / q.scale) ** 2
    return 0.5 * (var_ratio - 1 - np.log(var_ratio))


@register_kl(Binomial, Binomial)
def _kl_binomial_binomial(p, q):
    np = _np()
    kl = p.n * (p.prob * (p.logit - q.logit)
                + np.log1p(-p.prob) - np.log1p(-q.prob))
    return np.where(p.n > q.n, np.full_like(kl, _onp.inf), kl)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    np = _np()

    def log_det(mvn):
        return np.sum(np.log(np.diagonal(mvn.scale_tril, axis1=-2, axis2=-1)),
                      axis=-1)

    term1 = log_det(q) - log_det(p)
    term2 = np.trace(np.matmul(q.precision, p.cov), axis1=-2, axis2=-1)
    diff = q.loc - p.loc
    term3 = np.einsum("...i,...i->...", diff,
                      np.einsum("...jk,...j->...k", q.precision, diff))
    n = p.loc.shape[-1]
    return 0.5 * (term2 + term3 - n) + term1


@register_kl(Uniform, Normal)
def _kl_uniform_normal(p, q):
    np = _np()
    common_term = p.high - p.low
    t1 = np.log(math.sqrt(math.pi * 2) * q.scale / common_term)
    t2 = common_term ** 2 / 12
    t3 = ((p.high + p.low - 2 * q.loc) / 2) ** 2
    return t1 + 0.5 * (t2 + t3) / (q.scale ** 2)


@register_kl(Uniform, Gumbel)
def _kl_uniform_gumbel(p, q):
    np = _np()
    common_term = q.scale / (p.high - p.low)
    high_loc_diff = (p.high - q.loc) / q.scale
    low_loc_diff = (p.low - q.loc) / q.scale
    t1 = np.log(common_term) + 0.5 * (high_loc_diff + low_loc_diff)
    t2 = common_term * (np.exp(-high_loc_diff) - np.exp(-low_loc_diff))
    return t1 - t2


@register_kl(Exponential, Gumbel)
def _kl_exponential_gumbel(p, q):
    np = _np()
    scale_rate_prod = q.scale / p.scale
    loc_scale_ratio = q.loc / q.scale
    t1 = np.log(scale_rate_prod) - 1
    t2 = np.exp(loc_scale_ratio) * scale_rate_prod / (scale_rate_prod + 1)
    t3 = 1 / scale_rate_prod
    return t1 - loc_scale_ratio + t2 + t3


@register_kl(Exponential, Normal)
def _kl_exponential_normal(p, q):
    np = _np()
    var_normal = q.variance
    rate_sqr = p.scale ** (-2)
    t1 = 0.5 * np.log(rate_sqr * var_normal * 2 * math.pi)
    t2 = 1 / rate_sqr
    t3 = q.loc * p.scale
    t4 = (q.loc ** 2) * 0.5
    return t1 - 1 + (t2 - t3 + t4) / var_normal


@register_kl(Exponential, Gamma)
def _kl_exponential_gamma(p, q):
    np = _np()
    eg = _onp.euler_gamma
    ratio = p.scale / q.scale
    return (-q.shape * np.log(ratio) + ratio + gammaln(q.shape)
            + q.shape * eg - (1 + eg))


@register_kl(Independent, Independent)
def _kl_independent_independent(p, q):
    if p.reinterpreted_batch_ndims != q.reinterpreted_batch_ndims:
        raise NotImplementedError(
            "KL between Independents with different event dims")
    kl = kl_divergence(p.base_dist, q.base_dist)
    return sum_right_most(kl, p.reinterpreted_batch_ndims)
