"""Distributions (reference: `python/mxnet/gluon/probability/distributions/`)."""
from . import constraint  # noqa: F401
from .distribution import Distribution, ExponentialFamily  # noqa: F401
from .continuous import (Beta, Cauchy, Chi2, Dirichlet, Exponential,  # noqa: F401
                         FisherSnedecor, Gamma, Gumbel, HalfCauchy,
                         HalfNormal, Laplace, MultivariateNormal, Normal,
                         Pareto, StudentT, Uniform, Weibull)
from .discrete import (Bernoulli, Binomial, Categorical, Geometric,  # noqa: F401
                       Multinomial, NegativeBinomial, OneHotCategorical,
                       Poisson, RelaxedBernoulli, RelaxedOneHotCategorical)
from .compound import Independent, TransformedDistribution  # noqa: F401
from .divergence import empirical_kl, kl_divergence, register_kl  # noqa: F401

__all__ = [
    "Distribution", "ExponentialFamily", "Normal", "Laplace", "Cauchy",
    "HalfCauchy", "HalfNormal", "Uniform", "Exponential", "Pareto", "Gamma",
    "Chi2", "FisherSnedecor", "StudentT", "Weibull", "Gumbel", "Beta",
    "Dirichlet", "MultivariateNormal", "Bernoulli", "Binomial", "Geometric",
    "NegativeBinomial", "Poisson", "Categorical", "OneHotCategorical",
    "Multinomial", "RelaxedBernoulli", "RelaxedOneHotCategorical",
    "Independent", "TransformedDistribution", "register_kl", "kl_divergence",
    "empirical_kl", "constraint",
]
