"""Shared math for `gluon.probability` (reference:
`python/mxnet/gluon/probability/distributions/utils.py:1-185`).

The reference routes special functions through npx ops; here each one is a
differentiable `apply_op_flat` wrapper over `jax.scipy.special`, so log-probs
and entropies participate in the autograd tape and fuse under jit.

Sampling helper `sample_op` records the draw on the tape with the
distribution parameters as inputs, so reparameterized (pathwise) gradients
flow: jax supplies implicit reparameterization for gamma/beta/dirichlet draws.
"""
from __future__ import annotations

from functools import cached_property  # noqa: F401  (re-export, parity name)

from ....ndarray.ndarray import NDArray, apply_op_flat
from ....random import next_key


def _special(name, jfn_name=None):
    def op(x, *rest):
        import jax.scipy.special as jsp

        fn = getattr(jsp, jfn_name or name)
        return apply_op_flat(name, fn, (x, *rest), cacheable=True)

    op.__name__ = name
    return op


gammaln = _special("gammaln")
digamma = _special("digamma")
erf = _special("erf")
erfc = _special("erfc")
erfinv = _special("erfinv")
xlogy = _special("xlogy")
xlog1py = _special("xlog1py")
expit = _special("expit")  # sigmoid
logit_fn = _special("logit")


# Module-level pure functions (NOT per-call lambdas) so the op-call jit
# cache keys on a stable jfn identity — statics ride in as kwargs.

def _betaln_fn(x, y):
    import jax.scipy.special as jsp

    return jsp.gammaln(x) + jsp.gammaln(y) - jsp.gammaln(x + y)


def betaln(a, b):
    return apply_op_flat("betaln", _betaln_fn, (a, b), cacheable=True)


def _logsumexp_fn(v, axis=-1, keepdims=False):
    import jax.scipy.special as jsp

    return jsp.logsumexp(v, axis=axis, keepdims=keepdims)


def logsumexp(x, axis=-1, keepdims=False):
    return apply_op_flat("logsumexp", _logsumexp_fn, (x,),
                         {"axis": axis, "keepdims": keepdims}, cacheable=True)


def _log_softmax_fn(v, axis=-1):
    import jax.nn as jnn

    return jnn.log_softmax(v, axis=axis)


def log_softmax(x, axis=-1):
    return apply_op_flat("log_softmax", _log_softmax_fn, (x,),
                         {"axis": axis}, cacheable=True)


def _softmax_fn(v, axis=-1):
    import jax.nn as jnn

    return jnn.softmax(v, axis=axis)


def softmax(x, axis=-1):
    return apply_op_flat("softmax", _softmax_fn, (x,), {"axis": axis},
                         cacheable=True)


def _softplus_fn(v):
    import jax.nn as jnn

    return jnn.softplus(v)


def softplus(x):
    return apply_op_flat("softplus", _softplus_fn, (x,), cacheable=True)


_EPS = 1.19e-7  # float32 machine epsilon; reference clips probs the same way


def _clip_prob_fn(p):
    import jax.numpy as jnp

    return jnp.clip(p, _EPS, 1.0 - _EPS)


def clip_prob(prob):
    return apply_op_flat("clip_prob", _clip_prob_fn, (prob,), cacheable=True)


def _prob2logit_fn(p):
    import jax.numpy as jnp

    pc = jnp.clip(p, _EPS, 1 - _EPS)
    return jnp.log(pc) - jnp.log1p(-pc)


def _prob2logit_multi_fn(p):
    import jax.numpy as jnp

    return jnp.log(jnp.clip(p, _EPS, 1.0))


def prob2logit(prob, binary=True):
    """Convert probability to logit (reference utils.py prob2logit)."""
    if binary:
        return apply_op_flat("prob2logit", _prob2logit_fn, (prob,),
                             cacheable=True)
    return apply_op_flat("prob2logit_multi", _prob2logit_multi_fn, (prob,),
                         cacheable=True)


def _sigmoid_fn(v):
    import jax.nn as jnn

    return jnn.sigmoid(v)


def logit2prob(logit, binary=True):
    if binary:
        return apply_op_flat("logit2prob", _sigmoid_fn, (logit,),
                             cacheable=True)
    return apply_op_flat("logit2prob_multi", _softmax_fn, (logit,),
                         {"axis": -1}, cacheable=True)


def _sum_right_most_fn(v, ndim=1):
    import jax.numpy as jnp

    return jnp.sum(v, axis=tuple(range(-ndim, 0)))


def sum_right_most(x, ndim):
    """Sum out the rightmost `ndim` event dims of a log-prob tensor."""
    if ndim == 0:
        return x
    return apply_op_flat("sum_right_most", _sum_right_most_fn, (x,),
                         {"ndim": ndim}, cacheable=True)


def norm_size(size):
    """Normalize a user `size` argument: None | int | tuple → None | tuple."""
    if size is None:
        return None
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def sample_op(name, fn, *params, size=None):
    """Record a random draw on the autograd tape.

    ``fn(key, size, *param_buffers) -> buffer`` where `size` is None (use the
    broadcast parameter shape) or a full output-shape tuple. The PRNG key is
    taken from the global RNG (traced-fresh under hybridize via
    `trace_key_scope`) and held in the op closure; the distribution parameters
    are tape inputs so pathwise/implicit gradients flow to them.
    """
    key = next_key()
    sz = norm_size(size)
    return apply_op_flat(name, lambda *p: fn(key, sz, *p), params)


def as_ndarray(x, dtype=None):
    if isinstance(x, NDArray):
        return x if dtype is None else x.astype(dtype)
    return NDArray(x, dtype=dtype or "float32")


def promote_param(x):
    """Scalars stay Python numbers (cheap broadcasting); arrays become NDArray."""
    from numbers import Number

    if isinstance(x, Number):
        return x
    return as_ndarray(x)


def pshape(x):
    """Shape of a parameter that may be a Python scalar."""
    return getattr(x, "shape", ())


def broadcast_param(x, batch_shape):
    from ....numpy import broadcast_to as _bto

    if isinstance(x, NDArray):
        return _bto(x, batch_shape)
    import numpy as onp

    return as_ndarray(onp.broadcast_to(onp.asarray(x, dtype="float32"), batch_shape))
