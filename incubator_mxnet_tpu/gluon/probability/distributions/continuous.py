"""Continuous distributions (reference:
`python/mxnet/gluon/probability/distributions/{normal,laplace,cauchy,
half_cauchy,half_normal,uniform,exponential,pareto,gamma,chi2,
fishersnedecor,studentT,weibull,gumbel,beta,dirichlet,
multivariate_normal}.py`).

Each `sample` is a single fused `apply_op_flat` draw over `jax.random`
(pathwise/implicit-reparameterized where jax provides it — normal, uniform,
gamma, beta, dirichlet), so sampling is one XLA kernel and gradients flow to
the parameters through the tape. Densities compose autograd-aware `np` ops.
"""
from __future__ import annotations

import math

from . import constraint as C
from .distribution import Distribution, ExponentialFamily
from .utils import (as_ndarray, betaln, broadcast_param, digamma, erf, erfinv,
                    gammaln, norm_size, sample_op)

__all__ = [
    "Normal", "Laplace", "Cauchy", "HalfCauchy", "HalfNormal", "Uniform",
    "Exponential", "Pareto", "Gamma", "Chi2", "FisherSnedecor", "StudentT",
    "Weibull", "Gumbel", "Beta", "Dirichlet", "MultivariateNormal",
]

_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


def _np():
    from .... import numpy as np

    return np


def _bshape(*params):
    import jax.numpy as jnp

    return jnp.broadcast_shapes(*[getattr(p, "shape", ()) for p in params])


class Normal(ExponentialFamily):
    """Gaussian distribution (reference normal.py:30-160)."""

    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "scale": C.Positive()}

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        self.loc = as_ndarray(loc)
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        z = (value - self.loc) / self.scale
        return -0.5 * z * z - np.log(self.scale) - _HALF_LOG_2PI

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, loc, scale):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(loc), jnp.shape(scale))
            return loc + scale * jr.normal(key, shape, dtype=jnp.result_type(
                loc, scale, jnp.float32))

        return sample_op("normal_sample", fn, self.loc, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.loc, self.scale))

    def broadcast_to(self, batch_shape):
        return Normal(broadcast_param(self.loc, batch_shape),
                      broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        import math as m

        return 0.5 * (1.0 + erf((value - self.loc) / (self.scale * m.sqrt(2))))

    def icdf(self, value):
        import math as m

        return self.loc + self.scale * m.sqrt(2) * erfinv(2 * value - 1)

    @property
    def mean(self):
        return self.loc

    @property
    def stddev(self):
        return self.scale

    @property
    def variance(self):
        return self.scale ** 2

    @property
    def _natural_params(self):
        return (self.loc / self.scale ** 2, -0.5 / self.scale ** 2)

    _mean_carrier_measure = -_HALF_LOG_2PI  # E[log h(x)] = -log sqrt(2*pi)

    def _log_normalizer(self, x, y):
        import jax.numpy as jnp

        return -0.25 * x ** 2 / y - 0.5 * jnp.log(-2.0 * y)


class Laplace(Distribution):
    """Laplace distribution (reference laplace.py)."""

    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "scale": C.Positive()}

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        self.loc = as_ndarray(loc)
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        return -np.abs(value - self.loc) / self.scale - np.log(2 * self.scale)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, loc, scale):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(loc), jnp.shape(scale))
            return loc + scale * jr.laplace(key, shape)

        return sample_op("laplace_sample", fn, self.loc, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.loc, self.scale))

    def broadcast_to(self, batch_shape):
        return Laplace(broadcast_param(self.loc, batch_shape),
                       broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        np = _np()
        z = (value - self.loc) / self.scale
        return 0.5 - 0.5 * np.sign(z) * np.expm1(-np.abs(z))

    def icdf(self, value):
        np = _np()
        u = value - 0.5
        return self.loc - self.scale * np.sign(u) * np.log1p(-2 * np.abs(u))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2 * self.scale ** 2

    def entropy(self):
        np = _np()
        return 1.0 + np.log(2 * self.scale)


class Cauchy(Distribution):
    """Cauchy distribution (reference cauchy.py)."""

    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "scale": C.Positive()}

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        self.loc = as_ndarray(loc)
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        z = (value - self.loc) / self.scale
        return -math.log(math.pi) - np.log(self.scale) - np.log1p(z * z)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, loc, scale):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(loc), jnp.shape(scale))
            return loc + scale * jr.cauchy(key, shape)

        return sample_op("cauchy_sample", fn, self.loc, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.loc, self.scale))

    def broadcast_to(self, batch_shape):
        return Cauchy(broadcast_param(self.loc, batch_shape),
                      broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        np = _np()
        return np.arctan((value - self.loc) / self.scale) / math.pi + 0.5

    def icdf(self, value):
        np = _np()
        return self.loc + self.scale * np.tan(math.pi * (value - 0.5))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    def entropy(self):
        np = _np()
        return math.log(4 * math.pi) + np.log(self.scale)


class HalfCauchy(Distribution):
    """|X| for X ~ Cauchy(0, scale) (reference half_cauchy.py)."""

    has_grad = True
    support = C.NonNegative()
    arg_constraints = {"scale": C.Positive()}

    def __init__(self, scale=1.0, validate_args=None):
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        z = value / self.scale
        return math.log(2 / math.pi) - np.log(self.scale) - np.log1p(z * z)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, scale):
            shape = sz if sz is not None else jnp.shape(scale)
            return jnp.abs(scale * jr.cauchy(key, shape))

        return sample_op("half_cauchy_sample", fn, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.scale))

    def broadcast_to(self, batch_shape):
        return HalfCauchy(broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        np = _np()
        return 2.0 / math.pi * np.arctan(value / self.scale)

    def icdf(self, value):
        np = _np()
        return self.scale * np.tan(math.pi * value / 2)

    @property
    def mean(self):
        raise ValueError("HalfCauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("HalfCauchy distribution has no variance")

    def entropy(self):
        np = _np()
        return math.log(2 * math.pi) + np.log(self.scale)


class HalfNormal(Distribution):
    """|X| for X ~ Normal(0, scale) (reference half_normal.py)."""

    has_grad = True
    support = C.NonNegative()
    arg_constraints = {"scale": C.Positive()}

    def __init__(self, scale=1.0, validate_args=None):
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        z = value / self.scale
        return 0.5 * math.log(2 / math.pi) - np.log(self.scale) - 0.5 * z * z

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, scale):
            shape = sz if sz is not None else jnp.shape(scale)
            return jnp.abs(scale * jr.normal(key, shape))

        return sample_op("half_normal_sample", fn, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.scale))

    def broadcast_to(self, batch_shape):
        return HalfNormal(broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        return erf(value / (self.scale * math.sqrt(2)))

    def icdf(self, value):
        return self.scale * math.sqrt(2) * erfinv(value)

    @property
    def mean(self):
        return self.scale * math.sqrt(2 / math.pi)

    @property
    def variance(self):
        return self.scale ** 2 * (1 - 2 / math.pi)

    def entropy(self):
        np = _np()
        return 0.5 * math.log(math.pi / 2) + 0.5 + np.log(self.scale)


class Uniform(Distribution):
    """Uniform distribution on [low, high) (reference uniform.py)."""

    has_grad = True
    arg_constraints = {"low": C.Real(), "high": C.Real()}

    def __init__(self, low=0.0, high=1.0, validate_args=None):
        self.low = as_ndarray(low)
        self.high = as_ndarray(high)
        self.support = C.Interval(low, high)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        lp = -np.log(self.high - self.low)
        inside = np.logical_and(value >= self.low, value < self.high)
        return np.where(inside, lp, np.full_like(lp + value, -np.inf))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, low, high):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(low), jnp.shape(high))
            return low + (high - low) * jr.uniform(key, shape)

        return sample_op("uniform_sample", fn, self.low, self.high, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.low, self.high))

    def broadcast_to(self, batch_shape):
        return Uniform(broadcast_param(self.low, batch_shape),
                       broadcast_param(self.high, batch_shape))

    def cdf(self, value):
        np = _np()
        return np.clip((value - self.low) / (self.high - self.low), 0.0, 1.0)

    def icdf(self, value):
        return self.low + value * (self.high - self.low)

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return (self.high - self.low) ** 2 / 12

    def entropy(self):
        np = _np()
        return np.log(self.high - self.low)


class Exponential(ExponentialFamily):
    """Exponential distribution with mean `scale` (reference exponential.py)."""

    has_grad = True
    support = C.NonNegative()
    arg_constraints = {"scale": C.Positive()}

    def __init__(self, scale=1.0, validate_args=None):
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        return -np.log(self.scale) - value / self.scale

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, scale):
            shape = sz if sz is not None else jnp.shape(scale)
            return scale * jr.exponential(key, shape)

        return sample_op("exponential_sample", fn, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.scale))

    def broadcast_to(self, batch_shape):
        return Exponential(broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        np = _np()
        return -np.expm1(-value / self.scale)

    def icdf(self, value):
        np = _np()
        return -self.scale * np.log1p(-value)

    @property
    def mean(self):
        return self.scale

    @property
    def variance(self):
        return self.scale ** 2

    def entropy(self):
        np = _np()
        return 1.0 + np.log(self.scale)

    @property
    def _natural_params(self):
        return (-1.0 / self.scale,)

    def _log_normalizer(self, x):
        import jax.numpy as jnp

        return -jnp.log(-x)


class Pareto(Distribution):
    """Pareto Type I (reference pareto.py:31-120, built there as
    TransformedDistribution(Exponential, [Exp, Affine]); here closed-form)."""

    has_grad = True
    arg_constraints = {"alpha": C.Positive(), "scale": C.Positive()}

    def __init__(self, alpha, scale=1.0, validate_args=None):
        self.alpha = as_ndarray(alpha)
        self.scale = as_ndarray(scale)
        self.support = C.GreaterThanEq(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        return (np.log(self.alpha) + self.alpha * np.log(self.scale)
                - (self.alpha + 1) * np.log(value))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, alpha, scale):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(alpha), jnp.shape(scale))
            u = jr.uniform(key, shape, minval=1e-7, maxval=1.0)
            return scale * u ** (-1.0 / alpha)

        return sample_op("pareto_sample", fn, self.alpha, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.alpha, self.scale))

    def broadcast_to(self, batch_shape):
        return Pareto(broadcast_param(self.alpha, batch_shape),
                      broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        np = _np()
        return 1.0 - (self.scale / value) ** self.alpha

    def icdf(self, value):
        return self.scale * (1.0 - value) ** (-1.0 / self.alpha)

    @property
    def mean(self):
        np = _np()
        a = np.clip(self.alpha, 1.0, None)
        return np.where(self.alpha > 1, a * self.scale / (a - 1),
                        np.full_like(self.alpha, np.inf))

    @property
    def variance(self):
        np = _np()
        a = np.clip(self.alpha, 2.0, None)
        v = self.scale ** 2 * a / ((a - 1) ** 2 * (a - 2))
        return np.where(self.alpha > 2, v, np.full_like(self.alpha, np.inf))

    def entropy(self):
        np = _np()
        return (np.log(self.scale / self.alpha) + 1.0 + 1.0 / self.alpha)


class Gamma(ExponentialFamily):
    """Gamma(shape k, scale θ) (reference gamma.py:30-140). Sampling uses
    jax's implicitly-reparameterized gamma, so d(sample)/d(shape) exists."""

    has_grad = True
    support = C.Positive()
    arg_constraints = {"shape": C.Positive(), "scale": C.Positive()}

    def __init__(self, shape, scale=1.0, validate_args=None):
        self.shape = as_ndarray(shape)
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        return ((self.shape - 1) * np.log(value) - value / self.scale
                - gammaln(self.shape) - self.shape * np.log(self.scale))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, a, scale):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(a), jnp.shape(scale))
            return scale * jr.gamma(key, jnp.broadcast_to(a, shape))

        return sample_op("gamma_sample", fn, self.shape, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.shape, self.scale))

    def broadcast_to(self, batch_shape):
        return Gamma(broadcast_param(self.shape, batch_shape),
                     broadcast_param(self.scale, batch_shape))

    @property
    def mean(self):
        return self.shape * self.scale

    @property
    def variance(self):
        return self.shape * self.scale ** 2

    def entropy(self):
        np = _np()
        return (self.shape + np.log(self.scale) + gammaln(self.shape)
                + (1 - self.shape) * digamma(self.shape))

    @property
    def _natural_params(self):
        return (self.shape - 1, -1.0 / self.scale)

    def _log_normalizer(self, x, y):
        import jax.scipy.special as jsp
        import jax.numpy as jnp

        return jsp.gammaln(x + 1) + (x + 1) * jnp.log(-1.0 / y)


class Chi2(Gamma):
    """Chi-squared: Gamma(df/2, 2) (reference chi2.py:27-50)."""

    arg_constraints = {"df": C.Positive()}

    def __init__(self, df, validate_args=None):
        self.df = as_ndarray(df)
        super().__init__(self.df / 2, 2.0, validate_args=validate_args)

    def broadcast_to(self, batch_shape):
        return Chi2(broadcast_param(self.df, batch_shape))


class FisherSnedecor(Distribution):
    """F-distribution (reference fishersnedecor.py:32-130)."""

    has_grad = True
    support = C.Positive()
    arg_constraints = {"df1": C.Positive(), "df2": C.Positive()}

    def __init__(self, df1, df2, validate_args=None):
        self.df1 = as_ndarray(df1)
        self.df2 = as_ndarray(df2)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        d1, d2 = self.df1, self.df2
        return (0.5 * d1 * np.log(d1) + 0.5 * d2 * np.log(d2)
                + (0.5 * d1 - 1) * np.log(value)
                - 0.5 * (d1 + d2) * np.log(d2 + d1 * value)
                - betaln(0.5 * d1, 0.5 * d2))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, d1, d2):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(d1), jnp.shape(d2))
            k1, k2 = jr.split(key)
            g1 = jr.gamma(k1, jnp.broadcast_to(d1 / 2, shape)) * 2 / d1
            g2 = jr.gamma(k2, jnp.broadcast_to(d2 / 2, shape)) * 2 / d2
            return g1 / g2

        return sample_op("f_sample", fn, self.df1, self.df2, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.df1, self.df2))

    def broadcast_to(self, batch_shape):
        return FisherSnedecor(broadcast_param(self.df1, batch_shape),
                              broadcast_param(self.df2, batch_shape))

    @property
    def mean(self):
        np = _np()
        d2 = np.clip(self.df2, 2.001, None)
        return np.where(self.df2 > 2, d2 / (d2 - 2),
                        np.full_like(self.df2, np.nan))

    @property
    def variance(self):
        np = _np()
        d1, d2 = self.df1, np.clip(self.df2, 4.001, None)
        v = 2 * d2 ** 2 * (d1 + d2 - 2) / (d1 * (d2 - 2) ** 2 * (d2 - 4))
        return np.where(self.df2 > 4, v, np.full_like(self.df2, np.nan))


class StudentT(Distribution):
    """Student's t (reference studentT.py:31-130)."""

    has_grad = True
    support = C.Real()
    arg_constraints = {"df": C.Positive(), "loc": C.Real(),
                       "scale": C.Positive()}

    def __init__(self, df, loc=0.0, scale=1.0, validate_args=None):
        self.df = as_ndarray(df)
        self.loc = as_ndarray(loc)
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        z = (value - self.loc) / self.scale
        return (gammaln(0.5 * (self.df + 1)) - gammaln(0.5 * self.df)
                - 0.5 * np.log(self.df * math.pi) - np.log(self.scale)
                - 0.5 * (self.df + 1) * np.log1p(z * z / self.df))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, df, loc, scale):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(df), jnp.shape(loc), jnp.shape(scale))
            return loc + scale * jr.t(key, jnp.broadcast_to(df, shape), shape)

        return sample_op("t_sample", fn, self.df, self.loc, self.scale,
                         size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.df, self.loc, self.scale))

    def broadcast_to(self, batch_shape):
        return StudentT(broadcast_param(self.df, batch_shape),
                        broadcast_param(self.loc, batch_shape),
                        broadcast_param(self.scale, batch_shape))

    @property
    def mean(self):
        np = _np()
        return np.where(self.df > 1, self.loc + np.zeros_like(self.df),
                        np.full_like(self.df, np.nan))

    @property
    def variance(self):
        np = _np()
        df = np.clip(self.df, 2.001, None)
        v = self.scale ** 2 * df / (df - 2)
        inf = np.full_like(self.df, np.inf)
        nan = np.full_like(self.df, np.nan)
        return np.where(self.df > 2, v, np.where(self.df > 1, inf, nan))

    def entropy(self):
        np = _np()
        h = 0.5 * (self.df + 1)
        return (h * (digamma(h) - digamma(0.5 * self.df))
                + 0.5 * np.log(self.df) + betaln(0.5 * self.df, 0.5)
                + np.log(self.scale))


class Weibull(Distribution):
    """Weibull(concentration k, scale λ) (reference weibull.py:33-77, built
    there as a transformed Exponential; here closed-form)."""

    has_grad = True
    support = C.Positive()
    arg_constraints = {"concentration": C.Positive(), "scale": C.Positive()}

    def __init__(self, concentration, scale=1.0, validate_args=None):
        self.concentration = as_ndarray(concentration)
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        k, lam = self.concentration, self.scale
        z = value / lam
        return np.log(k / lam) + (k - 1) * np.log(z) - z ** k

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, k, lam):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(k), jnp.shape(lam))
            e = jr.exponential(key, shape)
            return lam * e ** (1.0 / k)

        return sample_op("weibull_sample", fn, self.concentration, self.scale,
                         size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.concentration, self.scale))

    def broadcast_to(self, batch_shape):
        return Weibull(broadcast_param(self.concentration, batch_shape),
                       broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        np = _np()
        return -np.expm1(-(value / self.scale) ** self.concentration)

    def icdf(self, value):
        np = _np()
        return self.scale * (-np.log1p(-value)) ** (1.0 / self.concentration)

    @property
    def mean(self):
        np = _np()
        return self.scale * np.exp(gammaln(1 + 1.0 / self.concentration))

    @property
    def variance(self):
        np = _np()
        g2 = np.exp(gammaln(1 + 2.0 / self.concentration))
        g1 = np.exp(gammaln(1 + 1.0 / self.concentration))
        return self.scale ** 2 * (g2 - g1 ** 2)

    def entropy(self):
        np = _np()
        return (np.euler_gamma * (1 - 1.0 / self.concentration)
                + np.log(self.scale / self.concentration) + 1.0)


class Gumbel(Distribution):
    """Gumbel (type-I extreme value) (reference gumbel.py)."""

    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real(), "scale": C.Positive()}

    def __init__(self, loc=0.0, scale=1.0, validate_args=None):
        self.loc = as_ndarray(loc)
        self.scale = as_ndarray(scale)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        z = (value - self.loc) / self.scale
        return -z - np.exp(-z) - np.log(self.scale)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, loc, scale):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(loc), jnp.shape(scale))
            return loc + scale * jr.gumbel(key, shape)

        return sample_op("gumbel_sample", fn, self.loc, self.scale, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.loc, self.scale))

    def broadcast_to(self, batch_shape):
        return Gumbel(broadcast_param(self.loc, batch_shape),
                      broadcast_param(self.scale, batch_shape))

    def cdf(self, value):
        np = _np()
        return np.exp(-np.exp(-(value - self.loc) / self.scale))

    def icdf(self, value):
        np = _np()
        return self.loc - self.scale * np.log(-np.log(value))

    @property
    def mean(self):
        np = _np()
        return self.loc + self.scale * np.euler_gamma

    @property
    def variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2

    def entropy(self):
        np = _np()
        return np.log(self.scale) + 1.0 + np.euler_gamma


class Beta(Distribution):
    """Beta distribution (reference beta.py). jax.random.beta is implicitly
    reparameterized (built on gamma), so pathwise gradients flow."""

    has_grad = True
    support = C.UnitInterval()
    arg_constraints = {"alpha": C.Positive(), "beta": C.Positive()}

    def __init__(self, alpha, beta, validate_args=None):
        self.alpha = as_ndarray(alpha)
        self.beta = as_ndarray(beta)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        return ((self.alpha - 1) * np.log(value)
                + (self.beta - 1) * np.log1p(-value)
                - betaln(self.alpha, self.beta))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, a, b):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(a), jnp.shape(b))
            return jr.beta(key, a, b, shape)

        return sample_op("beta_sample", fn, self.alpha, self.beta, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.alpha, self.beta))

    def broadcast_to(self, batch_shape):
        return Beta(broadcast_param(self.alpha, batch_shape),
                    broadcast_param(self.beta, batch_shape))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s ** 2 * (s + 1))

    def entropy(self):
        a, b = self.alpha, self.beta
        return (betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    """Dirichlet distribution over the simplex (reference dirichlet.py)."""

    has_grad = True
    support = C.Simplex()
    arg_constraints = {"alpha": C.Positive()}

    def __init__(self, alpha, validate_args=None):
        self.alpha = as_ndarray(alpha)
        super().__init__(event_dim=1, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        return (np.sum((self.alpha - 1) * np.log(value), axis=-1)
                - np.sum(gammaln(self.alpha), axis=-1)
                + gammaln(np.sum(self.alpha, axis=-1)))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, a):
            batch = sz if sz is not None else jnp.shape(a)[:-1]
            a_b = jnp.broadcast_to(a, tuple(batch) + (jnp.shape(a)[-1],))
            g = jr.gamma(key, a_b)
            return g / jnp.sum(g, axis=-1, keepdims=True)

        return sample_op("dirichlet_sample", fn, self.alpha, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.alpha)[:-1])

    def broadcast_to(self, batch_shape):
        k = self.alpha.shape[-1]
        return Dirichlet(broadcast_param(self.alpha, tuple(batch_shape) + (k,)))

    @property
    def mean(self):
        np = _np()
        return self.alpha / np.sum(self.alpha, axis=-1, keepdims=True)

    @property
    def variance(self):
        np = _np()
        a0 = np.sum(self.alpha, axis=-1, keepdims=True)
        m = self.alpha / a0
        return m * (1 - m) / (a0 + 1)

    def entropy(self):
        np = _np()
        a = self.alpha
        a0 = np.sum(a, axis=-1)
        k = a.shape[-1]
        return (np.sum(gammaln(a), axis=-1) - gammaln(a0)
                + (a0 - k) * digamma(a0)
                - np.sum((a - 1) * digamma(a), axis=-1))


class MultivariateNormal(Distribution):
    """Multivariate Gaussian (reference multivariate_normal.py:30-220).
    One of cov / precision / scale_tril parameterizes it; internally a single
    fused cholesky-based kernel computes log_prob/sample — the TPU-friendly
    formulation (triangular solves on the MXU instead of explicit inverses).
    """

    has_grad = True
    support = C.Real()
    arg_constraints = {"loc": C.Real()}

    def __init__(self, loc, cov=None, precision=None, scale_tril=None,
                 validate_args=None):
        given = sum(p is not None for p in (cov, precision, scale_tril))
        if given != 1:
            raise ValueError(
                "Exactly one of cov, precision or scale_tril must be given")
        self.loc = as_ndarray(loc)
        self._cov = as_ndarray(cov) if cov is not None else None
        self._precision = as_ndarray(precision) if precision is not None else None
        self._scale_tril_arg = (as_ndarray(scale_tril)
                                if scale_tril is not None else None)
        super().__init__(event_dim=1, validate_args=validate_args)

    @property
    def scale_tril(self):
        from ....ndarray.ndarray import apply_op_flat

        if self._scale_tril_arg is not None:
            return self._scale_tril_arg
        if self._cov is not None:
            import jax.numpy as jnp

            return apply_op_flat("mvn_chol", jnp.linalg.cholesky, (self._cov,))
        import jax.numpy as jnp
        import jax.scipy.linalg as jsl

        def prec_to_tril(p):
            # L of cov from cholesky of precision: cov = inv(P); stay
            # solve-based (triangular solves tile well on the MXU).
            lp = jnp.linalg.cholesky(p)
            ident = jnp.broadcast_to(
                jnp.eye(p.shape[-1], dtype=p.dtype), p.shape)
            linv = jsl.solve_triangular(lp, ident, lower=True)
            return jnp.linalg.cholesky(jnp.swapaxes(linv, -1, -2) @ linv)

        from ....ndarray.ndarray import apply_op_flat as _aof

        return _aof("mvn_prec_tril", prec_to_tril, (self._precision,))

    @property
    def cov(self):
        if self._cov is not None:
            return self._cov
        np = _np()
        lt = self.scale_tril
        return np.matmul(lt, np.swapaxes(lt, -1, -2))

    @property
    def precision(self):
        if self._precision is not None:
            return self._precision
        from ....ndarray.ndarray import apply_op_flat

        import jax.numpy as jnp

        return apply_op_flat("mvn_precision",
                             lambda c: jnp.linalg.inv(c), (self.cov,))

    def log_prob(self, value):
        import jax.numpy as jnp
        import jax.scipy.linalg as jsl

        from ....ndarray.ndarray import apply_op_flat

        def lp(loc, lt, x):
            d = x - loc
            lt = jnp.broadcast_to(lt, d.shape[:-1] + lt.shape[-2:])
            # solve L z = d  → Mahalanobis = |z|^2; batched triangular solve
            z = jsl.solve_triangular(lt, d[..., None], lower=True)[..., 0]
            maha = jnp.sum(z * z, axis=-1)
            logdet = jnp.sum(
                jnp.log(jnp.diagonal(lt, axis1=-2, axis2=-1)), axis=-1)
            k = x.shape[-1]
            return -0.5 * maha - logdet - 0.5 * k * math.log(2 * math.pi)

        return apply_op_flat("mvn_log_prob", lp,
                             (self.loc, self.scale_tril, value))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, loc, lt):
            batch = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(loc)[:-1], jnp.shape(lt)[:-2])
            k = jnp.shape(loc)[-1]
            eps = jr.normal(key, tuple(batch) + (k,))
            return loc + jnp.einsum("...ij,...j->...i", lt, eps)

        return sample_op("mvn_sample", fn, self.loc, self.scale_tril,
                         size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.loc)[:-1])

    def broadcast_to(self, batch_shape):
        k = self.loc.shape[-1]
        return MultivariateNormal(
            broadcast_param(self.loc, tuple(batch_shape) + (k,)),
            scale_tril=broadcast_param(self.scale_tril,
                                       tuple(batch_shape) + (k, k)))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        np = _np()
        return np.sum(self.scale_tril ** 2, axis=-1)

    def entropy(self):
        np = _np()
        k = self.loc.shape[-1]
        logdet = np.sum(np.log(np.diagonal(self.scale_tril,
                                           axis1=-2, axis2=-1)), axis=-1)
        return 0.5 * k * (1 + math.log(2 * math.pi)) + logdet
