"""Discrete distributions (reference:
`python/mxnet/gluon/probability/distributions/{bernoulli,binomial,geometric,
negative_binomial,poisson,categorical,one_hot_categorical,multinomial,
relaxed_bernoulli,relaxed_one_hot_categorical}.py`).

Dual prob/logit parameterization with lazily-derived twins (cached_property),
mirroring the reference's utils.prob2logit/logit2prob contract. Samplers are
single fused `jax.random` kernels; relaxed (Gumbel-softmax) variants carry
pathwise gradients for variational training.
"""
from __future__ import annotations

import math

from . import constraint as C
from .distribution import Distribution, ExponentialFamily
from .utils import (as_ndarray, broadcast_param, cached_property, clip_prob,
                    gammaln, log_softmax, logit2prob, norm_size, prob2logit,
                    sample_op, softmax, softplus, xlogy)

__all__ = [
    "Bernoulli", "Binomial", "Geometric", "NegativeBinomial", "Poisson",
    "Categorical", "OneHotCategorical", "Multinomial", "RelaxedBernoulli",
    "RelaxedOneHotCategorical",
]


def _np():
    from .... import numpy as np

    return np


def _bshape(*params):
    import jax.numpy as jnp

    return jnp.broadcast_shapes(*[getattr(p, "shape", ()) for p in params])


class _DualParam(Distribution):
    """Shared prob/logit dual parameterization (binary=True → sigmoid link,
    False → softmax link over the trailing axis)."""

    _binary = True

    def __init__(self, prob=None, logit=None, event_dim=0, validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError("Either `prob` or `logit` must be specified "
                             "(but not both).")
        if prob is not None:
            self.prob = as_ndarray(prob)
        else:
            self.logit = as_ndarray(logit)
        super().__init__(event_dim=event_dim, validate_args=validate_args)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, self._binary)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, self._binary)


class Bernoulli(_DualParam, ExponentialFamily):
    """Bernoulli distribution (reference bernoulli.py:29-150)."""

    support = C.Boolean()
    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}
    has_enumerate_support = True

    def __init__(self, prob=None, logit=None, validate_args=None):
        super().__init__(prob=prob, logit=logit, event_dim=0,
                         validate_args=validate_args)

    def log_prob(self, value):
        self._validate_samples(value)
        # value*logit - softplus(logit): numerically-stable BCE form
        return value * self.logit - softplus(self.logit)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, p):
            shape = sz if sz is not None else jnp.shape(p)
            return jr.bernoulli(key, p, shape).astype(jnp.float32)

        return sample_op("bernoulli_sample", fn, self.prob, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.prob))

    def broadcast_to(self, batch_shape):
        return Bernoulli(prob=broadcast_param(self.prob, batch_shape))

    def enumerate_support(self):
        np = _np()
        shape = (2,) + tuple(_bshape(self.prob))
        import numpy as onp

        vals = onp.zeros(shape, dtype="float32")
        vals[1] = 1.0
        return np.array(vals)

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        return self.prob * (1 - self.prob)

    def entropy(self):
        p = clip_prob(self.prob)
        return -(xlogy(p, p) + xlogy(1 - p, 1 - p))

    @property
    def _natural_params(self):
        return (self.logit,)

    def _log_normalizer(self, x):
        import jax.nn as jnn

        return jnn.softplus(x)


class Geometric(_DualParam):
    """Number of failures before first success (reference geometric.py)."""

    support = C.NonNegativeInteger()
    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}

    def __init__(self, prob=None, logit=None, validate_args=None):
        super().__init__(prob=prob, logit=logit, event_dim=0,
                         validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        p = clip_prob(self.prob)
        return value * np.log1p(-p) + np.log(p)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, p):
            shape = sz if sz is not None else jnp.shape(p)
            u = jr.uniform(key, shape, minval=1e-7, maxval=1.0)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))

        return sample_op("geometric_sample", fn, self.prob, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.prob))

    def broadcast_to(self, batch_shape):
        return Geometric(prob=broadcast_param(self.prob, batch_shape))

    @property
    def mean(self):
        return (1 - self.prob) / self.prob

    @property
    def variance(self):
        return (1 - self.prob) / self.prob ** 2

    def entropy(self):
        p = clip_prob(self.prob)
        return -(xlogy(p, p) + xlogy(1 - p, 1 - p)) / p


class Binomial(_DualParam):
    """Binomial(n, p) (reference binomial.py:30-170)."""

    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}

    def __init__(self, n=1, prob=None, logit=None, validate_args=None):
        self.n = as_ndarray(n)
        self.support = C.IntegerInterval(0, n)
        super().__init__(prob=prob, logit=logit, event_dim=0,
                         validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        p = clip_prob(self.prob)
        binomln = (gammaln(self.n + 1) - gammaln(value + 1)
                   - gammaln(self.n - value + 1))
        return binomln + xlogy(value, p) + xlogy(self.n - value, 1 - p)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, n, p):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(n), jnp.shape(p))
            return jr.binomial(key, jnp.broadcast_to(n, shape),
                               jnp.broadcast_to(p, shape)).astype(jnp.float32)

        return sample_op("binomial_sample", fn, self.n, self.prob, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.n, self.prob))

    def broadcast_to(self, batch_shape):
        return Binomial(broadcast_param(self.n, batch_shape),
                        prob=broadcast_param(self.prob, batch_shape))

    @property
    def mean(self):
        return self.n * self.prob

    @property
    def variance(self):
        return self.n * self.prob * (1 - self.prob)


class NegativeBinomial(_DualParam):
    """Number of successes before `n` failures (reference
    negative_binomial.py:32-140)."""

    support = C.NonNegativeInteger()
    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}

    def __init__(self, n, prob=None, logit=None, validate_args=None):
        self.n = as_ndarray(n)
        super().__init__(prob=prob, logit=logit, event_dim=0,
                         validate_args=validate_args)

    def log_prob(self, value):
        self._validate_samples(value)
        p = clip_prob(self.prob)
        comb = (gammaln(value + self.n) - gammaln(value + 1)
                - gammaln(self.n))
        return comb + xlogy(self.n, 1 - p) + xlogy(value, p)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, n, p):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(n), jnp.shape(p))
            k1, k2 = jr.split(key)
            # gamma-poisson mixture
            lam = jr.gamma(k1, jnp.broadcast_to(n, shape)) * p / (1 - p)
            return jr.poisson(k2, lam).astype(jnp.float32)

        return sample_op("negbinomial_sample", fn, self.n, self.prob,
                         size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.n, self.prob))

    def broadcast_to(self, batch_shape):
        return NegativeBinomial(broadcast_param(self.n, batch_shape),
                                prob=broadcast_param(self.prob, batch_shape))

    @property
    def mean(self):
        return self.n * self.prob / (1 - self.prob)

    @property
    def variance(self):
        return self.n * self.prob / (1 - self.prob) ** 2


class Poisson(ExponentialFamily):
    """Poisson distribution (reference poisson.py:30-120)."""

    support = C.NonNegativeInteger()
    arg_constraints = {"rate": C.Positive()}

    def __init__(self, rate=1.0, validate_args=None):
        self.rate = as_ndarray(rate)
        super().__init__(event_dim=0, validate_args=validate_args)

    def log_prob(self, value):
        np = _np()
        self._validate_samples(value)
        return xlogy(value, self.rate) - self.rate - gammaln(value + 1)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, lam):
            shape = sz if sz is not None else jnp.shape(lam)
            return jr.poisson(key, lam, shape).astype(jnp.float32)

        return sample_op("poisson_sample", fn, self.rate, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.rate))

    def broadcast_to(self, batch_shape):
        return Poisson(broadcast_param(self.rate, batch_shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    @property
    def _natural_params(self):
        np = _np()
        return (np.log(self.rate),)

    def _log_normalizer(self, x):
        import jax.numpy as jnp

        return jnp.exp(x)


class Categorical(Distribution):
    """Categorical over {0..num_events-1} (reference categorical.py:29-230)."""

    has_enumerate_support = True
    arg_constraints = {"prob": C.Real(), "logit": C.Real()}

    def __init__(self, num_events, prob=None, logit=None, validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError("Either `prob` or `logit` must be specified "
                             "(but not both).")
        self.num_events = int(num_events)
        if prob is not None:
            self.prob = as_ndarray(prob)
        else:
            self.logit = as_ndarray(logit)
        self.support = C.IntegerInterval(0, num_events - 1)
        super().__init__(event_dim=0, validate_args=validate_args)

    @cached_property
    def prob(self):
        return softmax(self.logit, axis=-1)

    @cached_property
    def logit(self):
        np = _np()
        return np.log(clip_prob(self.prob)) - np.log(
            np.sum(clip_prob(self.prob), axis=-1, keepdims=True))

    def log_prob(self, value):
        import jax.numpy as jnp
        import jax.nn as jnn

        from ....ndarray.ndarray import apply_op_flat

        self._validate_samples(value)

        def lp(logit, v):
            norm = jnn.log_softmax(logit, axis=-1)
            norm = jnp.broadcast_to(norm, jnp.shape(v) + (norm.shape[-1],))
            return jnp.take_along_axis(
                norm, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]

        return apply_op_flat("categorical_log_prob", lp, (self.logit, value))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        def fn(key, sz, logit):
            shape = sz if sz is not None else jnp.shape(logit)[:-1]
            return jr.categorical(key, logit, shape=shape).astype(jnp.float32)

        return sample_op("categorical_sample", fn, self.logit, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.logit)[:-1])

    def broadcast_to(self, batch_shape):
        return Categorical(
            self.num_events,
            logit=broadcast_param(self.logit,
                                  tuple(batch_shape) + (self.num_events,)))

    def enumerate_support(self):
        np = _np()
        import numpy as onp

        batch = _bshape(self.logit)[:-1]
        vals = onp.arange(self.num_events, dtype="float32").reshape(
            (self.num_events,) + (1,) * len(batch))
        return np.array(onp.broadcast_to(vals, (self.num_events,) + batch))

    @property
    def mean(self):
        raise ValueError("Categorical distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Categorical distribution has no variance")

    def entropy(self):
        np = _np()
        logp = log_softmax(self.logit, axis=-1)
        return -np.sum(np.exp(logp) * logp, axis=-1)


class OneHotCategorical(Categorical):
    """One-hot-coded categorical (reference one_hot_categorical.py:30-160)."""

    def __init__(self, num_events, prob=None, logit=None, validate_args=None):
        super().__init__(num_events, prob=prob, logit=logit,
                         validate_args=validate_args)
        self.support = C.Simplex()  # one-hot vectors live on simplex vertices
        self.event_dim = 1

    def log_prob(self, value):
        np = _np()
        logp = log_softmax(self.logit, axis=-1)
        return np.sum(logp * value, axis=-1)

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.nn as jnn
        import jax.random as jr

        n = self.num_events

        def fn(key, sz, logit):
            shape = sz if sz is not None else jnp.shape(logit)[:-1]
            idx = jr.categorical(key, logit, shape=shape)
            return jnn.one_hot(idx, n, dtype=jnp.float32)

        return sample_op("one_hot_categorical_sample", fn, self.logit,
                         size=size)

    def broadcast_to(self, batch_shape):
        return OneHotCategorical(
            self.num_events,
            logit=broadcast_param(self.logit,
                                  tuple(batch_shape) + (self.num_events,)))

    def enumerate_support(self):
        np = _np()
        import numpy as onp

        batch = tuple(_bshape(self.logit)[:-1])
        eye = onp.eye(self.num_events, dtype="float32").reshape(
            (self.num_events,) + (1,) * len(batch) + (self.num_events,))
        return np.array(onp.broadcast_to(
            eye, (self.num_events,) + batch + (self.num_events,)))

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        return self.prob * (1 - self.prob)


class Multinomial(Distribution):
    """Multinomial counts over `num_events` categories (reference
    multinomial.py:30-170)."""

    def __init__(self, num_events, prob=None, logit=None, total_count=1,
                 validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError("Either `prob` or `logit` must be specified "
                             "(but not both).")
        self.num_events = int(num_events)
        self.total_count = total_count
        if prob is not None:
            self.prob = as_ndarray(prob)
        else:
            self.logit = as_ndarray(logit)
        super().__init__(event_dim=1, validate_args=validate_args)

    @cached_property
    def prob(self):
        return softmax(self.logit, axis=-1)

    @cached_property
    def logit(self):
        np = _np()
        return np.log(clip_prob(self.prob))

    def log_prob(self, value):
        np = _np()
        n = np.sum(value, axis=-1)
        return (gammaln(n + 1) - np.sum(gammaln(value + 1), axis=-1)
                + np.sum(xlogy(value, clip_prob(self.prob)), axis=-1))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.random as jr

        tc = int(self.total_count)
        n_ev = self.num_events

        def fn(key, sz, p):
            batch = sz if sz is not None else jnp.shape(p)[:-1]
            p_b = jnp.broadcast_to(p, tuple(batch) + (n_ev,))
            idx = jr.categorical(
                key, jnp.log(jnp.clip(p_b, 1e-12, 1.0)),
                shape=(tc,) + tuple(batch))
            import jax.nn as jnn

            oh = jnn.one_hot(idx, n_ev, dtype=jnp.float32)
            return jnp.sum(oh, axis=0)

        return sample_op("multinomial_sample", fn, self.prob, size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.prob)[:-1])

    def broadcast_to(self, batch_shape):
        return Multinomial(
            self.num_events,
            prob=broadcast_param(self.prob,
                                 tuple(batch_shape) + (self.num_events,)),
            total_count=self.total_count)

    @property
    def mean(self):
        return self.total_count * self.prob

    @property
    def variance(self):
        return self.total_count * self.prob * (1 - self.prob)


class RelaxedBernoulli(Distribution):
    """Concrete / Gumbel-sigmoid relaxation of Bernoulli at temperature `T`
    (reference relaxed_bernoulli.py:31-140). Fully reparameterized."""

    has_grad = True
    support = C.UnitInterval()
    arg_constraints = {"prob": C.UnitInterval(), "logit": C.Real()}

    def __init__(self, T, prob=None, logit=None, validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError("Either `prob` or `logit` must be specified "
                             "(but not both).")
        self.T = as_ndarray(T)
        if prob is not None:
            self.prob = as_ndarray(prob)
        else:
            self.logit = as_ndarray(logit)
        super().__init__(event_dim=0, validate_args=validate_args)

    @cached_property
    def prob(self):
        return logit2prob(self.logit, True)

    @cached_property
    def logit(self):
        return prob2logit(self.prob, True)

    def log_prob(self, value):
        np = _np()
        # density of the logistic-transformed relaxed variable
        t, logit = self.T, self.logit
        y = np.log(value) - np.log1p(-value)
        diff = logit - t * y
        return np.log(t) + diff - 2 * softplus(diff) - np.log(
            value * (1 - value))

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.nn as jnn
        import jax.random as jr

        def fn(key, sz, t, logit):
            shape = sz if sz is not None else jnp.broadcast_shapes(
                jnp.shape(t), jnp.shape(logit))
            u = jr.uniform(key, shape, minval=1e-7, maxval=1.0 - 1e-7)
            gl = jnp.log(u) - jnp.log1p(-u)  # logistic noise
            return jnn.sigmoid((logit + gl) / t)

        return sample_op("relaxed_bernoulli_sample", fn, self.T, self.logit,
                         size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.logit))

    def broadcast_to(self, batch_shape):
        return RelaxedBernoulli(self.T,
                                logit=broadcast_param(self.logit, batch_shape))

    @property
    def mean(self):
        return self.prob


class RelaxedOneHotCategorical(Distribution):
    """Gumbel-softmax relaxation of OneHotCategorical at temperature `T`
    (reference relaxed_one_hot_categorical.py:32-200). Reparameterized."""

    has_grad = True
    support = C.Simplex()

    def __init__(self, T, num_events, prob=None, logit=None,
                 validate_args=None):
        if (prob is None) == (logit is None):
            raise ValueError("Either `prob` or `logit` must be specified "
                             "(but not both).")
        self.T = as_ndarray(T)
        self.num_events = int(num_events)
        if prob is not None:
            self.prob = as_ndarray(prob)
        else:
            self.logit = as_ndarray(logit)
        super().__init__(event_dim=1, validate_args=validate_args)

    @cached_property
    def prob(self):
        return softmax(self.logit, axis=-1)

    @cached_property
    def logit(self):
        np = _np()
        return np.log(clip_prob(self.prob))

    def log_prob(self, value):
        # Gumbel-softmax density (Maddison et al. 2017, eq. 6):
        # log p(y) = log(k-1)! + (k-1)logT + Σ(logπ-(T+1)logy) - k·lse(logπ-T·logy)
        from .utils import logsumexp

        np = _np()
        k = self.num_events
        t = self.T
        logp = log_softmax(self.logit, axis=-1)
        score = np.sum(logp - (t + 1) * np.log(value), axis=-1)
        denom = k * logsumexp(logp - t * np.log(value), axis=-1)
        return math.lgamma(k) + (k - 1) * np.log(t) + score - denom

    def sample(self, size=None):
        import jax.numpy as jnp
        import jax.nn as jnn
        import jax.random as jr

        def fn(key, sz, t, logit):
            batch = sz if sz is not None else jnp.shape(logit)[:-1]
            shape = tuple(batch) + (jnp.shape(logit)[-1],)
            g = jr.gumbel(key, shape)
            return jnn.softmax((logit + g) / t, axis=-1)

        return sample_op("relaxed_one_hot_sample", fn, self.T, self.logit,
                         size=size)

    def sample_n(self, size=None):
        sz = norm_size(size) or ()
        return self.sample(tuple(sz) + _bshape(self.logit)[:-1])

    def broadcast_to(self, batch_shape):
        return RelaxedOneHotCategorical(
            self.T, self.num_events,
            logit=broadcast_param(self.logit,
                                  tuple(batch_shape) + (self.num_events,)))

    @property
    def mean(self):
        return self.prob
