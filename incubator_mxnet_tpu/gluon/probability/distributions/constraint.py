"""Parameter/support constraints (reference:
`python/mxnet/gluon/probability/distributions/constraint.py`).

`check(value)` returns `value` when it satisfies the constraint and raises
`ValueError` otherwise. Under a jit trace the data-dependent check is skipped
(tracers have no concrete truth value); validation is an eager-mode debugging
aid, exactly like the reference's `validate_args` contract.
"""
from __future__ import annotations

__all__ = [
    "Constraint", "Real", "Boolean", "Positive", "NonNegative", "GreaterThan",
    "GreaterThanEq", "LessThan", "LessThanEq", "Interval", "HalfOpenInterval",
    "OpenInterval", "UnitInterval", "IntegerInterval", "NonNegativeInteger",
    "PositiveInteger", "IntegerGreaterThan", "IntegerGreaterThanEq", "Simplex",
    "LowerTriangular", "LowerCholesky", "PositiveDefinite", "Cat", "Stack",
]


def _concrete(cond):
    """Evaluate a boolean NDArray condition; None if tracing (skip check)."""
    try:
        import numpy as onp

        return bool(onp.all(onp.asarray(cond.asnumpy() if hasattr(cond, "asnumpy")
                                        else cond)))
    except Exception:  # tracer or abstract value: cannot validate
        return None


class Constraint:
    """Base class. Subclasses define `_cond(value)` returning a boolean array."""

    _err = "Constraint violated"

    def check(self, value):
        cond = self._cond(value)
        ok = _concrete(cond)
        if ok is False:
            raise ValueError(self._err)
        return value

    def _cond(self, value):  # pragma: no cover - abstract
        raise NotImplementedError


class Real(Constraint):
    _err = "Expected real-valued tensor without NaN"

    def _cond(self, value):
        return value == value  # NaN != NaN


class Boolean(Constraint):
    _err = "Expected values in {0, 1}"

    def _cond(self, value):
        from .... import numpy as np

        return np.logical_or(value == 0, value == 1)


class Positive(Constraint):
    _err = "Expected value > 0"

    def _cond(self, value):
        return value > 0


class NonNegative(Constraint):
    _err = "Expected value >= 0"

    def _cond(self, value):
        return value >= 0


class GreaterThan(Constraint):
    def __init__(self, lower_bound):
        self._lower_bound = lower_bound
        self._err = f"Expected value > {lower_bound}"

    def _cond(self, value):
        lb = self._lower_bound
        return value > (lb._data if hasattr(lb, "_data") else lb)


class GreaterThanEq(GreaterThan):
    def __init__(self, lower_bound):
        super().__init__(lower_bound)
        self._err = f"Expected value >= {lower_bound}"

    def _cond(self, value):
        lb = self._lower_bound
        return value >= (lb._data if hasattr(lb, "_data") else lb)


class LessThan(Constraint):
    def __init__(self, upper_bound):
        self._upper_bound = upper_bound
        self._err = f"Expected value < {upper_bound}"

    def _cond(self, value):
        return value < self._upper_bound


class LessThanEq(LessThan):
    def __init__(self, upper_bound):
        super().__init__(upper_bound)
        self._err = f"Expected value <= {upper_bound}"

    def _cond(self, value):
        return value <= self._upper_bound


class Interval(Constraint):
    def __init__(self, lower_bound, upper_bound):
        self._lower_bound = lower_bound
        self._upper_bound = upper_bound
        self._err = f"Expected value in [{lower_bound}, {upper_bound}]"

    def _cond(self, value):
        from .... import numpy as np

        return np.logical_and(value >= self._lower_bound,
                              value <= self._upper_bound)


class HalfOpenInterval(Interval):
    def __init__(self, lower_bound, upper_bound):
        super().__init__(lower_bound, upper_bound)
        self._err = f"Expected value in [{lower_bound}, {upper_bound})"

    def _cond(self, value):
        from .... import numpy as np

        return np.logical_and(value >= self._lower_bound,
                              value < self._upper_bound)


class OpenInterval(Interval):
    def __init__(self, lower_bound, upper_bound):
        super().__init__(lower_bound, upper_bound)
        self._err = f"Expected value in ({lower_bound}, {upper_bound})"

    def _cond(self, value):
        from .... import numpy as np

        return np.logical_and(value > self._lower_bound,
                              value < self._upper_bound)


class UnitInterval(Interval):
    def __init__(self):
        super().__init__(0.0, 1.0)


class IntegerInterval(Interval):
    def _cond(self, value):
        from .... import numpy as np

        return np.logical_and(value == np.floor(value), super()._cond(value))


class IntegerGreaterThan(GreaterThan):
    def _cond(self, value):
        from .... import numpy as np

        return np.logical_and(value == np.floor(value), super()._cond(value))


class IntegerGreaterThanEq(GreaterThanEq):
    def _cond(self, value):
        from .... import numpy as np

        return np.logical_and(value == np.floor(value), super()._cond(value))


class NonNegativeInteger(IntegerGreaterThanEq):
    def __init__(self):
        super().__init__(0)


class PositiveInteger(IntegerGreaterThanEq):
    def __init__(self):
        super().__init__(1)


class Simplex(Constraint):
    _err = "Expected vector summing to 1 with nonnegative entries"

    def _cond(self, value):
        from .... import numpy as np

        return np.logical_and(np.all(value >= 0, axis=-1),
                              np.abs(np.sum(value, axis=-1) - 1) < 1e-5)


class LowerTriangular(Constraint):
    _err = "Expected lower-triangular matrix"

    def _cond(self, value):
        from .... import numpy as np

        return np.all(np.abs(np.triu(value, 1)) < 1e-6, axis=(-2, -1))


class LowerCholesky(Constraint):
    _err = "Expected lower-triangular matrix with positive diagonal"

    def _cond(self, value):
        from .... import numpy as np

        tri = np.all(np.abs(np.triu(value, 1)) < 1e-6, axis=(-2, -1))
        diag = np.all(np.diagonal(value, axis1=-2, axis2=-1) > 0, axis=-1)
        return np.logical_and(tri, diag)


class PositiveDefinite(Constraint):
    _err = "Expected positive-definite matrix"

    def _cond(self, value):
        from .... import numpy as np

        sym = np.all(np.abs(value - np.swapaxes(value, -1, -2)) < 1e-5,
                     axis=(-2, -1))
        import numpy.linalg as onl  # eager eigvals check only

        try:
            ev = onl.eigvalsh(value.asnumpy())
            import numpy as onp

            return np.logical_and(sym, np.array(onp.all(ev > 0)))
        except Exception:
            return sym


class Cat(Constraint):
    """Concatenation of constraints applied to slices along `axis`."""

    def __init__(self, constraints, axis=0, lengths=None):
        self._constraints = list(constraints)
        self._axis = axis
        self._lengths = lengths

    def check(self, value):
        lengths = self._lengths or [1] * len(self._constraints)
        start = 0
        for c, n in zip(self._constraints, lengths):
            sl = [slice(None)] * (self._axis + 1)
            sl[self._axis] = slice(start, start + n)
            c.check(value[tuple(sl)])
            start += n
        return value

    def _cond(self, value):  # pragma: no cover
        raise NotImplementedError


class Stack(Constraint):
    def __init__(self, constraints, axis=0):
        self._constraints = list(constraints)
        self._axis = axis

    def check(self, value):
        for i, c in enumerate(self._constraints):
            sl = [slice(None)] * (self._axis + 1)
            sl[self._axis] = i
            c.check(value[tuple(sl)])
        return value

    def _cond(self, value):  # pragma: no cover
        raise NotImplementedError
