"""Distribution base classes (reference:
`python/mxnet/gluon/probability/distributions/distribution.py:28-210`,
`exp_family.py`).

TPU-native design: every method is a composition of autograd-aware `np` ops /
fused `apply_op_flat` kernels, so log_prob/entropy/sample are differentiable
and jit-safe — a `Distribution` can be constructed and consumed inside a
hybridized forward (parameters are traced NDArrays; draws pull fresh traced
keys from the global RNG).
"""
from __future__ import annotations

from .utils import cached_property  # noqa: F401

__all__ = ["Distribution", "ExponentialFamily"]


class Distribution:
    """Base class for probability distributions.

    Parameters
    ----------
    event_dim : int, default None
        Number of rightmost dims that define one event of the distribution.
    validate_args : bool, default None
        Whether to validate distribution parameters eagerly.
    """

    # Whether `sample` has pathwise (reparameterized) gradient.
    has_grad = False
    support = None
    has_enumerate_support = False
    arg_constraints = {}
    _validate_args = False

    @staticmethod
    def set_default_validate_args(value):
        if value not in (True, False):
            raise ValueError("validate_args must be True or False")
        Distribution._validate_args = value

    def __init__(self, event_dim=None, validate_args=None):
        self.event_dim = event_dim
        if validate_args is not None:
            self._validate_args = validate_args
        if self._validate_args:
            for param, constraint in self.arg_constraints.items():
                if param not in self.__dict__ and isinstance(
                        getattr(type(self), param, None), cached_property):
                    continue  # lazily-derived param (e.g. logit from prob)
                setattr(self, param, constraint.check(getattr(self, param)))
        super().__init__()

    # -- densities ---------------------------------------------------------
    def log_prob(self, value):
        """Log of the probability density/mass function at `value`."""
        raise NotImplementedError

    def prob(self, value):
        from .... import numpy as np

        return np.exp(self.log_prob(value))

    pdf = prob

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    # -- sampling ----------------------------------------------------------
    def sample(self, size=None):
        """Generate a sample of shape `size + batch_shape + event_shape`."""
        raise NotImplementedError

    def sample_n(self, size):
        """Generate `(size,) + batch_shape + event_shape` samples."""
        if size is None:
            return self.sample()
        if isinstance(size, int):
            size = (size,)
        return self.sample(tuple(size) + tuple(self._batch_shape()))

    def _batch_shape(self):
        m = self.mean
        return getattr(m, "shape", ())

    def broadcast_to(self, batch_shape):
        """New distribution instance with parameters broadcast to `batch_shape`."""
        raise NotImplementedError

    def enumerate_support(self):
        raise NotImplementedError

    # -- moments -----------------------------------------------------------
    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        from .... import numpy as np

        return np.sqrt(self.variance)

    def entropy(self):
        raise NotImplementedError

    def perplexity(self):
        from .... import numpy as np

        return np.exp(self.entropy())

    def __repr__(self):
        args = ", ".join(
            f"{k}={getattr(self, k, None)!r}" for k in self.arg_constraints)
        return f"{type(self).__name__}({args})"

    def _validate_samples(self, value):
        if self._validate_args and self.support is not None:
            return self.support.check(value)
        return value


class ExponentialFamily(Distribution):
    r"""Distributions of form
    :math:`p(x;\theta) = h(x)\exp(\eta(\theta)\cdot T(x) - A(\eta))`
    (reference `exp_family.py`). Entropy via the Bregman-divergence identity:
    the gradient of the log-normalizer w.r.t. natural parameters gives
    E[T(x)], so entropy falls out of one `jax.grad` call — the TPU analogue
    of the reference's autograd-over-`_log_normalizer` trick.
    """

    @property
    def _natural_params(self):
        """Tuple of NDArray natural parameters."""
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        """Log-normalizer A(η) over RAW jnp buffers (pure, jit-safe)."""
        raise NotImplementedError

    _mean_carrier_measure = 0.0

    def entropy(self):
        import jax

        from ....ndarray.ndarray import apply_op_flat

        log_norm = self._log_normalizer
        carrier = self._mean_carrier_measure

        def _ent(*nps):
            lg = log_norm(*nps)
            grads = jax.grad(lambda *ps: log_norm(*ps).sum(),
                             argnums=tuple(range(len(nps))))(*nps)
            result = lg - carrier
            for np_i, g_i in zip(nps, grads):
                result = result - np_i * g_i
            return result

        return apply_op_flat("exp_family_entropy", _ent,
                             tuple(self._natural_params))
