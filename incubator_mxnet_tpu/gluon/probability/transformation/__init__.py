"""Transformations / bijectors (reference:
`python/mxnet/gluon/probability/transformation/`)."""
from .transformation import (AbsTransform, AffineTransform,  # noqa: F401
                             ComposeTransform, ExpTransform, PowerTransform,
                             SigmoidTransform, SoftmaxTransform,
                             TransformBlock, Transformation)
from .domain_map import biject_to, domain_map, transform_to  # noqa: F401

__all__ = [
    "Transformation", "TransformBlock", "ComposeTransform", "ExpTransform",
    "AffineTransform", "PowerTransform", "SigmoidTransform",
    "SoftmaxTransform", "AbsTransform", "domain_map", "biject_to",
    "transform_to",
]
