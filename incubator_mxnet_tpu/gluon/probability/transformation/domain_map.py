"""Registry mapping constraints to transformations from unconstrained space
(reference: `python/mxnet/gluon/probability/transformation/domain_map.py`)."""
from __future__ import annotations

from numbers import Number

from ..distributions.constraint import (Constraint, GreaterThan,
                                        GreaterThanEq, HalfOpenInterval,
                                        Interval, LessThan, LowerCholesky,
                                        NonNegative, Positive, Real, Simplex,
                                        UnitInterval)
from .transformation import (AffineTransform, ComposeTransform, ExpTransform,
                             SigmoidTransform, SoftmaxTransform,
                             Transformation)

__all__ = ["domain_map", "biject_to", "transform_to"]


class domain_map:
    """Registry: constraint type → factory producing a Transformation that
    maps unconstrained reals onto the constrained domain."""

    def __init__(self):
        self._storage = {}
        super().__init__()

    def register(self, constraint, factory=None):
        if factory is None:
            return lambda f: self.register(constraint, f)
        if isinstance(constraint, Constraint):
            constraint = type(constraint)
        if not (isinstance(constraint, type)
                and issubclass(constraint, Constraint)):
            raise TypeError(
                "Expected constraint to be either a Constraint subclass or "
                f"instance, but got {constraint}")
        self._storage[constraint] = factory
        return factory

    def __call__(self, constraint):
        try:
            factory = self._storage[type(constraint)]
        except KeyError:
            raise NotImplementedError(
                f"Cannot transform {type(constraint).__name__} constraints")
        return factory(constraint)


biject_to = domain_map()
transform_to = domain_map()


class _IdentityTransform(Transformation):
    bijective = True
    sign = 1

    def _forward_compute(self, x):
        return x

    def _inverse_compute(self, y):
        return y

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        from .... import numpy as np

        return np.zeros_like(x)


@biject_to.register(Real)
@transform_to.register(Real)
def _transform_to_real(constraint):  # noqa: ARG001
    return _IdentityTransform()


@biject_to.register(Positive)
@biject_to.register(NonNegative)
@transform_to.register(Positive)
@transform_to.register(NonNegative)
def _transform_to_positive(constraint):  # noqa: ARG001
    return ExpTransform()


@biject_to.register(GreaterThan)
@biject_to.register(GreaterThanEq)
@transform_to.register(GreaterThan)
@transform_to.register(GreaterThanEq)
def _transform_to_greater_than(constraint):
    return ComposeTransform([ExpTransform(),
                             AffineTransform(constraint._lower_bound, 1)])


@biject_to.register(LessThan)
@transform_to.register(LessThan)
def _transform_to_less_than(constraint):
    return ComposeTransform([ExpTransform(),
                             AffineTransform(constraint._upper_bound, -1)])


@biject_to.register(UnitInterval)
@biject_to.register(Interval)
@biject_to.register(HalfOpenInterval)
@transform_to.register(UnitInterval)
@transform_to.register(Interval)
@transform_to.register(HalfOpenInterval)
def _transform_to_interval(constraint):
    lower = getattr(constraint, "_lower_bound", 0)
    upper = getattr(constraint, "_upper_bound", 1)
    lower_is_0 = isinstance(lower, Number) and lower == 0
    upper_is_1 = isinstance(upper, Number) and upper == 1
    if lower_is_0 and upper_is_1:
        return SigmoidTransform()
    return ComposeTransform([SigmoidTransform(),
                             AffineTransform(lower, upper - lower)])


@biject_to.register(Simplex)
@transform_to.register(Simplex)
def _transform_to_simplex(constraint):  # noqa: ARG001
    return SoftmaxTransform()


@biject_to.register(LowerCholesky)
@transform_to.register(LowerCholesky)
def _transform_to_lower_cholesky(constraint):  # noqa: ARG001
    class _LowerCholeskyTransform(Transformation):
        event_dim = 2

        def _forward_compute(self, x):
            from .... import numpy as np
            from ....ndarray.ndarray import apply_op_flat

            import jax.numpy as jnp

            def f(m):
                tril = jnp.tril(m, -1)
                diag = jnp.exp(jnp.diagonal(m, axis1=-2, axis2=-1))
                return tril + jnp.vectorize(jnp.diag,
                                            signature="(k)->(k,k)")(diag)

            return apply_op_flat("lower_cholesky_fwd", f, (x,))

        def _inverse_compute(self, y):
            from ....ndarray.ndarray import apply_op_flat

            import jax.numpy as jnp

            def f(m):
                tril = jnp.tril(m, -1)
                diag = jnp.log(jnp.diagonal(m, axis1=-2, axis2=-1))
                return tril + jnp.vectorize(jnp.diag,
                                            signature="(k)->(k,k)")(diag)

            return apply_op_flat("lower_cholesky_inv", f, (y,))

    return _LowerCholeskyTransform()
