"""Invertible transformations / bijectors (reference:
`python/mxnet/gluon/probability/transformation/transformation.py:32-290`).

Each transformation is a composition of autograd-aware `np` ops, so
`TransformedDistribution.log_prob` is differentiable end to end and traces
cleanly under hybridize/jit.
"""
from __future__ import annotations

import math

__all__ = [
    "Transformation", "TransformBlock", "ComposeTransform", "ExpTransform",
    "AffineTransform", "PowerTransform", "SigmoidTransform",
    "SoftmaxTransform", "AbsTransform",
]


def _np():
    from .... import numpy as np

    return np


class Transformation:
    """Abstract invertible transformation with computable log-det-Jacobian."""

    bijective = False
    event_dim = 0

    def __init__(self):
        self._inv = None
        super().__init__()

    @property
    def sign(self):
        """Sign of the derivative (+1/-1) for monotonic transforms."""
        raise NotImplementedError

    @property
    def inv(self):
        inv = None
        if self._inv is not None:
            inv = self._inv()
        if inv is None:
            inv = _InverseTransformation(self)
            import weakref

            self._inv = weakref.ref(inv)
        return inv

    def __call__(self, x):
        return self._forward_compute(x)

    def _inv_call(self, y):
        return self._inverse_compute(y)

    def _forward_compute(self, x):
        raise NotImplementedError

    def _inverse_compute(self, y):
        raise NotImplementedError

    def log_det_jacobian(self, x, y):
        """log|dy/dx| evaluated at (x, y=T(x))."""
        raise NotImplementedError


class _InverseTransformation(Transformation):
    """The inverse of a Transformation, sharing its state."""

    def __init__(self, forward_transformation):
        super().__init__()
        self._fn = forward_transformation

    @property
    def inv(self):
        return self._fn

    @property
    def sign(self):
        return self._fn.sign

    @property
    def event_dim(self):
        return self._fn.event_dim

    def __call__(self, x):
        return self._fn._inv_call(x)

    def log_det_jacobian(self, x, y):
        return -self._fn.log_det_jacobian(y, x)


class TransformBlock(Transformation):
    """Transformation that is also a gluon HybridBlock (can hold Parameters,
    e.g. learned flows). Reference transformation.py:113-122."""

    def __init__(self, *args, **kwargs):
        from ...block import HybridBlock

        Transformation.__init__(self)
        # cooperative: behave as a HybridBlock too
        self._block = HybridBlock(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__dict__["_block"], name)


class ComposeTransform(Transformation):
    """Composition T_n ∘ ... ∘ T_1."""

    def __init__(self, parts):
        super().__init__()
        self._parts = list(parts)

    def _forward_compute(self, x):
        for t in self._parts:
            x = t(x)
        return x

    def _inverse_compute(self, y):
        for t in reversed(self._parts):
            y = t.inv(y)
        return y

    @property
    def sign(self):
        s = 1
        for t in self._parts:
            s = s * t.sign
        return s

    @property
    def event_dim(self):
        return max(t.event_dim for t in self._parts) if self._parts else 0

    @property
    def inv(self):
        inv = None
        if self._inv is not None:
            inv = self._inv()
        if inv is None:
            inv = ComposeTransform([t.inv for t in reversed(self._parts)])
            import weakref

            self._inv = weakref.ref(inv)
            inv._inv = weakref.ref(self)
        return inv

    def log_det_jacobian(self, x, y):
        from ..distributions.utils import sum_right_most

        result = 0.0
        event_dim = self.event_dim
        for t in self._parts:
            y_t = t(x)
            result = result + sum_right_most(t.log_det_jacobian(x, y_t),
                                             event_dim - t.event_dim)
            x = y_t
        return result


class ExpTransform(Transformation):
    r"""y = exp(x)."""

    bijective = True
    sign = 1

    def _forward_compute(self, x):
        return _np().exp(x)

    def _inverse_compute(self, y):
        return _np().log(y)

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        return x


class AffineTransform(Transformation):
    r"""y = loc + scale * x."""

    bijective = True

    def __init__(self, loc, scale, event_dim=0):
        super().__init__()
        self._loc = loc
        self._scale = scale
        self.event_dim = event_dim

    def _forward_compute(self, x):
        return self._loc + self._scale * x

    def _inverse_compute(self, y):
        return (y - self._loc) / self._scale

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        np = _np()
        scale = self._scale
        if isinstance(scale, (int, float)):
            return np.full_like(x, math.log(abs(scale)))
        return np.broadcast_to(np.log(np.abs(scale)), x.shape)

    @property
    def sign(self):
        np = _np()
        if isinstance(self._scale, (int, float)):
            return 1 if self._scale > 0 else -1
        return np.sign(self._scale)


class PowerTransform(Transformation):
    r"""y = x ** exponent (for x > 0)."""

    bijective = True
    sign = 1

    def __init__(self, exponent):
        super().__init__()
        self._exponent = exponent

    def _forward_compute(self, x):
        return x ** self._exponent

    def _inverse_compute(self, y):
        return y ** (1.0 / self._exponent)

    def log_det_jacobian(self, x, y):
        np = _np()
        return np.log(np.abs(self._exponent * y / x))


class SigmoidTransform(Transformation):
    r"""y = 1 / (1 + exp(-x))."""

    bijective = True
    sign = 1

    def _forward_compute(self, x):
        from ..distributions.utils import expit

        return expit(x)

    def _inverse_compute(self, y):
        np = _np()
        return np.log(y) - np.log1p(-y)

    def log_det_jacobian(self, x, y):  # noqa: ARG002
        from ..distributions.utils import softplus

        return -softplus(-x) - softplus(x)


class SoftmaxTransform(Transformation):
    r"""y = softmax(x) over the trailing axis (not bijective; used for
    transform_to simplex constraints)."""

    event_dim = 1

    def _forward_compute(self, x):
        from ..distributions.utils import softmax

        return softmax(x, axis=-1)

    def _inverse_compute(self, y):
        return _np().log(y)


class AbsTransform(Transformation):
    r"""y = |x| (not bijective)."""

    def _forward_compute(self, x):
        return _np().abs(x)

    def _inverse_compute(self, y):
        return y
