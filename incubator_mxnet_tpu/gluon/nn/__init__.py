from .activations import *  # noqa: F401,F403
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403
from .extra_layers import (  # noqa: F401
    BatchNormReLU, DeformableConvolution, ModulatedDeformableConvolution,
    PixelShuffle1D, PixelShuffle2D, PixelShuffle3D, SyncBatchNorm,
)
