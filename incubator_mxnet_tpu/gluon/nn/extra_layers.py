"""Additional gluon layers (reference: `python/mxnet/gluon/nn/` —
PixelShuffle1D/2D/3D, SyncBatchNorm, BatchNormReLU from basic_layers.py /
conv_layers.py; DeformableConvolution / ModulatedDeformableConvolution
from contrib conv layers over `src/operator/contrib/
deformable_convolution.cc`)."""
from __future__ import annotations

from ... import numpy_extension as npx
from ...ndarray.ndarray import apply_op
from ..block import HybridBlock
from ..parameter import Parameter
from .basic_layers import BatchNorm

__all__ = ["PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D",
           "SyncBatchNorm", "BatchNormReLU", "DeformableConvolution",
           "ModulatedDeformableConvolution"]


class PixelShuffle1D(HybridBlock):
    """(N, C·f, W) → (N, C, W·f) sub-pixel upsampling (reference:
    conv_layers.py PixelShuffle1D)."""

    def __init__(self, factor):
        super().__init__()
        self._factor = int(factor)

    def forward(self, x):
        f = self._factor

        def fn(v):
            n, cf, w = v.shape
            c = cf // f
            return v.reshape(n, c, f, w).transpose(0, 1, 3, 2) \
                .reshape(n, c, w * f)

        return apply_op("pixel_shuffle1d", fn, (x,))


class PixelShuffle2D(HybridBlock):
    """(N, C·f1·f2, H, W) → (N, C, H·f1, W·f2) (reference:
    conv_layers.py PixelShuffle2D)."""

    def __init__(self, factor):
        super().__init__()
        self._factors = (factor, factor) if isinstance(factor, int) \
            else tuple(factor)

    def forward(self, x):
        f1, f2 = self._factors

        def fn(v):
            n, c_all, h, w = v.shape
            c = c_all // (f1 * f2)
            v = v.reshape(n, c, f1, f2, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)  # n c h f1 w f2
            return v.reshape(n, c, h * f1, w * f2)

        return apply_op("pixel_shuffle2d", fn, (x,))


class PixelShuffle3D(HybridBlock):
    """(N, C·f1·f2·f3, D, H, W) → (N, C, D·f1, H·f2, W·f3) (reference:
    conv_layers.py PixelShuffle3D)."""

    def __init__(self, factor):
        super().__init__()
        self._factors = (factor,) * 3 if isinstance(factor, int) \
            else tuple(factor)

    def forward(self, x):
        f1, f2, f3 = self._factors

        def fn(v):
            n, c_all, d, h, w = v.shape
            c = c_all // (f1 * f2 * f3)
            v = v.reshape(n, c, f1, f2, f3, d, h, w)
            v = v.transpose(0, 1, 5, 2, 6, 3, 7, 4)
            return v.reshape(n, c, d * f1, h * f2, w * f3)

        return apply_op("pixel_shuffle3d", fn, (x,))


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (reference: basic_layers.py
    SyncBatchNorm over `src/operator/contrib/sync_batch_norm.cc`).

    TPU-native: under the compiled data-parallel step (`DataParallel`),
    the whole global batch lives in ONE jit program, so plain batch
    statistics ARE the synchronized statistics — the reference's
    cross-GPU reduce is exactly what XLA's partitioner emits for the
    mean/var reductions over the dp-sharded batch axis. The class exists
    so reference code ports unchanged; `num_devices`/`key` are accepted
    for signature parity."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, **kwargs):  # noqa: ARG002
        super().__init__(momentum=momentum, epsilon=epsilon, center=center,
                         scale=scale, use_global_stats=use_global_stats,
                         in_channels=in_channels, **kwargs)


class BatchNormReLU(BatchNorm):
    """BatchNorm with fused ReLU (reference: basic_layers.py
    BatchNormReLU; the fusion itself is XLA's job)."""

    def forward(self, x):
        return npx.relu(super().forward(x))


class DeformableConvolution(HybridBlock):
    """Deformable conv v1 layer: an internal conv predicts the tap
    offsets (reference: contrib DeformableConvolution over
    `src/operator/contrib/deformable_convolution.cc`)."""

    _use_mask = False

    def __init__(self, channels, kernel_size=(3, 3), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), use_bias=True,
                 in_channels=0, num_deformable_group=1,
                 weight_initializer=None, bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", dtype="float32"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self._channels = channels
        self._kernel = tuple(kernel_size)
        self._stride = (strides, strides) if isinstance(strides, int) \
            else tuple(strides)
        self._pad = (padding, padding) if isinstance(padding, int) \
            else tuple(padding)
        self._dilate = (dilation, dilation) if isinstance(dilation, int) \
            else tuple(dilation)
        self._groups = num_deformable_group
        kh, kw = self._kernel
        taps = self._groups * kh * kw
        self._n_off = (3 if self._use_mask else 2) * taps
        self.weight = Parameter(
            shape=(channels, in_channels, kh, kw), dtype=dtype,
            init=weight_initializer, allow_deferred_init=True)
        self.bias = Parameter(shape=(channels,), dtype=dtype,
                              init=bias_initializer) if use_bias else None
        # offset-predicting conv (zeros init → starts as a regular conv)
        self.offset_weight = Parameter(
            shape=(self._n_off, in_channels, kh, kw), dtype=dtype,
            init=offset_weight_initializer, allow_deferred_init=True)
        self.offset_bias = Parameter(shape=(self._n_off,), dtype=dtype,
                                     init=offset_bias_initializer)

    def infer_shape(self, x, *args):
        in_c = x.shape[1]
        kh, kw = self._kernel
        self.weight.shape = (self._channels, in_c, kh, kw)
        self.offset_weight.shape = (self._n_off, in_c, kh, kw)

    def forward(self, x):
        pred = npx.convolution(
            x, self.offset_weight.data(), self.offset_bias.data(),
            kernel=self._kernel, stride=self._stride, dilate=self._dilate,
            pad=self._pad, num_filter=self._n_off)
        kh, kw = self._kernel
        taps = self._groups * kh * kw
        if self._use_mask:
            offset = pred[:, :2 * taps]
            mask = npx.sigmoid(pred[:, 2 * taps:])
        else:
            offset, mask = pred, None
        return npx.deformable_convolution(
            x, offset, self.weight.data(),
            None if self.bias is None else self.bias.data(),
            kernel=self._kernel, stride=self._stride, pad=self._pad,
            dilate=self._dilate, num_filter=self._channels,
            num_deformable_group=self._groups,
            no_bias=self.bias is None, mask=mask)


class ModulatedDeformableConvolution(DeformableConvolution):
    """Deformable conv v2: offsets + sigmoid modulation masks per tap
    (reference: contrib ModulatedDeformableConvolution)."""

    _use_mask = True
