"""Basic NN layers (reference: `python/mxnet/gluon/nn/basic_layers.py` —
Dense, Dropout, BatchNorm, LayerNorm, GroupNorm, InstanceNorm, Embedding,
Flatten, Sequential/HybridSequential, Lambda blocks)."""
from __future__ import annotations

from ... import numpy_extension as npx
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = [
    "Sequential", "HybridSequential", "Dense", "Dropout", "Flatten",
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "Embedding",
    "Lambda", "HybridLambda", "Identity", "Concatenate", "HybridConcatenate",
]


class Sequential(Block):
    """Stack of blocks (reference: basic_layers.py Sequential)."""

    def __init__(self):
        super().__init__()
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            self._layers.append(b)
            self.register_child(b)

    def forward(self, x, *args):
        for block in self._layers:
            x = block(x, *args)
            args = ()
        return x

    def __call__(self, x, *args):
        # containers delegate deferred-shape handling to children
        return self.forward(x, *args)

    def __len__(self):
        return len(self._layers)

    def __getitem__(self, key):
        if isinstance(key, slice):
            net = type(self)()
            net.add(*self._layers[key])
            return net
        return self._layers[key]

    def __iter__(self):
        return iter(self._layers)

    def hybridize(self, active=True, **kwargs):
        for b in self._layers:
            b.hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    def __init__(self):
        super().__init__()
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            self._layers.append(b)
            self.register_child(b)

    def forward(self, x, *args):
        for block in self._layers:
            x = block(x, *args)
            args = ()
        return x

    def __call__(self, *args, **kwargs):
        if not self._active:
            # run children directly so their deferred-init handling fires
            return self.forward(*args, **kwargs)
        return super().__call__(*args, **kwargs)

    def __len__(self):
        return len(self._layers)

    def __getitem__(self, key):
        if isinstance(key, slice):
            net = type(self)()
            net.add(*self._layers[key])
            return net
        return self._layers[key]

    def __iter__(self):
        return iter(self._layers)


class Dense(HybridBlock):
    """Fully-connected layer (reference: basic_layers.py Dense;
    kernel `src/operator/nn/fully_connected.cc` → jnp.matmul on the MXU)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0):
        super().__init__()
        self._units = units
        self._flatten = flatten
        self.act = Activation(activation) if activation else None
        self.weight = Parameter(shape=(units, in_units), dtype=dtype,
                                init=weight_initializer, allow_deferred_init=True)
        self.bias = Parameter(shape=(units,), dtype=dtype,
                              init=bias_initializer,
                              allow_deferred_init=True) if use_bias else None
        if self.act is not None:
            self.register_child(self.act, "act")

    def infer_shape(self, x, *args):
        in_units = x.size // x.shape[0] if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)
        if self.bias is not None:
            self.bias.shape = (self._units,)

    def forward(self, x):
        out = npx.fully_connected(
            x, self.weight.data(), None if self.bias is None else self.bias.data(),
            num_hidden=self._units, no_bias=self.bias is None,
            flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return f"Dense({self._units}, flatten={self._flatten})"


class Dropout(HybridBlock):
    def __init__(self, rate, axes=()):
        super().__init__()
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        return npx.dropout(x, p=self._rate, axes=self._axes)


class Flatten(HybridBlock):
    def forward(self, x):
        return x.reshape(x.shape[0], -1)


class _NormBase(HybridBlock):
    def __init__(self, in_channels, scale=True, center=True, dtype="float32",
                 gamma_initializer="ones", beta_initializer="zeros"):
        super().__init__()
        self.gamma = Parameter(shape=(in_channels,), dtype=dtype,
                               init=gamma_initializer, allow_deferred_init=True,
                               differentiable=scale)
        self.beta = Parameter(shape=(in_channels,), dtype=dtype,
                              init=beta_initializer, allow_deferred_init=True,
                              differentiable=center)


class BatchNorm(_NormBase):
    """Batch normalization with running stats (reference: basic_layers.py
    BatchNorm → `src/operator/nn/batch_norm.cc`; running stats are
    FMutateInputs aux state, functionalized under jit via TraceContext)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, in_channels=0,
                 dtype="float32", **kwargs):
        super().__init__(in_channels, scale, center, dtype)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._use_global_stats = use_global_stats
        self._scale = scale
        self.running_mean = Parameter(shape=(in_channels,), dtype=dtype,
                                      init="zeros", allow_deferred_init=True,
                                      differentiable=False)
        self.running_var = Parameter(shape=(in_channels,), dtype=dtype,
                                     init="ones", allow_deferred_init=True,
                                     differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def forward(self, x):
        return npx.batch_norm(
            x, self.gamma.data(), self.beta.data(), self.running_mean.data(),
            self.running_var.data(), eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale, use_global_stats=self._use_global_stats,
            axis=self._axis)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 in_channels=0, dtype="float32", **kwargs):
        super().__init__()
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = Parameter(shape=(in_channels,), dtype=dtype, init="ones",
                               allow_deferred_init=True, differentiable=scale)
        self.beta = Parameter(shape=(in_channels,), dtype=dtype, init="zeros",
                              allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def forward(self, x):
        return npx.layer_norm(x, self.gamma.data(), self.beta.data(),
                              axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 in_channels=0, dtype="float32", **kwargs):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = Parameter(shape=(in_channels,), dtype=dtype, init="ones",
                               allow_deferred_init=True, differentiable=scale)
        self.beta = Parameter(shape=(in_channels,), dtype=dtype, init="zeros",
                              allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def forward(self, x):
        return npx.group_norm(x, self.gamma.data(), self.beta.data(),
                              num_groups=self._num_groups, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 in_channels=0, dtype="float32", **kwargs):
        super().__init__()
        self._epsilon = epsilon
        self.gamma = Parameter(shape=(in_channels,), dtype=dtype, init="ones",
                               allow_deferred_init=True, differentiable=scale)
        self.beta = Parameter(shape=(in_channels,), dtype=dtype, init="zeros",
                              allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def forward(self, x):
        return npx.instance_norm(x, self.gamma.data(), self.beta.data(),
                                 eps=self._epsilon)


class Embedding(HybridBlock):
    """Index → vector lookup (reference: basic_layers.py Embedding).
    Default backward is XLA's native scatter-add; `sparse_grad=True` keeps
    the reference's row_sparse gradient option: the weight grad is a
    RowSparseNDArray holding only looked-up rows, and sgd/adam apply lazy
    row updates (reference `src/operator/optimizer_op.cc` sparse variants)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__()
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = Parameter(shape=(input_dim, output_dim), dtype=dtype,
                                init=weight_initializer,
                                grad_stype="row_sparse" if sparse_grad
                                else "default")

    def forward(self, x):
        return npx.embedding(x, self.weight.data(), input_dim=self._input_dim,
                             output_dim=self._output_dim,
                             sparse_grad=self._sparse_grad)


class Lambda(Block):
    def __init__(self, function):
        super().__init__()
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function):
        super().__init__()
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Concatenate(Sequential):
    """Run children on the same input, concat outputs along `axis`."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from ... import numpy as np

        return np.concatenate([block(x) for block in self._layers],
                              axis=self._axis)


class HybridConcatenate(HybridSequential):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from ... import numpy as np

        return np.concatenate([block(x) for block in self._layers],
                              axis=self._axis)


from .activations import Activation  # noqa: E402  (used by Dense)
