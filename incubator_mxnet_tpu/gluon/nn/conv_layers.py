"""Convolution & pooling layers (reference: `python/mxnet/gluon/nn/conv_layers.py`
— Conv1D-3D, transposed convs, pooling; kernels `src/operator/nn/convolution.cc`
→ `lax.conv_general_dilated` which XLA tiles onto the MXU)."""
from __future__ import annotations

import numpy as onp

from ... import numpy_extension as npx
from ..block import HybridBlock
from ..parameter import Parameter
from .activations import Activation

__all__ = [
    "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
    "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
    "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
    "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
    "ReflectionPad2D",
]


def _pair(x, n):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 dtype="float32"):
        super().__init__()
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._stride = _pair(strides, ndim)
        self._pad = _pair(padding, ndim)
        self._dilate = _pair(dilation, ndim)
        self._groups = groups
        self._layout = layout
        self.act = Activation(activation) if activation else None
        wshape = (channels, in_channels // groups if in_channels else 0) + kernel_size
        self.weight = Parameter(shape=wshape, dtype=dtype,
                                init=weight_initializer, allow_deferred_init=True)
        self.bias = Parameter(shape=(channels,), dtype=dtype,
                              init=bias_initializer,
                              allow_deferred_init=True) if use_bias else None
        if self.act is not None:
            self.register_child(self.act, "act")

    def infer_shape(self, x, *args):
        c_axis = self._layout.index("C")
        in_c = x.shape[c_axis]
        self._in_channels = in_c
        self.weight.shape = (self._channels, in_c // self._groups) + self._kernel
        if self.bias is not None:
            self.bias.shape = (self._channels,)

    def forward(self, x):
        out = npx.convolution(
            x, self.weight.data(),
            None if self.bias is None else self.bias.data(),
            kernel=self._kernel, stride=self._stride, dilate=self._dilate,
            pad=self._pad, num_filter=self._channels, num_group=self._groups,
            no_bias=self.bias is None, layout=self._layout)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, kernel_size="
                f"{self._kernel}, stride={self._stride})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", dtype="float32"):
        super().__init__(channels, _pair(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, dtype)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", dtype="float32"):
        super().__init__(channels, _pair(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, dtype)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 dtype="float32"):
        super().__init__(channels, _pair(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, dtype)


class _ConvTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides, padding, output_padding,
                 dilation, groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", dtype="float32"):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, dtype)
        self._output_padding = _pair(output_padding, len(kernel_size))

    def infer_shape(self, x, *args):
        c_axis = self._layout.index("C")
        in_c = x.shape[c_axis]
        self._in_channels = in_c
        # transposed conv weight: (in_channels, channels//groups, *kernel)
        self.weight.shape = (in_c, self._channels // self._groups) + self._kernel
        if self.bias is not None:
            self.bias.shape = (self._channels,)

    def forward(self, x):
        out = npx.deconvolution(
            x, self.weight.data(),
            None if self.bias is None else self.bias.data(),
            kernel=self._kernel, stride=self._stride, dilate=self._dilate,
            pad=self._pad, adj=self._output_padding, num_filter=self._channels,
            num_group=self._groups, no_bias=self.bias is None,
            layout=self._layout)
        if self.act is not None:
            out = self.act(out)
        return out


class Conv1DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 dtype="float32"):
        super().__init__(channels, _pair(kernel_size, 1), strides, padding,
                         output_padding, dilation, groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, dtype)


class Conv2DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 dtype="float32"):
        super().__init__(channels, _pair(kernel_size, 2), strides, padding,
                         output_padding, dilation, groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, dtype)


class Conv3DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", in_channels=0,
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", dtype="float32"):
        super().__init__(channels, _pair(kernel_size, 3), strides, padding,
                         output_padding, dilation, groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, dtype)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None):
        super().__init__()
        self._kernel = pool_size
        self._stride = _pair(strides if strides is not None else pool_size,
                             len(pool_size))
        self._pad = _pair(padding, len(pool_size))
        self._global_pool = global_pool
        self._pool_type = pool_type
        self._layout = layout
        self._count_include_pad = (True if count_include_pad is None
                                   else count_include_pad)
        self._ceil_mode = ceil_mode

    def forward(self, x):
        return npx.pooling(
            x, kernel=self._kernel, stride=self._stride, pad=self._pad,
            pool_type=self._pool_type, global_pool=self._global_pool,
            layout=self._layout, count_include_pad=self._count_include_pad,
            pooling_convention="full" if self._ceil_mode else "valid")

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._stride}, padding={self._pad})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False):
        super().__init__(_pair(pool_size, 1), strides, padding, ceil_mode,
                         False, "max", layout)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False):
        super().__init__(_pair(pool_size, 2), strides, padding, ceil_mode,
                         False, "max", layout)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False):
        super().__init__(_pair(pool_size, 3), strides, padding, ceil_mode,
                         False, "max", layout)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True):
        super().__init__(_pair(pool_size, 1), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True):
        super().__init__(_pair(pool_size, 2), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True):
        super().__init__(_pair(pool_size, 3), strides, padding, ceil_mode,
                         False, "avg", layout, count_include_pad)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW"):
        super().__init__((1,), None, 0, True, True, "max", layout)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW"):
        super().__init__((1, 1), None, 0, True, True, "max", layout)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW"):
        super().__init__((1, 1, 1), None, 0, True, True, "max", layout)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW"):
        super().__init__((1,), None, 0, True, True, "avg", layout)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW"):
        super().__init__((1, 1), None, 0, True, True, "avg", layout)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW"):
        super().__init__((1, 1, 1), None, 0, True, True, "avg", layout)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0):
        super().__init__()
        self._padding = padding

    def forward(self, x):
        from ...ndarray.ndarray import apply_op

        p = self._padding
        pw = ((0, 0), (0, 0), (p, p), (p, p)) if isinstance(p, int) else p

        def f(v):
            import jax.numpy as jnp

            return jnp.pad(v, pw, mode="reflect")

        return apply_op("reflection_pad", f, (x,))
