"""Activation blocks (reference: `python/mxnet/gluon/nn/activations.py`)."""
from __future__ import annotations

from ... import numpy_extension as npx
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "GELU", "SiLU",
           "Swish", "Mish"]


class Activation(HybridBlock):
    def __init__(self, activation):
        super().__init__()
        self._act_type = activation

    def forward(self, x):
        return npx.activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.leaky_relu(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="zeros", in_channels=1):
        super().__init__()
        from ... import initializer

        self.alpha = Parameter(shape=(in_channels,),
                               init=initializer.Constant(0.25))

    def forward(self, x):
        return npx.leaky_relu(x, gamma=self.alpha.data(), act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return npx.leaky_relu(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return npx.leaky_relu(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf"):
        super().__init__()
        self._approx = approximation

    def forward(self, x):
        return npx.gelu(x, approximate=self._approx != "erf")


class SiLU(HybridBlock):
    def forward(self, x):
        return npx.activation(x, act_type="silu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0):
        super().__init__()
        self._beta = beta

    def forward(self, x):
        return x * npx.sigmoid(self._beta * x)


class Mish(HybridBlock):
    def forward(self, x):
        return npx.activation(x, act_type="mish")
