"""Gluon Parameter (reference: `python/mxnet/gluon/parameter.py` — lazy shape
inference, grad_req, per-device copies).

TPU-native notes: a Parameter holds ONE NDArray; multi-device replication is
expressed with jax sharding over a Mesh (see `parallel/`) instead of the
reference's explicit per-GPU copies (`_init_impl`), so `list_data()` returns
a single logical array whose buffer may be device-sharded.
"""
from __future__ import annotations

import numpy as onp

from .. import initializer as init_mod
from ..base import np_dtype
from ..device import Device, current_device
from ..ndarray.ndarray import NDArray

__all__ = ["Parameter", "Constant", "DeferredInitializationError"]


class DeferredInitializationError(Exception):
    """Accessing a parameter whose shape is not yet known."""


def _shape_complete(shape):
    return shape is not None and all(isinstance(s, int) and s > 0 for s in shape)


class Parameter:
    def __init__(self, shape=None, dtype="float32", init=None,
                 grad_req="write", lr_mult=1.0, wd_mult=1.0,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default", name=None):  # noqa: ARG002
        self._name = name or "param"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = np_dtype(dtype)
        self.init = init
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self._grad_req = grad_req if differentiable else "null"
        self._stype = stype
        self._grad_stype = grad_stype
        self._allow_deferred_init = allow_deferred_init
        self._data: NDArray | None = None
        self._deferred_init = None  # (initializer, device)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        """Changing grad_req after init takes effect immediately
        (reference: parameter.py grad_req setter re-allocates grads):
        'null' detaches the live gradient buffer; write/add re-attach."""
        if req not in ("write", "add", "null"):
            raise ValueError(f"invalid grad_req {req!r}")
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._grad_req = "write"
            elif self._data._grad is None:
                self._data.attach_grad(req,
                                       stype=self._grad_stype
                                       if self._grad_stype != "default"
                                       else None)
            else:
                # existing buffer: switch its accumulation mode in place
                # (write<->add), keeping the allocated gradient
                self._data._grad_req = req

    # -- identity -----------------------------------------------------------
    @property
    def name(self):
        return self._name

    @name.setter
    def name(self, v):
        self._name = v

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is not None and _shape_complete(self._shape):
            if tuple(new_shape) != self._shape:
                raise ValueError(
                    f"cannot reset shape of initialized Parameter {self._name} "
                    f"from {self._shape} to {tuple(new_shape)}")
        self._shape = tuple(new_shape)

    # -- init ---------------------------------------------------------------
    def initialize(self, init=None, device=None, ctx=None,
                   default_init=init_mod.Uniform, force_reinit=False):
        device = device or ctx
        if self._data is not None and not force_reinit:
            return
        initializer = init_mod.create(init) if init is not None else (
            init_mod.create(self.init) if self.init is not None
            else default_init())
        if not _shape_complete(self._shape):
            if not self._allow_deferred_init:
                raise ValueError(
                    f"Parameter {self._name} has unknown shape {self._shape} and "
                    "allow_deferred_init=False")
            self._deferred_init = (initializer, device)
            return
        self._init_impl(initializer, device)

    def _init_impl(self, initializer, device):
        import jax.numpy as jnp

        dev = Device(device) if device is not None else current_device()
        arr = NDArray(jnp.zeros(self._shape, self.dtype), device=dev)
        if callable(initializer) and not isinstance(initializer, init_mod.Initializer):
            initializer(self._name, arr)
        else:
            initializer(self._name, arr)
        self._data = arr
        if self.grad_req != "null":
            arr.attach_grad(self.grad_req,
                            stype=self._grad_stype
                            if self._grad_stype != "default" else None)
        self._deferred_init = None

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if not _shape_complete(self._shape):
            raise DeferredInitializationError(self._name)
        initializer, device = self._deferred_init
        self._init_impl(initializer, device)

    # -- access -------------------------------------------------------------
    def data(self, device=None, ctx=None):  # noqa: ARG002
        if self._data is None:
            if self._deferred_init is not None:
                if _shape_complete(self._shape):
                    self._finish_deferred_init()
                    return self._data
                raise DeferredInitializationError(
                    f"Parameter {self._name} has not been initialized yet: "
                    "unknown shape")
            raise RuntimeError(
                f"Parameter {self._name} has not been initialized. "
                "Call .initialize() first")
        return self._data

    def list_data(self):
        return [self.data()]

    @property
    def grad_or_none(self):
        return self._data._grad if self._data is not None else None

    def grad(self, device=None, ctx=None):  # noqa: ARG002
        d = self.data()
        if d._grad is None:
            raise RuntimeError(
                f"Parameter {self._name} does not have gradient (grad_req="
                f"{self.grad_req!r})")
        return d._grad

    def list_grad(self):
        return [self.grad()]

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            import jax.numpy as jnp

            from ..ndarray.sparse import RowSparseNDArray

            g = self._data._grad
            if isinstance(g, RowSparseNDArray):
                g._set_sparse(
                    jnp.zeros((0,) + g.shape[1:], g._sp_values.dtype),
                    jnp.zeros((0,), jnp.int32))
            else:
                g._set_data(jnp.zeros(g.shape, g._data.dtype))

    def set_data(self, data):
        d = self.data() if self._data is not None else None
        value = data._data if isinstance(data, NDArray) else data
        if d is None:
            self._shape = tuple(value.shape)
            self._data = NDArray(value)
            if self.grad_req != "null":
                self._data.attach_grad(self.grad_req,
                                       stype=self._grad_stype
                                       if self._grad_stype != "default" else None)
        else:
            d._set_data(value.astype(d._data.dtype)
                        if hasattr(value, "astype") else value)

    def reset_device(self, device):  # single logical device — placement no-op
        if self._data is not None:
            self._data = self._data.to_device(device)

    reset_ctx = reset_device

    def cast(self, dtype):
        self.dtype = np_dtype(dtype)
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data = NDArray(self._data._data.astype(self.dtype))
            if had_grad:
                self._data.attach_grad(self.grad_req,
                                       stype=self._grad_stype
                                       if self._grad_stype != "default" else None)

    def var(self):
        raise NotImplementedError("symbol API not supported; use hybridize()")

    def __repr__(self):
        return (f"Parameter {self._name} (shape={self._shape}, "
                f"dtype={onp.dtype(self.dtype).name if self.dtype is not None and str(self.dtype) != 'bfloat16' else self.dtype})")


class Constant(Parameter):
    """Non-learnable parameter holding a constant (reference: parameter.py).
    The device buffer is shared between `value`, the initializer, and the
    working `_data` — one copy, ready to use without `initialize()`."""

    def __init__(self, value, name=None):
        if not isinstance(value, NDArray):
            value = NDArray(value)
        self.value = value
        super().__init__(shape=value.shape, dtype=value.dtype,
                         init=init_mod.Constant(value),
                         grad_req="null", name=name)
        self._data = value


class ParameterDict(dict):
    """dict of name → Parameter with the reference ParameterDict's bulk
    helpers (reference: `python/mxnet/gluon/parameter.py` ParameterDict —
    collect_params() returns this so `net.collect_params().zero_grad()`
    and friends keep working)."""

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def reset_ctx(self, ctx):  # noqa: ARG002 - single logical device
        return None

    reset_device = reset_ctx
