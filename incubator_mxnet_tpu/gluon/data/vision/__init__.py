from . import transforms  # noqa: F401
from .datasets import CIFAR10, CIFAR100, MNIST, FashionMNIST, ImageFolderDataset  # noqa: F401
