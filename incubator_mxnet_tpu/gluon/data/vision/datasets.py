"""Vision datasets (reference: `python/mxnet/gluon/data/vision/datasets.py`).

This environment has no network egress, so when the on-disk dataset files are
absent a deterministic synthetic stand-in with the right shapes/classes is
generated (seeded per dataset) — tests and examples run anywhere; real data
is used automatically when present under `root`.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as onp

from ..dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        from ....ndarray.ndarray import NDArray

        x = NDArray(self._data[idx])
        y = int(self._label[idx])
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST (28×28×1, 10 classes). Reads idx-format files when present."""

    _seed = 101
    _shape = (28, 28, 1)
    _classes = 10
    _files = {True: ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
              False: ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")}

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        img_f, lbl_f = self._files[self._train]
        img_path = os.path.join(self._root, img_f)
        lbl_path = os.path.join(self._root, lbl_f)
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            with gzip.open(lbl_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                label = onp.frombuffer(f.read(), dtype=onp.uint8)
            with gzip.open(img_path, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                data = onp.frombuffer(f.read(), dtype=onp.uint8).reshape(
                    n, rows, cols, 1)
            self._data, self._label = data, label.astype(onp.int32)
            return
        # deterministic synthetic fallback (no network egress available)
        n = 6000 if self._train else 1000
        rng = onp.random.RandomState(self._seed + (0 if self._train else 1))
        self._data = rng.randint(0, 256, size=(n,) + self._shape,
                                 dtype=onp.uint8)
        self._label = rng.randint(0, self._classes, size=(n,)).astype(onp.int32)


class FashionMNIST(MNIST):
    _seed = 202

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    _seed = 303
    _shape = (32, 32, 3)
    _classes = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        batches = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                   if self._train else ["test_batch.bin"])
        paths = [os.path.join(self._root, "cifar-10-batches-bin", b)
                 for b in batches]
        if all(os.path.exists(p) for p in paths):
            data, labels = [], []
            for p in paths:
                raw = onp.fromfile(p, dtype=onp.uint8).reshape(-1, 3073)
                labels.append(raw[:, 0])
                data.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                            .transpose(0, 2, 3, 1))
            self._data = onp.concatenate(data)
            self._label = onp.concatenate(labels).astype(onp.int32)
            return
        n = 5000 if self._train else 1000
        rng = onp.random.RandomState(self._seed + (0 if self._train else 1))
        self._data = rng.randint(0, 256, size=(n,) + self._shape,
                                 dtype=onp.uint8)
        self._label = rng.randint(0, self._classes, size=(n,)).astype(onp.int32)


class CIFAR100(CIFAR10):
    _seed = 404
    _classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=True, train=True, transform=None):  # noqa: ARG002
        super().__init__(root, train, transform)


class ImageFolderDataset(Dataset):
    """class-per-subfolder image dataset (reference: datasets.py)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                    self.items.append((os.path.join(path, fname), label))

    def __getitem__(self, idx):
        from ....image import imread
        from ....ndarray.ndarray import NDArray

        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = NDArray(onp.load(path))
        else:
            img = imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageRecordDataset(Dataset):
    """Images packed in a RecordIO file by im2rec (reference:
    `gluon/data/vision/datasets.py` ImageRecordDataset over
    `RecordFileDataset` + `recordio.unpack_img`)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset

        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....image import imdecode
        from ....recordio import unpack

        record = self._record[idx]
        header, img_bytes = unpack(record)
        img = imdecode(img_bytes, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record)
