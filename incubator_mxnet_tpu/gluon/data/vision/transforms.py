"""Vision transforms (reference: `gluon/data/vision/transforms/`)."""
from __future__ import annotations

import numpy as onp

from ....ndarray.ndarray import NDArray, apply_op
from ...nn.basic_layers import HybridSequential
from ...block import Block, HybridBlock

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "CropResize", "RandomCrop"]


def _jnp():
    import jax.numpy as jnp

    return jnp


class Compose(HybridSequential):
    def __init__(self, transforms=None):
        super().__init__()
        for t in transforms or []:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference: transforms ToTensor)."""

    def forward(self, x):
        jnp = _jnp()

        def f(v):
            v = v.astype(jnp.float32) / 255.0
            if v.ndim == 3:
                return jnp.transpose(v, (2, 0, 1))
            return jnp.transpose(v, (0, 3, 1, 2))

        return apply_op("to_tensor", f, (x,))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = onp.asarray(mean, dtype="float32")
        self._std = onp.asarray(std, dtype="float32")

    def forward(self, x):
        jnp = _jnp()
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return apply_op("normalize", lambda v: (v - mean) / std, (x,))


def _resize_hwc(v, size):
    import jax

    jnp = _jnp()
    h, w = (size, size) if isinstance(size, int) else (size[1], size[0])
    if v.ndim == 3:
        return jax.image.resize(v.astype(jnp.float32), (h, w, v.shape[2]),
                                method="bilinear").astype(v.dtype)
    return jax.image.resize(v.astype(jnp.float32),
                            (v.shape[0], h, w, v.shape[3]),
                            method="bilinear").astype(v.dtype)


class Resize(HybridBlock):
    """Resize to (W, H); int size + keep_ratio=True resizes the SHORTER
    edge to `size` preserving aspect (reference transforms.Resize)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):  # noqa: ARG002
        super().__init__()
        self._size = size
        self._keep = keep_ratio and isinstance(size, int)

    def forward(self, x):
        if self._keep:
            h, w = x.shape[-3], x.shape[-2]
            s = self._size
            tw, th = (s, s * h // w) if h > w else (s * w // h, s)
            return apply_op("resize",
                            lambda v: _resize_hwc(v, (tw, th)), (x,))
        return apply_op("resize", lambda v: _resize_hwc(v, self._size), (x,))


class CenterCrop(HybridBlock):
    def __init__(self, size, interpolation=1):  # noqa: ARG002
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size

        def f(v):
            H, W = v.shape[-3], v.shape[-2]
            y0 = max((H - h) // 2, 0)
            x0 = max((W - w) // 2, 0)
            out = v[..., y0:y0 + h, x0:x0 + w, :]
            if out.shape[-3] != h or out.shape[-2] != w:
                out = _resize_hwc(out, (w, h))
            return out

        return apply_op("center_crop", f, (x,))


class CropResize(HybridBlock):
    def __init__(self, x, y, width, height, size=None, interpolation=None):  # noqa: ARG002
        super().__init__()
        self._x, self._y, self._w, self._h = x, y, width, height
        self._size = size

    def forward(self, img):
        x0, y0, w, h = self._x, self._y, self._w, self._h
        size = self._size

        def f(v):
            out = v[..., y0:y0 + h, x0:x0 + w, :]
            if size is not None:
                out = _resize_hwc(out, size)
            return out

        return apply_op("crop_resize", f, (img,))


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):  # noqa: ARG002
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import math
        import random as pyrandom

        H, W = x.shape[-3], x.shape[-2]
        area = H * W
        for _ in range(10):
            target_area = pyrandom.uniform(*self._scale) * area
            log_ratio = (math.log(self._ratio[0]), math.log(self._ratio[1]))
            aspect = math.exp(pyrandom.uniform(*log_ratio))
            w = int(round(math.sqrt(target_area * aspect)))
            h = int(round(math.sqrt(target_area / aspect)))
            if 0 < w <= W and 0 < h <= H:
                x0 = pyrandom.randint(0, W - w)
                y0 = pyrandom.randint(0, H - h)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                return apply_op("rrc",
                                lambda v: _resize_hwc(v, self._size), (crop,))
        return apply_op("rrc", lambda v: _resize_hwc(v, self._size), (x,))


class _RandomFlip(Block):
    _axis = -2

    def forward(self, x):
        import random as pyrandom

        if pyrandom.random() < 0.5:
            return x
        jnp = _jnp()
        ax = self._axis
        return apply_op("flip", lambda v: jnp.flip(v, axis=ax), (x,))


class RandomFlipLeftRight(_RandomFlip):
    _axis = -2


class RandomFlipTopBottom(_RandomFlip):
    _axis = -3


class _RandomJitter(Block):
    def __init__(self, value):
        super().__init__()
        self._value = value


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        import random as pyrandom

        alpha = 1.0 + pyrandom.uniform(-self._value, self._value)
        return apply_op("brightness", lambda v: v * alpha, (x,))


class RandomContrast(_RandomJitter):
    def forward(self, x):
        import random as pyrandom

        jnp = _jnp()
        alpha = 1.0 + pyrandom.uniform(-self._value, self._value)

        def f(v):
            gray = jnp.mean(v, axis=tuple(range(v.ndim - 3, v.ndim)),
                            keepdims=True)
            return v * alpha + gray * (1 - alpha)

        return apply_op("contrast", f, (x,))


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        import random as pyrandom

        jnp = _jnp()
        alpha = 1.0 + pyrandom.uniform(-self._value, self._value)

        def f(v):
            gray = jnp.mean(v, axis=-1, keepdims=True)
            return v * alpha + gray * (1 - alpha)

        return apply_op("saturation", f, (x,))


class RandomCrop(Block):
    """Random spatial crop, padding when the image is smaller (reference:
    `gluon/data/vision/transforms.py` RandomCrop)."""

    def __init__(self, size, pad=None, interpolation=1):  # noqa: ARG002
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        # pad: int (all four sides) or 4-tuple (left, top, right, bottom),
        # applied to the H/W dims of an HWC image (reference RandomCrop)
        if pad is not None and not isinstance(pad, int):
            pad = tuple(pad)
            if len(pad) != 4:
                raise ValueError("RandomCrop: pad must be an int or a "
                                 "(left, top, right, bottom) 4-tuple")
        self._pad = pad

    def forward(self, x):
        import random as pyrandom

        if x.ndim != 3:
            raise ValueError(f"RandomCrop expects an HWC image, got rank "
                             f"{x.ndim}")
        w, h = self._size
        if self._pad:
            p = self._pad
            widths = ((p, p), (p, p), (0, 0)) if isinstance(p, int) else \
                ((p[1], p[3]), (p[0], p[2]), (0, 0))

            def padf(v):
                import jax.numpy as jnp

                return jnp.pad(v, widths)

            x = apply_op("rc_pad", padf, (x,))
        H, W = x.shape[-3], x.shape[-2]
        if H < h or W < w:
            return apply_op("rc_resize",
                            lambda v: _resize_hwc(v, self._size), (x,))
        y0 = pyrandom.randint(0, H - h)
        x0 = pyrandom.randint(0, W - w)
        return x[..., y0:y0 + h, x0:x0 + w, :]
