"""Datasets (reference: `python/mxnet/gluon/data/dataset.py`)."""
from __future__ import annotations

import os
import struct

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([s for s in self if fn(s)])

    def shard(self, num_shards, index):
        assert 0 <= index < num_shards
        items = list(range(index, len(self), num_shards))
        return _SubsetDataset(self, items)

    def take(self, count):
        return _SubsetDataset(self, list(range(min(count, len(self)))))

    def sample(self, sampler):
        return _SubsetDataset(self, list(sampler))

    def transform(self, fn, lazy=True):  # noqa: ARG002
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(*items):
            if len(items) == 1:
                return fn(items[0])
            return (fn(items[0]),) + items[1:]

        return self.transform(first, lazy)


class _SubsetDataset(Dataset):
    def __init__(self, dataset, indices):
        self._dataset = dataset
        self._indices = indices

    def __getitem__(self, idx):
        return self._dataset[self._indices[idx]]

    def __len__(self):
        return len(self._indices)


class _LazyTransformDataset(Dataset):
    def __init__(self, dataset, fn):
        self._dataset = dataset
        self._fn = fn

    def __getitem__(self, idx):
        item = self._dataset[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)

    def __len__(self):
        return len(self._dataset)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __getitem__(self, idx):
        return self._data[idx]

    def __len__(self):
        return len(self._data)


class ArrayDataset(Dataset):
    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            assert len(a) == self._length, "all arrays must have the same length"
            self._data.append(a)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over a dmlc RecordIO file (reference: `recordio.py` +
    `gluon/data/dataset.py RecordFileDataset`). Uses the pure-python
    RecordIO reader in `incubator_mxnet_tpu.recordio`."""

    def __init__(self, filename):
        from ...recordio import IndexCreator, MXIndexedRecordIO

        self._filename = filename
        idx_file = os.path.splitext(filename)[0] + ".idx"
        if not os.path.exists(idx_file):
            creator = IndexCreator(filename, idx_file)
            creator.create_index()
            creator.close()
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
