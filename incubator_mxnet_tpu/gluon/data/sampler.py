"""Samplers (reference: `python/mxnet/gluon/data/sampler.py`)."""
from __future__ import annotations

import numpy as onp

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "FilterSampler", "ElasticSampler"]


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        return iter(onp.random.permutation(self._length).tolist())

    def __len__(self):
        return self._length


class FilterSampler(Sampler):
    def __init__(self, fn, dataset):
        self._indices = [i for i in range(len(dataset)) if fn(dataset[i])]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class ElasticSampler(Sampler):
    """Rank-sharded sampler whose shard assignment can change MID-epoch.

    Every rank holds the same seeded epoch permutation and takes the
    interleaved stride ``perm[base + pos*num_shards + index]`` (the same
    striding as ``Dataset.shard``). When an elastic topology transition
    shrinks the fleet (`fault.elastic.ElasticController`), survivors call
    :meth:`reshard` at the drained step boundary: the CONSUMED prefix of
    the permutation is frozen and only the unconsumed remainder is
    re-strided across the new world — no sample is double-fed (the prefix
    never re-enters) and none is dropped (the remainder is covered
    exactly once by the new stride).

    The consumed-prefix arithmetic assumes lockstep SPMD consumption:
    every rank has drawn the same number of samples when the transition
    runs (true at a drained train-step boundary, which is the only place
    the controller reshards).
    """

    def __init__(self, length, num_shards=1, index=0, shuffle=False,
                 seed=0):
        if not 0 <= index < num_shards:
            raise ValueError(
                f"ElasticSampler: index {index} ∉ [0, {num_shards})")
        self._perm = (onp.random.RandomState(seed).permutation(length)
                      if shuffle else onp.arange(length)).tolist()
        self._num_shards = int(num_shards)
        self._index = int(index)
        self._base = 0          # global offset of the unconsumed remainder
        self._pos = 0           # samples THIS rank drew since last reshard

    def __iter__(self):
        while True:
            g = self._base + self._pos * self._num_shards + self._index
            if g >= len(self._perm):
                return
            self._pos += 1
            yield self._perm[g]

    def __len__(self):
        # what a fresh __iter__ will still yield for THIS rank
        total = len(self._perm) - self._base - self._index
        mine = -(-total // self._num_shards) if total > 0 else 0
        return max(0, mine - self._pos)

    def reshard(self, num_shards, index, consumed=None):
        """Re-partition the unconsumed remainder across a new world.
        Call at a drained step boundary (all ranks consumed equally).

        `consumed` is the re-admission path (the GROW direction of an
        elastic transition): a rank joining mid-epoch holds a FRESH
        sampler that drew nothing locally, so the frozen prefix cannot
        be derived from ``_pos`` — the survivors broadcast the
        fleet-wide consumed count (``length - survivor.remaining()``)
        and the rejoiner passes it here. Survivors leave it None."""
        if not 0 <= index < num_shards:
            raise ValueError(
                f"ElasticSampler.reshard: index {index} ∉ [0, {num_shards})")
        if consumed is None:
            consumed = min(len(self._perm) - self._base,
                           self._pos * self._num_shards)
            self._base += consumed
        else:
            self._base = min(len(self._perm), max(0, int(consumed)))
        self._num_shards = int(num_shards)
        self._index = int(index)
        self._pos = 0

    def remaining(self):
        """Unconsumed samples fleet-wide (the remainder reshard splits)."""
        return max(0, len(self._perm) - self._base
                   - self._pos * self._num_shards)


class BatchSampler(Sampler):
    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(f"last_batch must be keep/discard/rollover, "
                                 f"got {self._last_batch}")

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        return (len(self._sampler) + len(self._prev)) // self._batch_size
