"""DataLoader (reference: `python/mxnet/gluon/data/dataloader.py`, 816 LoC —
multiprocessing workers with POSIX-shm NDArray transfer).

TPU-native design: worker processes produce *numpy* batches (host memory);
the main process uploads to device HBM asynchronously (`jax.device_put`),
which double-buffers naturally because jax dispatch is async. Large batch
arrays cross the process boundary through POSIX shared memory (the
reference's CPUSharedStorage role, `src/storage/cpu_shared_storage_
manager.h`) instead of being serialized through the pool's result pipe;
small leaves keep the plain pickle path (descriptor overhead would
dominate).
"""
from __future__ import annotations

import logging
import multiprocessing as mp

from .batchify import default_batchify_fn
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader"]

_LOG = logging.getLogger("incubator_mxnet_tpu.gluon.data")


def _suppressed(where, exc):
    """Classified, logged swallow (replaces bare `except: pass` — FL006)."""
    from ...fault.retry import suppressed

    suppressed("dataloader." + where, exc)

_worker_dataset = None
_worker_batchify = None
_worker_use_shm = True
_SHM_MIN_BYTES = 1 << 20   # leaves below 1 MB ship by pickle
_SHM_TAG = "__mxshm__"


def _worker_init(dataset, batchify_fn, use_shm=True):
    global _worker_dataset, _worker_batchify, _worker_use_shm
    _worker_dataset = dataset
    _worker_batchify = batchify_fn
    _worker_use_shm = use_shm
    # MXNET_MP_OPENCV_NUM_THREADS (env_var.md): cap cv2's internal pool
    # per worker so P workers don't spawn P x ncores decode threads
    import os

    v = os.environ.get("MXNET_MP_OPENCV_NUM_THREADS")
    if v:
        try:
            import cv2

            cv2.setNumThreads(max(0, int(v)))
        except (ImportError, ValueError):
            pass


def _export_shm(arr):
    """Worker side: copy `arr` into a fresh POSIX shm segment; ownership
    (unlink) transfers to the consumer."""
    import numpy as onp
    from multiprocessing import resource_tracker, shared_memory

    shm = shared_memory.SharedMemory(create=True, size=arr.nbytes)
    onp.ndarray(arr.shape, arr.dtype, buffer=shm.buf)[...] = arr
    name = shm.name
    shm.close()
    # the CONSUMER unlinks; stop this process's resource_tracker from
    # reporting the segment as leaked at pool shutdown
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception as e:
        _suppressed("shm.unregister", e)   # cosmetic tracker noise only
    return (_SHM_TAG, name, arr.shape, str(arr.dtype))


def _import_shm(desc):
    """Consumer side: attach, copy out, unlink."""
    import numpy as onp
    from multiprocessing import resource_tracker, shared_memory

    _tag, name, shape, dtype = desc
    shm = shared_memory.SharedMemory(name=name)
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception as e:
        _suppressed("shm.unregister", e)   # cosmetic tracker noise only
    try:
        arr = onp.array(onp.ndarray(shape, dtype, buffer=shm.buf))
    finally:
        shm.close()
        shm.unlink()
    return arr


def _unlink_shm_tree(b):
    """Release shm segments referenced by an un-imported result tree
    (consumer abandoned the iterator before wrapping the batch)."""
    if isinstance(b, tuple) and len(b) == 4 and b[0] == _SHM_TAG:
        from multiprocessing import resource_tracker, shared_memory

        try:
            shm = shared_memory.SharedMemory(name=b[1])
        except FileNotFoundError:
            return
        try:
            resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
        except Exception as e:
            _suppressed("shm.unregister", e)
        shm.close()
        shm.unlink()
    elif isinstance(b, (tuple, list)):
        for x in b:
            _unlink_shm_tree(x)


def _worker_fn(samples):
    import numpy as onp

    # chaos seams (armed from the inherited MXNET_FAULT_INJECT env by the
    # worker's own package import): 'dataloader_worker' raises — the
    # consumer's bounded retry/fallback path handles it; '..._exit' kills
    # the process outright (an OOM-kill/segfault stand-in) — the pool
    # respawns the worker and the consumer re-times-out the lost task
    from ...fault import injection

    injection.inject_at("dataloader_worker")
    if injection.injection_enabled("dataloader_worker_exit"):
        try:
            injection.inject_at("dataloader_worker_exit")
        except injection.FaultInjected:
            import os

            os._exit(3)

    batch = _worker_batchify([_worker_dataset[i] for i in samples])

    def to_numpy(b):
        if isinstance(b, (tuple, list)):
            return tuple(to_numpy(x) for x in b)
        if hasattr(b, "asnumpy"):
            b = b.asnumpy()
        arr = onp.ascontiguousarray(b)
        if _worker_use_shm and arr.nbytes >= _SHM_MIN_BYTES:
            return _export_shm(arr)
        return arr

    return to_numpy(batch)


def _mp_context():
    """Worker start method. The parent process is JAX-multithreaded by the
    time a DataLoader is built, so `fork` would deadlock in the child (the
    reference re-initialises its engine in pthread_atfork handlers instead:
    `src/initialize.cc:75-88`). `forkserver` forks workers from a clean
    single-threaded server process; `spawn` is the portable fallback."""
    import os

    method = os.environ.get("MXNET_MP_START_METHOD")
    if not method:
        methods = mp.get_all_start_methods()
        method = "forkserver" if "forkserver" in methods else "spawn"
    return mp.get_context(method)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=None, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120,
                 try_nopython=None, use_shared_memory=True):  # noqa: ARG002
        self._dataset = dataset
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        from ...util import default_worker_retries

        self._worker_retries = default_worker_retries()
        if num_workers is None:
            # env-config default ONLY when the caller didn't choose:
            # explicit num_workers=0 must stay worker-free (reference
            # MXNET_CPU_WORKER_NTHREADS semantics)
            from ...util import default_num_workers

            num_workers = default_num_workers()
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch or 2 * self._num_workers)
        self._pool = None
        if self._num_workers > 0:
            import weakref

            ctx = _mp_context()
            self._pool = self._start_pool(ctx, dataset, use_shared_memory)
            # finalizers run at atexit, BEFORE interpreter teardown strips
            # the mp module globals a late __del__ would trip over
            self._finalizer = weakref.finalize(
                self, DataLoader._terminate_pool, self._pool)

    @staticmethod
    def _terminate_pool(pool):
        try:
            pool.terminate()
            pool.join()
        except Exception as e:
            _suppressed("pool.terminate", e)   # best-effort atexit teardown

    def _start_pool(self, ctx, dataset, use_shared_memory):
        import os
        import sys

        # spawn/forkserver workers re-run __main__ from its __file__; a
        # heredoc/REPL parent reports "<stdin>", which the worker bootstrap
        # tries to open as a real path and dies. Drop the phantom path for
        # good — the pool respawns dead workers long after __init__ returns,
        # so restoring it would re-arm the crash for them (workers only need
        # importable modules, not the interactive main).
        main_mod = sys.modules.get("__main__")
        main_file = getattr(main_mod, "__file__", None)
        if (main_mod is not None and main_file is not None
                and getattr(main_mod, "__spec__", None) is None
                and not os.path.exists(main_file)):
            del main_mod.__file__

        # workers do host-side decode/augment only; if the dataset pickles
        # NDArray leaves, unpickling would initialise a jax backend in each
        # worker — on a TPU host that contends for the chip's single-client
        # lock. Children inherit env at creation: pin them to jax-CPU.
        override = {"JAX_PLATFORMS": "cpu", "JAX_PLATFORM_NAME": "cpu"}
        saved = {k: os.environ.get(k) for k in override}
        os.environ.update(override)
        try:
            return ctx.Pool(self._num_workers, initializer=_worker_init,
                            initargs=(dataset, self._batchify_fn,
                                      use_shared_memory))
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def __iter__(self):
        from ...ndarray.ndarray import NDArray
        from ...telemetry import goodput, tracing

        def wrap(b):
            if isinstance(b, tuple) and len(b) == 4 and b[0] == _SHM_TAG:
                return NDArray(_import_shm(b))
            if isinstance(b, (tuple, list)):
                return tuple(wrap(x) for x in b)
            if isinstance(b, NDArray):
                return b
            return NDArray(b)

        if self._pool is None:
            for n, batch_idx in enumerate(self._batch_sampler):
                with tracing.span("dataloader.batch", batch=n, workers=0), \
                        goodput.lease("data_wait"):
                    out = wrap(self._batchify_fn([self._dataset[i]
                                                  for i in batch_idx]))
                yield out
            return

        # pipelined: keep `prefetch` batches in flight in the pool.
        # Self-healing (fault subsystem): a failed/timed-out task is
        # retried `_worker_retries` times (the pool respawns dead worker
        # processes on its own; the resubmit is what re-schedules the lost
        # work), then falls back — LOUDLY — to computing that one batch in
        # this process. Fatal-class errors (a dataset bug raising the same
        # ValueError on every attempt would burn the budget silently)
        # propagate immediately with their classification logged.
        batches = iter(self._batch_sampler)
        in_flight = []       # entries: [samples, AsyncResult, attempts]
        abandoned = []       # timed-out futures: drain their shm at close

        def submit(samples, attempts=0, front=False):
            entry = [samples, self._pool.apply_async(_worker_fn, (samples,)),
                     attempts]
            if front:
                in_flight.insert(0, entry)
            else:
                in_flight.append(entry)

        try:
            for _ in range(self._prefetch):
                b = next(batches, None)
                if b is None:
                    break
                submit(b)
            n_yielded = 0
            while in_flight:
                # the batch-fetch segment of the trace: wait on the
                # worker's future (+ any retries) through NDArray wrap
                with tracing.span("dataloader.batch", batch=n_yielded,
                                  workers=self._num_workers), \
                        goodput.lease("data_wait"):
                    samples, fut, attempts = in_flight[0]
                    try:
                        result = fut.get(self._timeout)
                    except Exception as e:
                        in_flight.pop(0)
                        if isinstance(e, mp.TimeoutError):
                            # the task may still complete later (stuck
                            # worker): keep the future so its shm gets
                            # drained at close
                            abandoned.append([samples, fut, attempts])
                        result = self._recover_batch(samples, attempts, e)
                        if result is None:   # resubmitted (ordered: front)
                            submit(samples, attempts + 1, front=True)
                            continue
                    else:
                        in_flight.pop(0)
                    b = next(batches, None)
                    if b is not None:
                        submit(b)
                    out = wrap(result)
                n_yielded += 1
                yield out
        finally:
            # consumer abandoned the iterator (generator close / exception /
            # timeout) with batches still in flight: import-and-unlink their
            # shm segments so nothing leaks in /dev/shm until reboot. One
            # deadline across ALL futures — a stuck worker must not stall
            # generator close by 5s per prefetched batch.
            import time

            deadline = time.monotonic() + 5.0
            for _samples, fut, _attempts in in_flight + abandoned:
                try:
                    _unlink_shm_tree(
                        fut.get(max(0.0, deadline - time.monotonic())))
                except Exception as e:
                    _suppressed("shm.drain", e)   # abandoned-iterator sweep

    def _recover_batch(self, samples, attempts, exc):
        """Worker-task failure policy: classify, then retry (return None —
        the caller resubmits at the queue front to preserve batch order)
        or compute the batch in-process as the loud last resort. Fatal
        errors re-raise: a deterministic dataset bug must not be laundered
        through the retry budget."""
        from ...fault.retry import classify_exception
        from ...telemetry import registry

        kind = classify_exception(exc)
        if kind == "fatal":
            _LOG.error(
                "DataLoader worker task failed with fatal %s (samples "
                "%s..): %s — propagating, not retrying",
                type(exc).__name__, list(samples)[:4], exc)
            raise exc
        if attempts < self._worker_retries:
            registry.counter("mx_retries_total",
                             "retries taken by fault.RetryPolicy").inc()
            registry.counter("mx_retries_total",
                             "retries taken by fault.RetryPolicy",
                             labels={"policy": "dataloader"}).inc()
            _LOG.warning(
                "DataLoader worker task failed with retryable %s (attempt "
                "%d/%d): %s — resubmitting to the (respawned) pool",
                type(exc).__name__, attempts + 1, self._worker_retries, exc)
            return None
        registry.counter(
            "mx_dataloader_fallbacks_total",
            "batches recomputed in-process after worker retries").inc()
        _LOG.error(
            "DataLoader worker retries exhausted (%d) for %s: %s — "
            "falling back to single-process batchify for this batch "
            "(slow but correct)", self._worker_retries,
            type(exc).__name__, exc)
        return self._batchify_fn([self._dataset[i] for i in samples])

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        if getattr(self, "_finalizer", None) is not None:
            self._finalizer()
