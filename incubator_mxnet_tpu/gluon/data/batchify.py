"""Batchify functions (reference: `python/mxnet/gluon/data/batchify.py`)."""
from __future__ import annotations

import numpy as onp

from ...ndarray.ndarray import NDArray

__all__ = ["Stack", "Pad", "Group", "default_batchify_fn"]


def _stack_arrs(arrs):
    import jax.numpy as jnp

    if isinstance(arrs[0], NDArray):
        return NDArray(jnp.stack([a._data for a in arrs]))
    return NDArray(onp.stack([onp.asarray(a) for a in arrs]))


def default_batchify_fn(data):
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn([d[i] for d in data])
                     for i in range(len(data[0])))
    return _stack_arrs(data)


class Stack:
    def __call__(self, data):
        return _stack_arrs(data)


class Pad:
    def __init__(self, axis=0, val=0, dtype=None):
        self._axis = axis
        self._val = val
        self._dtype = dtype

    def __call__(self, data):
        arrs = [onp.asarray(d) for d in data]
        max_len = max(a.shape[self._axis] for a in arrs)
        padded = []
        for a in arrs:
            pad_width = [(0, 0)] * a.ndim
            pad_width[self._axis] = (0, max_len - a.shape[self._axis])
            padded.append(onp.pad(a, pad_width, constant_values=self._val))
        out = onp.stack(padded)
        if self._dtype is not None:
            out = out.astype(self._dtype)
        return NDArray(out)


class Group:
    def __init__(self, *fns):
        self._fns = fns

    def __call__(self, data):
        return tuple(fn([d[i] for d in data])
                     for i, fn in enumerate(self._fns))
