from .dataset import ArrayDataset, Dataset, SimpleDataset, RecordFileDataset  # noqa: F401
from .sampler import (  # noqa: F401
    BatchSampler, ElasticSampler, RandomSampler, Sampler, SequentialSampler,
    FilterSampler,
)
from .dataloader import DataLoader  # noqa: F401
from . import batchify  # noqa: F401
from . import vision  # noqa: F401
