"""Gluon utilities (reference: `python/mxnet/gluon/utils.py`)."""
from __future__ import annotations

from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "download",
           "check_sha1"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list=None, device_list=None, batch_axis=0,
                   even_split=True):
    """Split a batch across devices (reference: utils.py split_and_load).

    On TPU the idiomatic equivalent is a sharded array over the mesh; this
    helper keeps API parity by returning per-device NDArray slices."""
    devices = device_list or ctx_list
    if not isinstance(data, NDArray):
        data = NDArray(data)
    if len(devices) == 1:
        return [data.to_device(devices[0])]
    slices = split_data(data, len(devices), batch_axis, even_split)
    return [s.to_device(d) for s, d in zip(slices, devices)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    from ..numpy_extension import clip_global_norm as _impl

    return _impl(arrays, max_norm, check_isfinite)


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):  # noqa: ARG001
    raise RuntimeError(
        "download() is unavailable: this environment has no network egress. "
        "Place files locally and pass their path instead.")
