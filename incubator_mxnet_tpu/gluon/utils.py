"""Gluon utilities (reference: `python/mxnet/gluon/utils.py`)."""
from __future__ import annotations

from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "download",
           "check_sha1", "shape_is_known", "split_rnn_params"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list=None, device_list=None, batch_axis=0,
                   even_split=True):
    """Split a batch across devices (reference: utils.py split_and_load).

    On TPU the idiomatic equivalent is a sharded array over the mesh; this
    helper keeps API parity by returning per-device NDArray slices."""
    devices = device_list or ctx_list
    if not isinstance(data, NDArray):
        data = NDArray(data)
    if len(devices) == 1:
        return [data.to_device(devices[0])]
    slices = split_data(data, len(devices), batch_axis, even_split)
    return [s.to_device(d) for s, d in zip(slices, devices)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    from ..numpy_extension import clip_global_norm as _impl

    return _impl(arrays, max_norm, check_isfinite)


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):  # noqa: ARG001
    raise RuntimeError(
        "download() is unavailable: this environment has no network egress. "
        "Place files locally and pass their path instead.")


def shape_is_known(shape):
    """True when a shape tuple has no unknown (0/-1/None) dims
    (reference: gluon/utils.py shape_is_known)."""
    if shape is None:
        return False
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return all(s is not None and s > 0 for s in shape)


def split_rnn_params(params, mode, num_layers, input_size, hidden_size,
                     bidirectional=False):
    """Split a packed fused-RNN parameter vector into the per-layer
    i2h/h2h weight/bias dict (reference: gluon/utils.py
    split_rnn_params over the fused RNN op's packed layout)."""
    import numpy as _onp

    gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
    dirs = 2 if bidirectional else 1
    flat = params.asnumpy().reshape(-1) if isinstance(params, NDArray) \
        else _onp.asarray(params).reshape(-1)
    out, pos = {}, 0

    def take(n, shape):
        nonlocal pos
        v = flat[pos:pos + n].reshape(shape)
        pos += n
        return NDArray(v)

    gh = gates * hidden_size
    for layer in range(num_layers):
        for d in range(dirs):
            suffix = "_r" if d else ""
            in_sz = input_size if layer == 0 else hidden_size * dirs
            out[f"l{layer}{suffix}_i2h_weight"] = take(gh * in_sz,
                                                       (gh, in_sz))
            out[f"l{layer}{suffix}_h2h_weight"] = take(gh * hidden_size,
                                                       (gh, hidden_size))
    for layer in range(num_layers):
        for d in range(dirs):
            suffix = "_r" if d else ""
            out[f"l{layer}{suffix}_i2h_bias"] = take(gh, (gh,))
            out[f"l{layer}{suffix}_h2h_bias"] = take(gh, (gh,))
    if pos != flat.size:
        raise ValueError(
            f"split_rnn_params: packed vector has {flat.size} elements but "
            f"the {mode} layout consumes {pos}; check mode/num_layers/"
            f"input_size/hidden_size")
    return out
