"""Gluon Block / HybridBlock (reference: `python/mxnet/gluon/block.py:202,1006`).

TPU-native design of `hybridize()`:

Reference path: first call traces forward under deferred-compute into an
nnvm::Symbol, wraps it in a C++ CachedOp which optimizes (CSE, fusion,
memory plan) and replays through the imperative engine
(`block.py:1104 _build_cache`, `src/imperative/cached_op.cc:833`).

Here: first call runs eagerly (completing deferred parameter shape
inference), then the whole forward is traced by `jax.jit` into StableHLO —
XLA owns CSE/fusion/memory-planning. Mutable state is functionalized:
parameter values enter as jit arguments, auxiliary-state updates (BatchNorm
running stats) are collected by a TraceContext and returned as extra
outputs, and RNG draws fold a traced key (see `random.trace_key_scope`).
Under `autograd.record()`, one compiled call records as a single tape node
whose vjp is `jax.vjp` of the whole compiled function.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as onp

from .. import autograd
from ..device import Device
from ..ndarray.ndarray import NDArray, apply_op
from ..random import next_key, trace_key_scope
from ..utils.trace import TraceContext
from .parameter import DeferredInitializationError, Parameter

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class Block:
    """Base building block (reference: gluon/block.py:202)."""

    def __init__(self):
        self._children: OrderedDict[str, Block] = OrderedDict()
        self._reg_params: OrderedDict[str, Parameter] = OrderedDict()

    # -- attribute magic: registering children/params on assignment ---------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            existing = self.__dict__.get("_reg_params")
            if existing is not None:
                value.name = name
                existing[name] = value
        super().__setattr__(name, value)

    # -- params -------------------------------------------------------------
    def collect_params(self, select=None) -> dict:
        """name → Parameter for self and descendants (reference: block.py:340)."""
        import re

        out = {}

        def walk(block, prefix):
            for n, p in block._reg_params.items():
                out[prefix + n] = p
            for n, c in block._children.items():
                walk(c, f"{prefix}{n}.")

        walk(self, "")
        if select is not None:
            pat = re.compile(select)
            out = {k: v for k, v in out.items() if pat.match(k)}
        return out

    @property
    def params(self):
        return dict(self._reg_params)

    def initialize(self, init=None, device=None, ctx=None, verbose=False,
                   force_reinit=False):  # noqa: ARG002
        for name, p in self.collect_params().items():
            p.name = name
            p.initialize(init=None if p.init is not None else init,
                         device=device or ctx, force_reinit=force_reinit)

    def setattr(self, name, value):
        for p in self.collect_params().values():
            setattr(p, name, value)

    def register_child(self, block, name=None):
        name = name or str(len(self._children))
        self._children[name] = block

    def register_block(self, name, block):
        self._children[name] = block
        super().__setattr__(name, block)

    def apply(self, fn):
        for c in self._children.values():
            c.apply(fn)
        fn(self)
        return self

    # -- lifecycle ----------------------------------------------------------
    def cast(self, dtype):
        for p in self.collect_params().values():
            p.cast(dtype)
        for c in self._children.values():
            c.cast(dtype)

    def reset_device(self, device):
        for p in self.collect_params().values():
            p.reset_device(device)

    reset_ctx = reset_device

    def hybridize(self, active=True, **kwargs):
        for c in self._children.values():
            c.hybridize(active, **kwargs)

    def zero_grad(self):
        for p in self.collect_params().values():
            p.zero_grad()

    # -- checkpointing (reference: block.py:340 save_parameters / :379) -----
    def save_parameters(self, filename, deduplicate=False):  # noqa: ARG002
        params = self.collect_params()
        payload = {}
        for name, p in params.items():
            if p._data is not None:
                payload[name] = p.data().asnumpy()
        onp.savez(filename + ".npz" if not filename.endswith(".npz") else filename,
                  **payload)
        import os

        if not filename.endswith(".npz") and os.path.exists(filename + ".npz"):
            os.replace(filename + ".npz", filename)

    def load_parameters(self, filename, device=None, ctx=None,
                        allow_missing=False, ignore_extra=False,
                        cast_dtype=False, dtype_source="current"):  # noqa: ARG002
        params = self.collect_params()
        with onp.load(filename, allow_pickle=False) as z:
            loaded = {k: z[k] for k in z.keys()}
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise KeyError(f"Parameter {name} missing in file {filename}")
        extra = set(loaded) - set(params)
        if extra and not ignore_extra:
            raise KeyError(f"file {filename} contains extra parameters: {sorted(extra)}")

    def load_dict(self, param_dict, device=None, allow_missing=False,
                  ignore_extra=False):  # noqa: ARG002
        params = self.collect_params()
        for name, p in params.items():
            if name in param_dict:
                v = param_dict[name]
                p.set_data(v if not isinstance(v, NDArray) else v)
            elif not allow_missing:
                raise KeyError(f"Parameter {name} missing in dict")

    # -- call ---------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        try:
            return self.forward(*args, **kwargs)
        except DeferredInitializationError:
            self._deferred_infer_shape(*args, **kwargs)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            return self.forward(*args, **kwargs)

    def _deferred_infer_shape(self, *args, **kwargs):
        if hasattr(self, "infer_shape"):
            self.infer_shape(*args, **kwargs)
        else:
            raise

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary (reference: block.py summary)."""
        rows = []

        def hook(block, indent):
            name = type(block).__name__
            n_params = sum(int(onp.prod(p.shape)) for p in
                           block._reg_params.values()
                           if p.shape is not None and all(s > 0 for s in p.shape))
            rows.append(("  " * indent + name, n_params))
            for c in block._children.values():
                hook(c, indent + 1)

        hook(self, 0)
        total = sum(int(onp.prod(p.shape)) for p in self.collect_params().values()
                    if p.shape is not None and all(s > 0 for s in p.shape))
        lines = [f"{'Layer':<48}{'Params':>12}", "-" * 60]
        lines += [f"{n:<48}{p:>12}" for n, p in rows]
        lines += ["-" * 60, f"{'Total params':<48}{total:>12}"]
        out = "\n".join(lines)
        print(out)
        return out

    def __repr__(self):
        s = f"{type(self).__name__}(\n"
        for name, c in self._children.items():
            s += f"  ({name}): {type(c).__name__}\n"
        return s + ")"


class _CachedGraph:
    """Compiled forward (the CachedOp analogue). One compiled graph per
    (training-mode, input-signature); jax.jit's shape cache provides the
    per-signature part."""

    def __init__(self, block):
        self.block = block
        self.param_arrays = [p.data() for p in block.collect_params().values()]
        self._modes = {}  # training(bool) -> mode dict

    def _mode(self, training: bool):
        mode = self._modes.get(training)
        if mode is not None:
            return mode
        import jax

        block = self.block
        param_arrays = self.param_arrays
        probe = {}

        def fn(param_vals, key, *input_vals):
            saved = [(a, a._data) for a in param_arrays]
            for a, v in zip(param_arrays, param_vals):
                a._data = v
            tc = TraceContext()
            try:
                with tc, trace_key_scope(key), autograd.pause(train_mode=training):
                    wrapped = [NDArray(v) for v in input_vals]
                    out = block.forward(*wrapped)
            finally:
                for a, v in saved:
                    a._data = v
            if isinstance(out, (list, tuple)):
                out_vals = tuple(o._data for o in out)
                probe["tree"] = ("tuple", len(out_vals))
            else:
                out_vals = (out._data,)
                probe["tree"] = "single"
            aux_pairs = list(tc.updates.values())
            probe["aux_arrays"] = [a for a, _ in aux_pairs]
            return out_vals + tuple(nv for _, nv in aux_pairs)

        mode = {"jitted": jax.jit(fn), "probe": probe, "ready": False}
        self._modes[training] = mode
        return mode

    def __call__(self, args):
        mode = self._mode(autograd.is_training())
        param_vals = [a._data for a in self.param_arrays]
        input_vals = [a._data if isinstance(a, NDArray) else a for a in args]
        key = next_key()

        if not mode["ready"]:
            # warmup call populates probe (output structure + aux set)
            mode["jitted"](tuple(param_vals), key, *input_vals)
            probe = mode["probe"]
            mode["aux_arrays"] = probe["aux_arrays"]
            mode["out_tree"] = probe["tree"]
            mode["n_out"] = (1 if probe["tree"] == "single" else probe["tree"][1])
            mode["ready"] = True

        jit = mode["jitted"]
        n_out = mode["n_out"]
        aux_arrays = mode["aux_arrays"]
        n_param = len(self.param_arrays)
        n_in = len(input_vals)

        def pure(*tensor_vals):
            pv = tensor_vals[:n_param]
            iv = tensor_vals[n_param:n_param + n_in]
            return jit(tuple(pv), key, *iv)

        op_args = list(self.param_arrays) + list(args)
        outs = apply_op("cached_op", pure, tuple(op_args),
                        n_outputs=n_out + len(aux_arrays))
        if not isinstance(outs, tuple):
            outs = (outs,)
        main = outs[:n_out]
        aux_new = outs[n_out:]
        from ..utils.trace import register_aux_update

        for a, nv in zip(aux_arrays, aux_new):
            register_aux_update(a, nv._data)
        if mode["out_tree"] == "single":
            return main[0]
        return tuple(main)


class HybridBlock(Block):
    """Block that can compile its forward with XLA (reference: block.py:1006)."""

    def __init__(self):
        super().__init__()
        self._active = False
        self._cached_graph: _CachedGraph | None = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  backend=None, backend_opts=None, **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape,
                           backend=backend, backend_opts=backend_opts, **kwargs)
        self._cached_graph = None
        for c in self._children.values():
            if isinstance(c, Block) and not isinstance(c, HybridBlock):
                c.hybridize(active, **kwargs)
        # children of a hybridized block execute inside the parent's trace

    def optimize_for(self, x, *args, backend=None, **kwargs):
        self.hybridize(True, backend=backend, **kwargs)
        return self(x, *args)

    def __call__(self, *args, **kwargs):
        if not self._active or kwargs:
            return super().__call__(*args, **kwargs)
        if any(not isinstance(a, NDArray) for a in args):
            return super().__call__(*args, **kwargs)
        if self._cached_graph is None:
            # eager first call completes deferred init; then compile
            out = super().__call__(*args)
            self._cached_graph = _CachedGraph(self)
            return out
        return self._cached_graph(args)

    def export(self, path, epoch=0, remove_amp_cast=True):  # noqa: ARG002
        """Serialize for deployment (reference: block.py:1480 writes
        model-symbol.json + params; here: params + a config manifest)."""
        import json

        self.save_parameters(f"{path}-{epoch:04d}.params")
        manifest = {"class": type(self).__name__, "format": "tpu-native-v1"}
        with open(f"{path}-symbol.json", "w") as f:
            json.dump(manifest, f)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"

    def infer_shape(self, *args):
        """Subclasses with deferred params override this."""
        raise DeferredInitializationError(
            f"{type(self).__name__} cannot infer parameter shapes")

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class SymbolBlock(HybridBlock):
    """Reference parity stub: importing reference-format symbol files is not
    supported (the symbolic JSON IR is replaced by XLA/StableHLO)."""

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, device=None):
        raise NotImplementedError(
            "SymbolBlock.imports: legacy nnvm JSON graphs are not portable to "
            "the TPU-native build; re-export the model with HybridBlock.export")
