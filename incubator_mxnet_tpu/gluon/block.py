"""Gluon Block / HybridBlock (reference: `python/mxnet/gluon/block.py:202,1006`).

TPU-native design of `hybridize()`:

Reference path: first call traces forward under deferred-compute into an
nnvm::Symbol, wraps it in a C++ CachedOp which optimizes (CSE, fusion,
memory plan) and replays through the imperative engine
(`block.py:1104 _build_cache`, `src/imperative/cached_op.cc:833`).

Here: first call runs eagerly (completing deferred parameter shape
inference), then the whole forward is traced by `jax.jit` into StableHLO —
XLA owns CSE/fusion/memory-planning. Mutable state is functionalized:
parameter values enter as jit arguments, auxiliary-state updates (BatchNorm
running stats) are collected by a TraceContext and returned as extra
outputs, and RNG draws fold a traced key (see `random.trace_key_scope`).
Under `autograd.record()`, one compiled call records as a single tape node
whose vjp is `jax.vjp` of the whole compiled function.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as onp

from .. import autograd
from ..device import Device
from ..ndarray.ndarray import NDArray, apply_op
from ..random import next_key, trace_key_scope
from ..utils.trace import TraceContext
from .parameter import DeferredInitializationError, Parameter

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class Block:
    """Base building block (reference: gluon/block.py:202)."""

    def __init__(self):
        self._children: OrderedDict[str, Block] = OrderedDict()
        self._reg_params: OrderedDict[str, Parameter] = OrderedDict()

    # -- attribute magic: registering children/params on assignment ---------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            existing = self.__dict__.get("_reg_params")
            if existing is not None:
                value.name = name
                existing[name] = value
        super().__setattr__(name, value)

    # -- params -------------------------------------------------------------
    def collect_params(self, select=None) -> dict:
        """name → Parameter for self and descendants (reference: block.py:340).
        Returns a ParameterDict (dict subclass) so bulk helpers like
        zero_grad()/setattr() work on the result."""
        import re

        from .parameter import ParameterDict

        out = ParameterDict()

        def walk(block, prefix):
            for n, p in block._reg_params.items():
                out[prefix + n] = p
            for n, c in block._children.items():
                walk(c, f"{prefix}{n}.")

        walk(self, "")
        if select is not None:
            pat = re.compile(select)
            return ParameterDict({k: v for k, v in out.items()
                                  if pat.match(k)})
        return out

    def share_parameters(self, shared):
        """Rebind this block's parameters to `shared` (the dict another
        block's collect_params() returned), matching by structured name —
        tied-weight blocks after the fact (reference: block.py
        share_parameters). Missing names keep their own parameters."""
        if shared is None:
            return self

        def walk(block, prefix):
            for n in list(block._reg_params):
                full = prefix + n
                if full in shared:
                    block._reg_params[n] = shared[full]
                    setattr(block, n, shared[full])
            for n, c in block._children.items():
                walk(c, f"{prefix}{n}.")

        walk(self, "")
        return self

    @property
    def params(self):
        return dict(self._reg_params)

    def initialize(self, init=None, device=None, ctx=None, verbose=False,
                   force_reinit=False):  # noqa: ARG002
        for name, p in self.collect_params().items():
            p.name = name
            p.initialize(init=None if p.init is not None else init,
                         device=device or ctx, force_reinit=force_reinit)

    def setattr(self, name, value):
        for p in self.collect_params().values():
            setattr(p, name, value)

    def register_child(self, block, name=None):
        name = name or str(len(self._children))
        self._children[name] = block

    def register_block(self, name, block):
        self._children[name] = block
        super().__setattr__(name, block)

    def apply(self, fn):
        for c in self._children.values():
            c.apply(fn)
        fn(self)
        return self

    # -- lifecycle ----------------------------------------------------------
    def cast(self, dtype):
        for p in self.collect_params().values():
            p.cast(dtype)
        for c in self._children.values():
            c.cast(dtype)

    def reset_device(self, device):
        for p in self.collect_params().values():
            p.reset_device(device)

    reset_ctx = reset_device

    def hybridize(self, active=True, **kwargs):
        for c in self._children.values():
            c.hybridize(active, **kwargs)

    def zero_grad(self):
        for p in self.collect_params().values():
            p.zero_grad()

    def audit(self, *args, train_mode=None, **kwargs):
        """Audit this block's forward for compile-time hazards (host
        syncs, recompilation churn, promotion drift, parameter mutation)
        — `mx.analysis.audit(self, ...)`. Run a warmup forward first so
        deferred parameter initialization doesn't show up as activity
        inside the audited program."""
        from .. import analysis

        return analysis.audit(self, *args, train_mode=train_mode, **kwargs)

    # -- checkpointing (reference: block.py:340 save_parameters / :379) -----
    def save_parameters(self, filename, deduplicate=False):  # noqa: ARG002
        params = self.collect_params()
        payload = {}
        for name, p in params.items():
            if p._data is not None:
                payload[name] = p.data().asnumpy()
        # tmp-write + atomic rename + checksum sidecar (fault subsystem):
        # a preemption mid-save can never corrupt the last good .params,
        # and loads can detect truncation (preemption.verify_checkpoint)
        from .. import preemption

        def _write(tmp):
            with open(tmp, "wb") as f:
                onp.savez(f, **payload)

        preemption.atomic_save(filename, _write)

    def load_parameters(self, filename, device=None, ctx=None,
                        allow_missing=False, ignore_extra=False,
                        cast_dtype=False, dtype_source="current"):  # noqa: ARG002
        """Load parameters from npz (native) or the reference's binary
        .params container (auto-detected; `ndarray/legacy_io.py`).
        Reference checkpoints with `arg:`/`aux:` name prefixes load
        transparently (reference: block.py:419). Files written by
        `save_parameters` carry a `.crc32` sidecar; a checksum mismatch
        (truncated/corrupt file) raises MXNetError before any parameter
        is touched."""
        params = self.collect_params()
        from .. import preemption
        from ..base import MXNetError
        from ..ndarray import legacy_io

        if preemption.verify_checkpoint(filename) is False:
            raise MXNetError(
                f"parameter file {filename} failed checksum validation "
                "(truncated or corrupt) — restore a previous checkpoint "
                "generation (preemption.TrainingCheckpointer.resume does "
                "this automatically)")

        if legacy_io.is_legacy_file(filename):
            raw = legacy_io.load(filename)
            if not isinstance(raw, dict):
                raise ValueError(f"{filename} carries no parameter names")
            loaded = {}
            for k, v in raw.items():
                if k.startswith(("arg:", "aux:")):
                    k = k[4:]
                loaded[k] = v.asnumpy()
        else:
            with onp.load(filename, allow_pickle=False) as z:
                loaded = {k: z[k] for k in z.keys()}
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise KeyError(f"Parameter {name} missing in file {filename}")
        extra = set(loaded) - set(params)
        if extra and not ignore_extra:
            raise KeyError(f"file {filename} contains extra parameters: {sorted(extra)}")

    def load_dict(self, param_dict, device=None, allow_missing=False,
                  ignore_extra=False):  # noqa: ARG002
        params = self.collect_params()
        for name, p in params.items():
            if name in param_dict:
                v = param_dict[name]
                p.set_data(v if not isinstance(v, NDArray) else v)
            elif not allow_missing:
                raise KeyError(f"Parameter {name} missing in dict")

    # -- call ---------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        try:
            return self.forward(*args, **kwargs)
        except DeferredInitializationError:
            self._deferred_infer_shape(*args, **kwargs)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            return self.forward(*args, **kwargs)

    def _deferred_infer_shape(self, *args, **kwargs):
        if hasattr(self, "infer_shape"):
            self.infer_shape(*args, **kwargs)
        else:
            raise

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary (reference: block.py summary)."""
        rows = []

        def hook(block, indent):
            name = type(block).__name__
            n_params = sum(int(onp.prod(p.shape)) for p in
                           block._reg_params.values()
                           if p.shape is not None and all(s > 0 for s in p.shape))
            rows.append(("  " * indent + name, n_params))
            for c in block._children.values():
                hook(c, indent + 1)

        hook(self, 0)
        total = sum(int(onp.prod(p.shape)) for p in self.collect_params().values()
                    if p.shape is not None and all(s > 0 for s in p.shape))
        lines = [f"{'Layer':<48}{'Params':>12}", "-" * 60]
        lines += [f"{n:<48}{p:>12}" for n, p in rows]
        lines += ["-" * 60, f"{'Total params':<48}{total:>12}"]
        out = "\n".join(lines)
        print(out)
        return out

    def __repr__(self):
        s = f"{type(self).__name__}(\n"
        for name, c in self._children.items():
            s += f"  ({name}): {type(c).__name__}\n"
        return s + ")"


class _CachedGraph:
    """Compiled forward (the CachedOp analogue). One compiled graph per
    (training-mode, input-signature); jax.jit's shape cache provides the
    per-signature part."""

    def __init__(self, block):
        self.block = block
        self.param_arrays = [p.data() for p in block.collect_params().values()]
        self._modes = {}  # training(bool) -> mode dict

    def _mode(self, training: bool):
        mode = self._modes.get(training)
        if mode is not None:
            return mode
        import jax

        block = self.block
        param_arrays = self.param_arrays
        probe = {}

        def fn(param_vals, key, *input_vals):
            import jax.tree_util as jtu

            saved = [(a, a._data) for a in param_arrays]
            for a, v in zip(param_arrays, param_vals):
                a._data = v
            tc = TraceContext()
            try:
                with tc, trace_key_scope(key), autograd.pause(train_mode=training):
                    wrapped = [NDArray(v) for v in input_vals]
                    out = block.forward(*wrapped)
            finally:
                for a, v in saved:
                    a._data = v
            # outputs may be any pytree of NDArrays (tuple, nested list —
            # e.g. StochasticBlock returns (out, [losses])); flatten and
            # remember the structure for replay
            flat, treedef = jtu.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            out_vals = tuple(o._data for o in flat)
            probe["treedef"] = treedef
            probe["n_out"] = len(out_vals)
            aux_pairs = list(tc.updates.values())
            probe["aux_arrays"] = [a for a, _ in aux_pairs]
            return out_vals + tuple(nv for _, nv in aux_pairs)

        backend_name = getattr(block, "_flags", {}).get("backend")
        if backend_name:
            # partition backend (reference: optimize_for → subgraph
            # property pass): trace with ops outlined, pattern-rewrite the
            # op-level jaxpr, inline the result
            from ..partition import apply_backend, get_backend

            fn = apply_backend(fn, get_backend(backend_name))
        from .. import remat as _remat

        fn = _remat.wrap(fn, getattr(block, "_flags", {}).get("remat"))
        mode = {"jitted": jax.jit(fn), "probe": probe, "ready": False}
        self._modes[training] = mode
        return mode

    def __call__(self, args):
        mode = self._mode(autograd.is_training())
        param_vals = [a._data for a in self.param_arrays]
        input_vals = [a._data if isinstance(a, NDArray) else a for a in args]
        key = next_key()

        if not mode["ready"]:
            # warmup call populates probe (output structure + aux set);
            # its wall time is the program's trace+compile cost — feed the
            # telemetry mx_jit_compile_seconds series when imported
            import sys as _sys
            import time as _time

            _t0 = _time.perf_counter()
            mode["jitted"](tuple(param_vals), key, *input_vals)
            _dt = _time.perf_counter() - _t0
            _telem = _sys.modules.get(
                "incubator_mxnet_tpu.telemetry.registry")
            if _telem is not None:
                _telem.observe_compile(
                    f"cached_op:{type(self.block).__name__}", _dt)
            _comp = _sys.modules.get(
                "incubator_mxnet_tpu.telemetry.compiles")
            if _comp is not None:
                # compile-observatory ledger entry (per training mode —
                # the second mode's compile diffs against the first)
                _comp.record_compile(
                    f"cached_op:{type(self.block).__name__}", _dt,
                    args=(tuple(param_vals), key) + tuple(input_vals),
                    fn=mode["jitted"], observe=False)
            probe = mode["probe"]
            mode["aux_arrays"] = probe["aux_arrays"]
            mode["treedef"] = probe["treedef"]
            mode["n_out"] = probe["n_out"]
            mode["ready"] = True

        jit = mode["jitted"]
        n_out = mode["n_out"]
        aux_arrays = mode["aux_arrays"]
        n_param = len(self.param_arrays)
        n_in = len(input_vals)

        def pure(*tensor_vals):
            pv = tensor_vals[:n_param]
            iv = tensor_vals[n_param:n_param + n_in]
            return jit(tuple(pv), key, *iv)

        op_args = list(self.param_arrays) + list(args)
        outs = apply_op("cached_op", pure, tuple(op_args),
                        n_outputs=n_out + len(aux_arrays))
        if not isinstance(outs, tuple):
            outs = (outs,)
        main = outs[:n_out]
        aux_new = outs[n_out:]
        from ..utils.trace import register_aux_update

        for a, nv in zip(aux_arrays, aux_new):
            register_aux_update(a, nv._data)
        import jax.tree_util as jtu

        return jtu.tree_unflatten(mode["treedef"], main)


class HybridBlock(Block):
    """Block that can compile its forward with XLA (reference: block.py:1006)."""

    def __init__(self):
        super().__init__()
        self._active = False
        self._cached_graph: _CachedGraph | None = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  backend=None, backend_opts=None, remat=None, **kwargs):
        """`remat`: activation-rematerialization policy for the compiled
        forward (True / policy name / callable — see
        `incubator_mxnet_tpu.remat`; None consults MXNET_BACKWARD_DO_MIRROR
        / MXNET_MEMORY_OPT)."""
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape,
                           backend=backend, backend_opts=backend_opts,
                           remat=remat, **kwargs)
        self._cached_graph = None
        for c in self._children.values():
            if isinstance(c, Block) and not isinstance(c, HybridBlock):
                c.hybridize(active, **kwargs)
        # children of a hybridized block execute inside the parent's trace

    def optimize_for(self, x, *args, backend=None, backend_opts=None,
                     **kwargs):
        """Apply a registered partition backend and compile (reference:
        block.py:1190 optimize_for → C++ subgraph pass; here →
        `incubator_mxnet_tpu.partition`). The backend's block-level
        rewrite runs once, its dataflow patterns apply at trace time."""
        if backend is not None:
            from ..partition import get_backend

            get_backend(backend).rewrite_block(self, **(backend_opts or {}))
        self.hybridize(True, backend=backend, backend_opts=backend_opts,
                       **kwargs)
        self(x, *args)            # eager pass: deferred init + cache setup
        return self(x, *args)     # compiled pass: backend rewrite applies

    def __call__(self, *args, **kwargs):
        if args and all(isinstance(a, NDArray) for a in args):
            self._in_sig = [(tuple(a._data.shape), str(a._data.dtype))
                            for a in args]
        if not self._active or kwargs:
            return super().__call__(*args, **kwargs)
        if any(not isinstance(a, NDArray) for a in args):
            return super().__call__(*args, **kwargs)
        if self._cached_graph is None:
            # eager first call completes deferred init; then compile
            out = super().__call__(*args)
            self._cached_graph = _CachedGraph(self)
            return out
        return self._cached_graph(args)

    def export(self, path, epoch=0, remove_amp_cast=True):  # noqa: ARG002
        """Serialize for deployment (reference: block.py:1480 writes
        model-symbol.json + binary params).

        TPU-native: the inference forward is traced once and serialized as a
        portable StableHLO artifact via `jax.export` (`<path>-symbol.stablehlo`),
        with a JSON manifest (`<path>-symbol.json`) describing inputs/outputs
        and parameter order, plus the parameters themselves
        (`<path>-<epoch>.params`). `SymbolBlock.imports` reloads and runs the
        artifact without the original Python class.

        Note: nested output pytrees are flattened — a reimported SymbolBlock
        returns a flat tuple of output arrays (single array for one output),
        matching the reference SymbolBlock's flat-output contract even when
        the original block returned a nested structure."""
        import json
        import os

        import jax
        from jax import export as jexport

        if getattr(self, "_in_sig", None) is None:
            raise RuntimeError(
                "HybridBlock.export: run at least one forward pass first so "
                "input shapes/dtypes are known")
        params = self.collect_params()
        param_names = list(params)
        param_vals = [params[n].data()._data for n in param_names]

        cg = self._cached_graph
        if cg is None:
            cg = _CachedGraph(self)
        mode = cg._mode(False)
        jitted = mode["jitted"]
        key = jax.random.PRNGKey(0)

        def infer_fn(param_vals, *input_vals):
            return jitted(tuple(param_vals), key, *input_vals)

        import numpy as _np

        import jax.tree_util as jtu

        in_sds = [jax.ShapeDtypeStruct(s, _np.dtype(d))
                  for (s, d) in self._in_sig]
        param_sds = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in param_vals]
        out_sds = jax.eval_shape(infer_fn, param_sds, *in_sds)
        probe = mode["probe"]
        n_out = probe["n_out"]
        single = jtu.treedef_is_leaf(probe["treedef"])

        # Export the leading (batch) dimension symbolically so the artifact
        # runs at any batch size (reference SymbolBlock accepts arbitrary
        # batches). Falls back to the concrete shapes if any op in the graph
        # cannot be lowered with a symbolic dimension.
        exported = None
        dynamic_batch = False
        batch0 = self._in_sig[0][0][0] if self._in_sig[0][0] else None
        if batch0 is not None:
            try:
                (b,) = jexport.symbolic_shape("b")
                sym_sds = [
                    jax.ShapeDtypeStruct((b,) + s[1:], _np.dtype(d))
                    if s and s[0] == batch0 else
                    jax.ShapeDtypeStruct(s, _np.dtype(d))
                    for (s, d) in self._in_sig
                ]
                exported = jexport.export(jax.jit(infer_fn))(param_sds, *sym_sds)
                dynamic_batch = True
            except Exception:
                exported = None
        if exported is None:
            exported = jexport.export(jax.jit(infer_fn))(param_sds, *in_sds)
        hlo_path = f"{path}-symbol.stablehlo"
        with open(hlo_path, "wb") as f:
            f.write(exported.serialize())

        params_path = f"{path}-{epoch:04d}.params"
        self.save_parameters(params_path)
        manifest = {
            "class": type(self).__name__,
            "format": "tpu-native-stablehlo-v1",
            "artifact": os.path.basename(hlo_path),
            "param_names": param_names,
            "inputs": [[list(s), d] for (s, d) in self._in_sig],
            "n_outputs": int(n_out),
            "n_total_outputs": len(out_sds),
            "out_tree": "single" if single else "tuple",
            "dynamic_batch": dynamic_batch,
        }
        with open(f"{path}-symbol.json", "w") as f:
            json.dump(manifest, f, indent=2)
        return f"{path}-symbol.json", params_path

    def infer_shape(self, *args):
        """Subclasses with deferred params override this."""
        raise DeferredInitializationError(
            f"{type(self).__name__} cannot infer parameter shapes")

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class SymbolBlock(HybridBlock):
    """Runs a serialized model without its original Python class
    (reference: gluon/block.py:1713 SymbolBlock over symbol JSON).

    TPU-native: wraps either (a) a deserialized `jax.export` StableHLO
    artifact produced by `HybridBlock.export` — the compiled program is the
    "symbol"; parameters are plain arrays fed positionally in manifest order —
    or (b) a live `mx.sym.Symbol` graph via the reference constructor form
    ``SymbolBlock(outputs, inputs, params=...)`` (gluon/block.py:1654), in
    which case free symbol variables not listed in `inputs` become block
    Parameters and forward evaluates the graph through the op funnel (so it
    hybridizes/trains like any other block)."""

    def __init__(self, outputs, inputs=None, params=None):
        from ..symbol.symbol import Symbol as _Sym

        if isinstance(outputs, _Sym) or (
                isinstance(outputs, (list, tuple)) and outputs
                and isinstance(outputs[0], _Sym)):
            super().__init__()
            self._init_from_symbol(outputs, inputs, params)
            return
        # internal form: (exported, manifest, param_vals)
        exported, manifest, param_vals = outputs, inputs, params
        super().__init__()
        self._sym = None
        self._exported = exported
        self._manifest = manifest
        from .parameter import Parameter

        for name, v in zip(manifest["param_names"], param_vals):
            p = Parameter(shape=v.shape, dtype=str(v.dtype), name=name,
                          grad_req="null")  # inference-only: no grad buffers
            p.set_data(NDArray(v))
            self._reg_params[name] = p

    def _init_from_symbol(self, outputs, inputs, params):
        from ..symbol.symbol import Group, Symbol as _Sym
        from .parameter import Parameter

        if isinstance(outputs, (list, tuple)):
            outputs = outputs[0] if len(outputs) == 1 else Group(outputs)
        if inputs is None:
            raise ValueError("SymbolBlock(symbol, ...) requires `inputs`")
        if isinstance(inputs, _Sym):
            inputs = [inputs]
        self._sym = outputs
        self._sym_inputs = [i.name if isinstance(i, _Sym) else str(i)
                            for i in inputs]
        self._exported = None
        self._manifest = None
        params = params or {}
        aux = set(outputs.list_auxiliary_states())
        for name in outputs._all_inputs():
            if name in self._sym_inputs:
                continue
            v = params.get(name)
            if v is None:
                raise ValueError(
                    f"SymbolBlock: no value for free variable {name!r}; "
                    f"pass it in `params` or list it in `inputs`")
            v = v if isinstance(v, NDArray) else NDArray(v)
            # aux states (BN running stats) must not receive grads/updates
            p = Parameter(shape=v.shape, dtype=str(v.dtype), name=name,
                          grad_req="null" if name in aux else "write")
            p.set_data(v)
            self._reg_params[name] = p

    def forward(self, *args):
        if getattr(self, "_sym", None) is not None:
            env = {n: (a if isinstance(a, NDArray) else NDArray(a))
                   for n, a in zip(self._sym_inputs, args)}
            for name, p in self._reg_params.items():
                env[name] = p.data()
            outs = self._sym._eval(env)
            return outs[0] if len(outs) == 1 else tuple(outs)
        vals = [a._data if isinstance(a, NDArray) else a for a in args]
        pvals = [self._reg_params[n].data()._data
                 for n in self._manifest["param_names"]]
        outs = self._exported.call(pvals, *vals)
        if not isinstance(outs, (list, tuple)):
            outs = (outs,)
        n_out = self._manifest["n_outputs"]
        main = [NDArray(o) for o in outs[:n_out]]
        if self._manifest["out_tree"] == "single":
            return main[0]
        return tuple(main)

    @staticmethod
    def imports(symbol_file, input_names=None, param_file=None, device=None):  # noqa: ARG004
        """Load a model exported by `HybridBlock.export`
        (reference: gluon/block.py:1795)."""
        import json
        import os

        from jax import export as jexport

        with open(symbol_file) as f:
            manifest = json.load(f)
        if manifest.get("format") != "tpu-native-stablehlo-v1":
            raise ValueError(
                f"SymbolBlock.imports: unsupported format "
                f"{manifest.get('format')!r}; re-export with HybridBlock.export")
        base = os.path.dirname(os.path.abspath(symbol_file))
        with open(os.path.join(base, manifest["artifact"]), "rb") as f:
            exported = jexport.deserialize(f.read())
        param_vals = []
        if param_file is None and manifest["param_names"]:
            raise ValueError("SymbolBlock.imports: model has parameters; "
                             "pass param_file")
        if param_file is not None:
            import jax.numpy as jnp

            with onp.load(param_file, allow_pickle=False) as z:
                loaded = {k: z[k] for k in z.keys()}
            for name in manifest["param_names"]:
                if name not in loaded:
                    raise KeyError(f"parameter {name} missing in {param_file}")
                param_vals.append(jnp.asarray(loaded[name]))
        return SymbolBlock(exported, manifest, param_vals)
