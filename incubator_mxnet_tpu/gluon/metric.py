"""Evaluation metrics (reference: `python/mxnet/gluon/metric.py`, 1867 LoC)."""
from __future__ import annotations

import numpy as onp

from ..ndarray.ndarray import NDArray

__all__ = [
    "EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy", "F1",
    "Fbeta", "BinaryAccuracy", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy",
    "NegativeLogLikelihood", "Perplexity", "PearsonCorrelation", "PCC",
    "MeanPairwiseDistance", "MeanCosineSimilarity", "Loss", "CustomMetric",
    "create", "np",
]

_REGISTRY: dict = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    key = str(metric).lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy", "nll_loss":
               "negativeloglikelihood", "pearsonr": "pearsoncorrelation",
               "top_k_accuracy": "topkaccuracy"}
    key = aliases.get(key, key)
    if key not in _REGISTRY:
        raise ValueError(f"unknown metric {metric!r}")
    return _REGISTRY[key](*args, **kwargs)


def _to_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):  # noqa: ARG002
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        self.metrics = [create(m) for m in (metrics or [])]
        super().__init__(name, **kwargs)

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return (names, values)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        self.axis = axis
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        self.top_k = top_k
        super().__init__(f"{name}_{top_k}", **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype("int32").ravel()
            argsorted = onp.argsort(pred, axis=1)[:, -self.top_k:]
            self.sum_metric += (argsorted == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


def _binarize(pred, threshold=0.5):
    pred = _to_numpy(pred)
    if pred.ndim > 1 and pred.shape[-1] > 1:
        if pred.shape[-1] > 2:
            raise ValueError(
                "F1/Fbeta/BinaryAccuracy currently only support binary "
                "classification (got predictions over "
                f"{pred.shape[-1]} classes)")
        return pred.argmax(axis=-1).ravel()
    return (pred.ravel() > threshold).astype("int32")


@register
class Fbeta(EvalMetric):
    """F-beta score with micro/macro averaging (reference: metric.py:816
    Fbeta over metric.py:551 _ClassificationMetrics). `average='micro'`
    accumulates global tp/fp/fn; `'macro'` averages the per-update score."""

    def __init__(self, name="fbeta", beta=1, average="macro", threshold=0.5,
                 **kwargs):
        self.average = average
        self.beta = beta
        self.threshold = threshold
        super().__init__(name, **kwargs)

    def reset(self):
        self.tp = self.fp = self.fn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def _score(self, tp, fp, fn):
        prec = tp / max(tp + fp, 1)
        rec = tp / max(tp + fn, 1)
        b2 = self.beta ** 2
        denom = b2 * prec + rec
        return ((1 + b2) * prec * rec / denom) if denom > 0 else 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).ravel().astype("int32")
            pred = _binarize(pred, self.threshold)
            tp = int(((pred == 1) & (label == 1)).sum())
            fp = int(((pred == 1) & (label == 0)).sum())
            fn = int(((pred == 0) & (label == 1)).sum())
            if self.average == "micro":
                self.tp += tp
                self.fp += fp
                self.fn += fn
            else:
                self.sum_metric += self._score(tp, fp, fn)
            self.num_inst += 1

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        if self.average == "micro":
            return (self.name, self._score(self.tp, self.fp, self.fn))
        return (self.name, self.sum_metric / self.num_inst)


@register
class F1(Fbeta):
    def __init__(self, name="f1", average="macro", threshold=0.5, **kwargs):
        super().__init__(name=name, beta=1, average=average,
                         threshold=threshold, **kwargs)


@register
class BinaryAccuracy(EvalMetric):
    """Accuracy of thresholded binary predictions
    (reference: metric.py:877)."""

    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        self.threshold = threshold
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).ravel().astype("int32")
            pred = _binarize(pred, self.threshold)
            self.sum_metric += int((pred == label).sum())
            self.num_inst += len(label)


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        self.tp = self.fp = self.fn = self.tn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype("int32")
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.ravel() > 0.5).astype("int32")
            pred = pred.ravel()
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.tn += int(((pred == 0) & (label == 0)).sum())
            self.num_inst += 1

    def get(self):
        import math

        denom = math.sqrt((self.tp + self.fp) * (self.tp + self.fn)
                          * (self.tn + self.fp) * (self.tn + self.fn))
        mcc = ((self.tp * self.tn - self.fp * self.fn) / denom
               if denom > 0 else 0.0)
        return (self.name, mcc)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).reshape(pred.shape)
            self.sum_metric += onp.abs(label - pred).mean() * len(label)
            self.num_inst += len(label)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).reshape(pred.shape)
            self.sum_metric += ((label - pred) ** 2).mean() * len(label)
            self.num_inst += len(label)


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, onp.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        self.eps = eps
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype("int32")
            prob = pred[onp.arange(label.shape[0]), label]
            self.sum_metric += (-onp.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):  # noqa: ARG002
        self.ignore_label = ignore_label
        super().__init__(name=name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype("int32")
            prob = pred.reshape(-1, pred.shape[-1])[
                onp.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                keep = label != self.ignore_label
                prob = prob[keep]
            self.sum_metric += (-onp.log(prob + self.eps)).sum()
            self.num_inst += prob.shape[0]

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(onp.exp(self.sum_metric / self.num_inst)))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        self._labels = []
        self._preds = []
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_to_numpy(label).ravel())
            self._preds.append(_to_numpy(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return (self.name, float("nan"))
        l = onp.concatenate(self._labels)
        p = onp.concatenate(self._preds)
        return (self.name, float(onp.corrcoef(l, p)[0, 1]))


@register
class PCC(EvalMetric):
    """Multiclass Pearson correlation (Gorodkin's K-category correlation
    over the running confusion matrix; reference: metric.py:1595)."""

    def __init__(self, name="pcc", **kwargs):
        self.k = 2
        super().__init__(name, **kwargs)

    def reset(self):
        self.cm = onp.zeros((2, 2), dtype=onp.int64)
        self.num_inst = 0
        self.sum_metric = 0.0

    def _grow(self, k):
        if k > self.cm.shape[0]:
            new = onp.zeros((k, k), dtype=onp.int64)
            new[:self.cm.shape[0], :self.cm.shape[1]] = self.cm
            self.cm = new

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).ravel().astype("int64")
            pred = _to_numpy(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.ravel().astype("int64")
            k = int(max(label.max(initial=0), pred.max(initial=0))) + 1
            self._grow(k)
            onp.add.at(self.cm, (label, pred), 1)
            self.num_inst += len(label)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        c = self.cm.astype(onp.float64)
        n = c.sum()
        trace = onp.trace(c)
        row = c.sum(axis=1)
        col = c.sum(axis=0)
        cov_xy = trace * n - row @ col
        cov_xx = n * n - row @ row
        cov_yy = n * n - col @ col
        denom = onp.sqrt(cov_xx * cov_yy)
        return (self.name, float(cov_xy / denom) if denom > 0 else 0.0)


@register
class MeanPairwiseDistance(EvalMetric):
    """Mean p-norm distance between predictions and labels
    (reference: metric.py:1202)."""

    def __init__(self, name="mpd", p=2, **kwargs):
        self.p = p
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).reshape(pred.shape)
            pred = pred.reshape(pred.shape[0], -1)
            label = label.reshape(label.shape[0], -1)
            d = (onp.abs(pred - label) ** self.p).sum(axis=1) ** (1 / self.p)
            self.sum_metric += d.sum()
            self.num_inst += len(d)


@register
class MeanCosineSimilarity(EvalMetric):
    """Mean cosine similarity along the last axis
    (reference: metric.py:1269)."""

    def __init__(self, name="cos_sim", eps=1e-8, **kwargs):
        self.eps = eps
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).reshape(pred.shape)
            num = (pred * label).sum(axis=-1)
            den = (onp.linalg.norm(pred, axis=-1)
                   * onp.linalg.norm(label, axis=-1))
            sim = num / onp.maximum(den, self.eps)
            self.sum_metric += sim.sum()
            self.num_inst += sim.size


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = _to_numpy(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval(label, pred) into a metric
    (reference: metric.py:1807)."""
    return CustomMetric(numpy_feval, name or numpy_feval.__name__,
                        allow_extra_outputs)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):  # noqa: ARG002
        self._feval = feval
        super().__init__(f"custom({name})", **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            v = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1
