"""Evaluation metrics (reference: `python/mxnet/gluon/metric.py`, 1867 LoC)."""
from __future__ import annotations

import numpy as onp

from ..ndarray.ndarray import NDArray

__all__ = [
    "EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy", "F1",
    "MCC", "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
    "Perplexity", "PearsonCorrelation", "Loss", "create",
]

_REGISTRY: dict = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    key = str(metric).lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy", "nll_loss":
               "negativeloglikelihood", "pearsonr": "pearsoncorrelation",
               "top_k_accuracy": "topkaccuracy"}
    key = aliases.get(key, key)
    if key not in _REGISTRY:
        raise ValueError(f"unknown metric {metric!r}")
    return _REGISTRY[key](*args, **kwargs)


def _to_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):  # noqa: ARG002
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        self.metrics = [create(m) for m in (metrics or [])]
        super().__init__(name, **kwargs)

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return (names, values)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        self.axis = axis
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        self.top_k = top_k
        super().__init__(f"{name}_{top_k}", **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype("int32").ravel()
            argsorted = onp.argsort(pred, axis=1)[:, -self.top_k:]
            self.sum_metric += (argsorted == label[:, None]).any(axis=1).sum()
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", threshold=0.5, **kwargs):
        self.average = average
        self.threshold = threshold
        super().__init__(name, **kwargs)

    def reset(self):
        self.tp = self.fp = self.fn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype("int32")
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.ravel() > self.threshold).astype("int32")
            pred = pred.ravel()
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += 1

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1)
        rec = self.tp / max(self.tp + self.fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return (self.name, f1)


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        self.tp = self.fp = self.fn = self.tn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype("int32")
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.ravel() > 0.5).astype("int32")
            pred = pred.ravel()
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.tn += int(((pred == 0) & (label == 0)).sum())
            self.num_inst += 1

    def get(self):
        import math

        denom = math.sqrt((self.tp + self.fp) * (self.tp + self.fn)
                          * (self.tn + self.fp) * (self.tn + self.fn))
        mcc = ((self.tp * self.tn - self.fp * self.fn) / denom
               if denom > 0 else 0.0)
        return (self.name, mcc)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).reshape(pred.shape)
            self.sum_metric += onp.abs(label - pred).mean() * len(label)
            self.num_inst += len(label)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).reshape(pred.shape)
            self.sum_metric += ((label - pred) ** 2).mean() * len(label)
            self.num_inst += len(label)


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, onp.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        self.eps = eps
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype("int32")
            prob = pred[onp.arange(label.shape[0]), label]
            self.sum_metric += (-onp.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):  # noqa: ARG002
        self.ignore_label = ignore_label
        super().__init__(name=name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype("int32")
            prob = pred.reshape(-1, pred.shape[-1])[
                onp.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                keep = label != self.ignore_label
                prob = prob[keep]
            self.sum_metric += (-onp.log(prob + self.eps)).sum()
            self.num_inst += prob.shape[0]

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(onp.exp(self.sum_metric / self.num_inst)))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        self._labels = []
        self._preds = []
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_to_numpy(label).ravel())
            self._preds.append(_to_numpy(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return (self.name, float("nan"))
        l = onp.concatenate(self._labels)
        p = onp.concatenate(self._preds)
        return (self.name, float(onp.corrcoef(l, p)[0, 1]))


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = _to_numpy(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):  # noqa: ARG002
        self._feval = feval
        super().__init__(f"custom({name})", **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            v = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1
