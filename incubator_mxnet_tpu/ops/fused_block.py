"""Fused residual + dropout + LayerNorm as one pallas TPU kernel.

The post-LN transformer cell computes ``ln(x + dropout(h))`` twice per
layer — in BERT-base that is 24 sites, each touching a (B·T, C)
activation. Left to XLA this is 4-5 HBM passes per site forward (mask
bits, masked h, the sum, the stats, the normalize) and more backward
(saved mask read, softmax-style LN backward); profiling the seq-512
train step shows the dropout/add/LN chain costing ~45 ms of a 143 ms
step (`divide_subtract_fusion` + `convert_add_fusion` +
`multiply_reduce_fusion` lanes).

Fused: forward reads x and h ONCE, draws the dropout mask from the
on-chip hardware PRNG (`pltpu.prng_seed` / `prng_random_bits`), and
writes the normalized output plus tiny (rows,) f32 stats — 2 reads,
1 write. Backward re-seeds the same stream to recompute the mask and
the pre-norm sum (zero mask/activation residuals — the trick
`ops/dropout.py` and flash attention already use), emitting dx, dh and
the per-block dgamma/dbeta partials in one pass.

Reference role: the fused dropout-add-LN the reference gets from oneDNN
subgraph rewrites on CPU (`src/operator/subgraph/dnnl/`), built
TPU-native instead.

Off-TPU the same semantics run as plain jnp ops (jax.random mask) so
the contract is testable on the CPU mesh; bit-exact parity with the
hardware generator is impossible, matching the `ops/dropout.py`
emulation discipline.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default():
    return jax.default_backend() != "tpu"


def supports(shape, feat):
    """Last-axis LN over a lane-aligned feature dim, like ops/layer_norm."""
    return feat % 128 == 0 and feat <= 8192 and len(shape) >= 2


def _threshold(p):
    return min(int(p * 4294967296.0), 4294967295)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _mask(seed_ref, shape, threshold):
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0), seed_ref[1])
    bits = pltpu.prng_random_bits(shape)
    return bits.astype(jnp.uint32) >= jnp.uint32(threshold)


def _fwd_kernel(seed_ref, x_ref, h_ref, g_ref, b_ref,
                y_ref, m_ref, r_ref, *, threshold, scale, eps, use_rng):
    x = x_ref[...].astype(jnp.float32)
    h = h_ref[...].astype(jnp.float32)
    if use_rng:
        keep = _mask(seed_ref, x_ref.shape, threshold)
        s = x + jnp.where(keep, h * scale, 0.0)
    else:
        s = x + h
    c = s.shape[1]
    mean = jnp.sum(s, axis=1, keepdims=True) / c
    sc = s - mean
    var = jnp.sum(sc * sc, axis=1, keepdims=True) / c
    rstd = jax.lax.rsqrt(var + eps)
    y = sc * rstd * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    m_ref[...] = mean
    r_ref[...] = rstd


def _bwd_kernel(seed_ref, x_ref, h_ref, dy_ref, m_ref, r_ref, g_ref,
                dx_ref, dh_ref, dgb_ref, acc_scr, *,
                threshold, scale, eps, use_rng, n_blocks):
    del eps
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(jnp.float32)
    h = h_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    if use_rng:
        keep = _mask(seed_ref, x_ref.shape, threshold)
        s = x + jnp.where(keep, h * scale, 0.0)
    else:
        s = x + h
    mean, rstd = m_ref[...], r_ref[...]
    g = g_ref[...].astype(jnp.float32)
    c = s.shape[1]
    xhat = (s - mean) * rstd
    wdy = dy * g
    c1 = jnp.sum(wdy, axis=1, keepdims=True) / c
    c2 = jnp.sum(wdy * xhat, axis=1, keepdims=True) / c
    ds = (wdy - c1 - xhat * c2) * rstd
    dx_ref[...] = ds.astype(dx_ref.dtype)
    if use_rng:
        dh = jnp.where(keep, ds * scale, 0.0)
    else:
        dh = ds
    dh_ref[...] = dh.astype(dh_ref.dtype)
    acc_scr[0:1, :] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    acc_scr[1:2, :] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == n_blocks - 1)
    def _fini():
        dgb_ref[...] = acc_scr[...]


def _block_rows(rows, cols, itemsize):
    # sized for the BACKWARD kernel's VMEM footprint (x, h, dy upcast to
    # f32 + dx, dh + scratch, double-buffered): ~6 live f32 tiles must fit
    # the 16 MB scoped window. fwd and bwd MUST share the block size — the
    # dropout mask stream is seeded per (seed, program_id) block.
    target = max(8, (1 << 20) // max(1, cols * itemsize))
    block = max(8, min(256, target) // 8 * 8)
    return block if rows >= block else rows


def _fwd(x2d, h2d, gamma, beta, seeds, p, eps, interpret):
    rows, feat = x2d.shape
    block = _block_rows(rows, feat, x2d.dtype.itemsize)
    n_blocks = rows // block
    kernel = functools.partial(
        _fwd_kernel, threshold=_threshold(p), scale=1.0 / (1.0 - p) if p else 1.0,
        eps=eps, use_rng=p > 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
            pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
            pl.BlockSpec((1, feat), lambda i, s: (0, 0)),
            pl.BlockSpec((1, feat), lambda i, s: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
            pl.BlockSpec((block, 1), lambda i, s: (i, 0)),
            pl.BlockSpec((block, 1), lambda i, s: (i, 0)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((rows, feat), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(seeds, x2d, h2d, gamma.reshape(1, feat), beta.reshape(1, feat))


def _bwd(x2d, h2d, dy2d, mean, rstd, gamma, seeds, p, eps, interpret):
    rows, feat = x2d.shape
    block = _block_rows(rows, feat, x2d.dtype.itemsize)
    n_blocks = rows // block
    kernel = functools.partial(
        _bwd_kernel, threshold=_threshold(p),
        scale=1.0 / (1.0 - p) if p else 1.0, eps=eps, use_rng=p > 0,
        n_blocks=n_blocks)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
            pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
            pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
            pl.BlockSpec((block, 1), lambda i, s: (i, 0)),
            pl.BlockSpec((block, 1), lambda i, s: (i, 0)),
            pl.BlockSpec((1, feat), lambda i, s: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
            pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
            pl.BlockSpec((8, feat), lambda i, s: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((8, feat), jnp.float32)],
    )
    dx, dh, dgb = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((rows, feat), x2d.dtype),
            jax.ShapeDtypeStruct((rows, feat), h2d.dtype),
            jax.ShapeDtypeStruct((8, feat), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(seeds, x2d, h2d, dy2d, mean, rstd, gamma.reshape(1, feat))
    return dx, dh, dgb[0], dgb[1]


# ---------------------------------------------------------------------------
# differentiable core + public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _core(x2d, h2d, gamma, beta, seeds, p, eps, interpret):
    y, _, _ = _fwd(x2d, h2d, gamma, beta, seeds, p, eps, interpret)
    return y


def _core_fwd(x2d, h2d, gamma, beta, seeds, p, eps, interpret):
    y, mean, rstd = _fwd(x2d, h2d, gamma, beta, seeds, p, eps, interpret)
    return y, (x2d, h2d, gamma, seeds, mean, rstd)


def _core_bwd(p, eps, interpret, res, dy):
    import numpy as onp

    x2d, h2d, gamma, seeds, mean, rstd = res
    dx, dh, dg, db = _bwd(x2d, h2d, dy, mean, rstd, gamma, seeds, p, eps,
                          interpret)
    return (dx, dh, dg.astype(gamma.dtype), db.astype(gamma.dtype),
            onp.zeros(seeds.shape, jax.dtypes.float0))


_core.defvjp(_core_fwd, _core_bwd)


def _emulate(x, h, gamma, beta, seeds, p, eps):
    """Off-TPU path: identical contract via jnp + jax.random (plain
    autodiff — no custom vjp needed off-chip)."""
    import jax.random as jr

    if p > 0:
        key = jr.fold_in(jr.PRNGKey(seeds[0]), seeds[1])
        keep = jr.bits(key, x.shape, jnp.uint32) >= jnp.uint32(_threshold(p))
        s = x.astype(jnp.float32) \
            + jnp.where(keep, h.astype(jnp.float32) / (1.0 - p), 0.0)
    else:
        s = x.astype(jnp.float32) + h.astype(jnp.float32)
    mean = jnp.mean(s, axis=-1, keepdims=True)
    var = jnp.var(s, axis=-1, keepdims=True)
    y = (s - mean) * jax.lax.rsqrt(var + eps) * gamma.astype(jnp.float32) \
        + beta.astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# fused gelu + dropout (the FFN hidden-activation site)
# ---------------------------------------------------------------------------
#
# dropout(gelu(u)) on the (B·T, 4C) FFN hidden is the largest dropout in a
# transformer (402 MB bf16 at BERT-base seq-512); XLA's path writes and
# re-reads the RNG bit tensor through HBM (~200 MB per site) and saves the
# keep mask for backward. The kernel draws bits in VMEM and backward
# re-seeds the same stream — the bit/mask tensors never touch HBM.
# erf has no pallas TPU lowering, so Φ uses the Abramowitz–Stegun 7.1.26
# rational approximation (|err| < 1.5e-7 — below bf16 resolution).

_AS_P = 0.3275911
_AS_A = (0.254829592, -0.284496736, 1.421413741, -1.453152027, 1.061405429)


def _erf_approx(z):
    s = jnp.sign(z)
    za = jnp.abs(z)
    t = 1.0 / (1.0 + _AS_P * za)
    poly = t * (_AS_A[0] + t * (_AS_A[1] + t * (
        _AS_A[2] + t * (_AS_A[3] + t * _AS_A[4]))))
    return s * (1.0 - poly * jnp.exp(-za * za))


def _gelu_parts(u):
    """(gelu(u), gelu'(u)) in f32: Φ(u) via erf approx; φ(u) closed-form."""
    phi_cdf = 0.5 * (1.0 + _erf_approx(u * 0.7071067811865476))
    pdf = jnp.exp(-0.5 * u * u) * 0.3989422804014327
    return u * phi_cdf, phi_cdf + u * pdf


def _gd_fwd_kernel(seed_ref, u_ref, h_ref, *, threshold, scale, use_rng):
    u = u_ref[...].astype(jnp.float32)
    g, _ = _gelu_parts(u)
    if use_rng:
        keep = _mask(seed_ref, u_ref.shape, threshold)
        g = jnp.where(keep, g * scale, 0.0)
    h_ref[...] = g.astype(h_ref.dtype)


def _gd_bwd_kernel(seed_ref, u_ref, dy_ref, du_ref, *,
                   threshold, scale, use_rng):
    u = u_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    _, dg = _gelu_parts(u)
    if use_rng:
        keep = _mask(seed_ref, u_ref.shape, threshold)
        du = jnp.where(keep, dy * dg * scale, 0.0)
    else:
        du = dy * dg
    du_ref[...] = du.astype(du_ref.dtype)


def _gd_call(kernel, out_dtype, x2d, seeds, extra, p, interpret):
    rows, feat = x2d.shape
    block = _block_rows(rows, feat, x2d.dtype.itemsize)
    n_blocks = rows // block
    k = functools.partial(kernel, threshold=_threshold(p),
                          scale=1.0 / (1.0 - p) if p else 1.0,
                          use_rng=p > 0)
    in_specs = [pl.BlockSpec((block, feat), lambda i, s: (i, 0))
                for _ in range(1 + len(extra))]
    return pl.pallas_call(
        k,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_blocks,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((block, feat), lambda i, s: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((rows, feat), out_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(seeds, x2d, *extra)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _gd_core(u2d, seeds, p, interpret):
    return _gd_call(_gd_fwd_kernel, u2d.dtype, u2d, seeds, (), p, interpret)


def _gd_core_fwd(u2d, seeds, p, interpret):
    return _gd_core(u2d, seeds, p, interpret), (u2d, seeds)


def _gd_core_bwd(p, interpret, res, dy):
    import numpy as onp

    u2d, seeds = res
    du = _gd_call(_gd_bwd_kernel, u2d.dtype, u2d, seeds, (dy,), p, interpret)
    return du, onp.zeros(seeds.shape, jax.dtypes.float0)


_gd_core.defvjp(_gd_core_fwd, _gd_core_bwd)


def _gd_emulate(u, seeds, p):
    import jax.random as jr

    g = jax.nn.gelu(u.astype(jnp.float32), approximate=False)
    if p > 0:
        key = jr.fold_in(jr.PRNGKey(seeds[0]), seeds[1])
        keep = jr.bits(key, u.shape, jnp.uint32) >= jnp.uint32(_threshold(p))
        g = jnp.where(keep, g / (1.0 - p), 0.0)
    return g.astype(u.dtype)


def gelu_dropout(u, p, seeds, interpret=None):
    """``dropout_p(gelu(u))`` over the last axis, one fused pass with
    in-VMEM RNG (backward re-seeds the stream; no mask/bit residuals)."""
    if interpret is None:
        interpret = _interpret_default()
    if interpret:
        return _gd_emulate(u, seeds, float(p))
    shape = u.shape
    feat = shape[-1]
    rows = 1
    for s_ in shape[:-1]:
        rows *= s_
    if rows == 0:
        # empty batch: no grid to launch (block would be 0 → pad divides
        # by zero); the contract output is just the empty input shape
        return u
    u2d = u.reshape(rows, feat)
    block = _block_rows(rows, feat, u2d.dtype.itemsize)
    pad = (-rows) % block if block else 0
    if pad:
        u2d = jnp.pad(u2d, ((0, pad), (0, 0)))
    h = _gd_core(u2d, jnp.asarray(seeds, jnp.int32), float(p),
                 bool(interpret))
    if pad:
        h = h[:rows]
    return h.reshape(shape)


def residual_dropout_ln(x, h, gamma, beta, p, seeds, eps=1e-5,
                        interpret=None):
    """``layer_norm(x + dropout_p(h))`` over the last axis, one fused pass.

    x, h: same-shape activations (leading axes collapse to rows);
    gamma/beta: (C,) affine params; seeds: (2,) int32 PRNG words (a fresh
    framework key per call — reproducible under `mx.random.seed`).
    """
    if interpret is None:
        interpret = _interpret_default()
    shape = x.shape
    feat = shape[-1]
    if interpret:
        return _emulate(x, h, gamma, beta, seeds, float(p), float(eps))
    rows = 1
    for s_ in shape[:-1]:
        rows *= s_
    if rows == 0:
        # empty batch: no grid to launch (block would be 0 → pad divides
        # by zero); ln of nothing is nothing
        return x
    x2d = x.reshape(rows, feat)
    h2d = h.reshape(rows, feat)
    block = _block_rows(rows, feat, x2d.dtype.itemsize)
    pad = (-rows) % block if block else 0
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
        h2d = jnp.pad(h2d, ((0, pad), (0, 0)))
    y = _core(x2d, h2d, gamma, beta, jnp.asarray(seeds, jnp.int32),
              float(p), float(eps), bool(interpret))
    if pad:
        y = y[:rows]
    return y.reshape(shape)
