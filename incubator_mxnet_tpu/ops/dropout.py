"""Dropout as a pallas TPU kernel over the on-chip hardware RNG.

Why a kernel at all: dropout is the classic "free-looking op that isn't" —
measured on one v5e chip, BERT-base training spends ~40% of its step time
generating threefry random bits on the VPU (88k tok/s with jax.random
bernoulli dropout vs 144k with dropout off). The reference hits the same
wall differently: its GPU dropout uses cuDNN's stateful generator
(`src/operator/nn/dropout-inl.h`), not a counter-based PRNG recomputed per
element. The TPU-native answer is the per-core hardware PRNG
(`pltpu.prng_seed` / `prng_random_bits`): seed once per (call, block),
draw 32 raw bits per element, compare against a uint32 threshold.

Backward recomputes the mask from the same seed instead of saving it —
zero residual memory traffic for the mask (the same trick flash attention
uses for probabilities).

Numerics: keep-probability is exact to 2^-32; the drawn bits are
independent of the jax.random stream but deterministic given the folded-in
framework key, so `mx.random.seed` reproducibility holds.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default():
    return jax.default_backend() != "tpu"


def _mask_kernel_body(seed_ref, x_ref, o_ref, *, threshold, scale, grad):
    # distinct stream per block: fold the block index into the seed pair
    # (the TPU seed primitive takes at most two words)
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0), seed_ref[1])
    bits = pltpu.prng_random_bits(x_ref.shape)
    keep = bits.astype(jnp.uint32) >= jnp.uint32(threshold)
    src = x_ref[...]
    o_ref[...] = jnp.where(keep, src * scale, 0.0).astype(o_ref.dtype)
    del grad  # fwd and bwd bodies are identical: y = mask(x), dx = mask(dy)


def _emulate(x2d, seeds, threshold, scale):
    """Off-TPU stand-in: `pltpu.prng_seed` has no CPU lowering (not even in
    interpret mode), so non-TPU backends draw deterministically from the
    same seed pair via jax.random. Bit-exact parity with the hardware
    generator is impossible; the CONTRACT (mask/scale semantics, fwd/bwd
    mask identity, per-seed determinism) is identical and pinned by
    tests/test_dropout_kernel.py."""
    import jax.random as jr

    key = jr.fold_in(jr.PRNGKey(seeds[0]), seeds[1])
    bits = jr.bits(key, x2d.shape, jnp.uint32)
    keep = bits >= jnp.uint32(threshold)
    return jnp.where(keep, x2d * scale, 0).astype(x2d.dtype)


def _run_kernel(x2d, seeds, threshold, scale, interpret, grad):
    if interpret:
        del grad
        return _emulate(x2d, seeds, threshold, scale)
    rows, cols = x2d.shape
    # block rows sized to keep the (block, cols) tile within ~2 MB VMEM
    target = max(1, (2 << 20) // max(1, cols * x2d.dtype.itemsize))
    block = max(8, min(1024, target) // 8 * 8)  # sublane-tiled: multiple of 8
    if rows < block:
        block = rows
    grid = (rows + block - 1) // block
    return pl.pallas_call(
        functools.partial(_mask_kernel_body, threshold=threshold,
                          scale=scale, grad=grad),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((block, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(seeds, x2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _dropout_core(x2d, seeds, p, interpret):
    threshold = min(int(p * 4294967296.0), 4294967295)
    return _run_kernel(x2d, seeds, threshold, 1.0 / (1.0 - p), interpret,
                       grad=False)


def _dropout_core_fwd(x2d, seeds, p, interpret):
    return _dropout_core(x2d, seeds, p, interpret), seeds


def _dropout_core_bwd(p, interpret, seeds, dy):
    import numpy as onp

    threshold = min(int(p * 4294967296.0), 4294967295)
    dx = _run_kernel(dy, seeds, threshold, 1.0 / (1.0 - p), interpret,
                     grad=True)
    return dx, onp.zeros(seeds.shape, jax.dtypes.float0)


_dropout_core.defvjp(_dropout_core_fwd, _dropout_core_bwd)


def supports(shape, axes, dtype, p=0.5):
    """Kernel eligibility: plain (non-broadcast) dropout with 0<p<1 on
    shapes whose trailing dim tiles the 128-lane VPU; anything else falls
    back to the jax.random path."""
    if axes:
        return False
    if not jnp.issubdtype(dtype, jnp.floating):  # covers bf16 (kind 'V')
        return False
    if not 0.0 < p < 1.0:   # p=1 would divide by zero in the kernel scale;
        return False        # the jax.random fallback handles it (all-zero)
    if len(shape) == 0:
        return False
    size = 1
    for s in shape:
        size *= s
    return size >= 1024 and (shape[-1] % 128 == 0 or size % 1024 == 0)


def use_kernel(key):
    """The pallas kernel beats threefry dropout (113k vs 88k BERT tok/s on
    v5e) but loses to the fully-fused XLA path when keys are rbg-class
    (124k) — a kernel boundary costs more than hardware bit-gen saves. So:
    kernel only for threefry keys on a real TPU."""
    if jax.default_backend() != "tpu":
        return False
    if jnp.issubdtype(getattr(key, "dtype", None), jax.dtypes.prng_key):
        return "fry" in str(jax.random.key_impl(key))
    return True  # legacy uint32 key arrays are threefry


def dropout(x, key, p):
    """Hardware-RNG dropout: y = x/(1-p) where kept, 0 where dropped.

    `key` is a jax PRNG key (any impl); its raw words seed the on-chip
    generator so each framework-level draw gets an independent stream.
    """
    if jnp.issubdtype(getattr(key, "dtype", None), jax.dtypes.prng_key):
        raw = jax.random.key_data(key)
    else:
        raw = key  # legacy uint32 key array
    seeds = raw.reshape(-1)[:2].astype(jnp.int32)
    if seeds.shape[0] < 2:
        seeds = jnp.concatenate([seeds, jnp.zeros((1,), jnp.int32)])
    shape = x.shape
    if shape[-1] % 128 == 0:
        x2d = x.reshape(-1, shape[-1])
    else:
        x2d = x.reshape(-1, 1024)
    out = _dropout_core(x2d, seeds, float(p), _interpret_default())
    return out.reshape(shape)
