"""Flash attention (forward + backward) as pallas TPU kernels.

Memory-linear attention: O(T) live memory instead of the O(T^2) score
matrix, with the online-softmax recurrence. Forward saves only the
per-row logsumexp; backward recomputes probabilities blockwise.

Reference role: the fused self-attention the reference only has as a CPU
oneDNN subgraph (`src/operator/subgraph/dnnl/dnnl_transformer_qk_property.h`,
`dnnl_transformer_valid_mask.cc`); here it is a first-class TPU kernel
feeding the MXU with (block_q × block_k) bf16 tiles and f32 accumulators.

Structure: 3D grid (batch·heads, q-blocks, kv-blocks). The kv axis is the
innermost ("arbitrary") dimension; running max / sum / output accumulate in
VMEM scratch across kv steps and spill to HBM once per q-block, so VMEM
usage is independent of sequence length. Pallas double-buffers the K/V
block DMAs against compute. Causal masking skips fully-masked kv blocks.

Layout: q/k/v are (batch, heads, seq, head_dim). Padding/causal masking is
expressed with a per-sequence `lengths` vector, not a dense (T, T) mask —
a dense mask would defeat the memory linearity.

On CPU backends (the virtual 8-device test mesh) the kernels run in
pallas interpret mode, so numerics are testable without a TPU.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1.0e30  # finite stand-in for -inf: keeps exp()/max() NaN-free


def _interpret_default():
    return jax.default_backend() != "tpu"


def _round_up(x, m):
    return (x + m - 1) // m * m


def _dot(a, b, ta=False, tb=False):
    """Tile matmul on the MXU in the operands' dtype, f32 accumulation."""
    dims = (((0 if ta else 1,), (1 if tb else 0,)), ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *,
                sm_scale, causal, block_q, block_k, n_kv, need_mask,
                have_lengths):
    qi, kj = pl.program_id(1), pl.program_id(2)
    kv_len = len_ref[pl.program_id(0)]

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: skip kv blocks strictly above the diagonal band
    run = (kj * block_k <= (qi + 1) * block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0]                                   # (bq, D) input dtype
        k = k_ref[0]
        v = v_ref[0]
        s = _dot(q, k, tb=True) * sm_scale             # (bq, bk) f32
        if need_mask:
            rows = (qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
            cols = (kj * block_k
                    + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            mask = cols < kv_len
            if causal:
                mask = jnp.logical_and(mask, cols <= rows)
            s = jnp.where(mask, s, NEG_INF)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        if need_mask:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + _dot(p.astype(v.dtype), v)

    @pl.when(kj == n_kv - 1)
    def _fini():
        m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
        l_safe = jnp.where(l > 0, l, 1.0)
        o = acc / l_safe
        if have_lengths:
            # self-attention row-validity: zero rows past the sequence
            # length; +inf lse makes backward's exp(s - lse) vanish there
            rows = (qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
            valid = rows < kv_len
            o = jnp.where(valid, o, 0.0)
            lse = jnp.where(jnp.logical_and(l > 0, valid),
                            m + jnp.log(l_safe), jnp.inf)
        elif need_mask:
            # kv padding / causal only: rows stay live; guard empty rows
            lse = jnp.where(l > 0, m + jnp.log(l_safe), jnp.inf)
        else:
            lse = m + jnp.log(l_safe)
        o_ref[0] = o.astype(o_ref.dtype)
        lse_ref[0] = lse


def _fwd(q, k, v, lens, sm_scale, causal, block_q, block_k, interpret,
         need_mask, have_lengths):
    bh, tq, d = q.shape
    tk = k.shape[1]
    n_q, n_kv = tq // block_q, tk // block_k
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, n_kv=n_kv, need_mask=need_mask,
        have_lengths=have_lengths)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, lens: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, lens: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, lens: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, lens: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j, lens: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(lens, q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *,
               sm_scale, causal, block_q, block_k, n_kv, need_mask):
    qi, kj = pl.program_id(1), pl.program_id(2)
    kv_len = len_ref[pl.program_id(0)]

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (kj * block_k <= (qi + 1) * block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]
        lse, delta = lse_ref[0], delta_ref[0]          # (bq, 1) f32
        s = _dot(q, k, tb=True) * sm_scale
        if need_mask:
            rows = (qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
            cols = (kj * block_k
                    + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            mask = cols < kv_len
            if causal:
                mask = jnp.logical_and(mask, cols <= rows)
            p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        else:
            p = jnp.exp(s - lse)
        dp = _dot(do, v, tb=True)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_scr[...] = dq_scr[...] + _dot(ds, k)

    @pl.when(kj == n_kv - 1)
    def _fini():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *,
                sm_scale, causal, block_q, block_k, n_q, need_mask):
    kj, qi = pl.program_id(1), pl.program_id(2)
    kv_len = len_ref[pl.program_id(0)]

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = (kj * block_k <= (qi + 1) * block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0]
        lse, delta = lse_ref[0], delta_ref[0]
        s = _dot(q, k, tb=True) * sm_scale             # (bq, bk)
        if need_mask:
            rows = (qi * block_q
                    + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
            cols = (kj * block_k
                    + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            mask = cols < kv_len
            if causal:
                mask = jnp.logical_and(mask, cols <= rows)
            p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        else:
            p = jnp.exp(s - lse)
        dv_scr[...] = dv_scr[...] + _dot(p.astype(do.dtype), do, ta=True)
        dp = _dot(do, v, tb=True)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_scr[...] = dk_scr[...] + _dot(ds, q, ta=True)

    @pl.when(qi == n_q - 1)
    def _fini():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, lens, do, sm_scale, causal, block_q, block_k,
         interpret, need_mask):
    bh, tq, d = q.shape
    tk = k.shape[1]
    n_q, n_kv = tq // block_q, tk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)             # (bh, tq, 1)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_kv=n_kv,
                          need_mask=need_mask),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, n_q, n_kv),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda b, i, j, lens: (b, i, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda b, i, j, lens: (b, j, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda b, i, j, lens: (b, j, 0)),
                pl.BlockSpec((1, block_q, d),
                             lambda b, i, j, lens: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda b, i, j, lens: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda b, i, j, lens: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, j, lens: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(lens, q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q=n_q,
                          need_mask=need_mask),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, n_kv, n_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda b, j, i, lens: (b, i, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda b, j, i, lens: (b, j, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda b, j, i, lens: (b, j, 0)),
                pl.BlockSpec((1, block_q, d),
                             lambda b, j, i, lens: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda b, j, i, lens: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda b, j, i, lens: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d),
                             lambda b, j, i, lens: (b, j, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda b, j, i, lens: (b, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(lens, q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_core(q, k, v, lens, sm_scale, causal, block_q, block_k, interpret,
                need_mask, have_lengths):
    o, _ = _fwd(q, k, v, lens, sm_scale, causal, block_q, block_k, interpret,
                need_mask, have_lengths)
    return o


def _flash_core_fwd(q, k, v, lens, sm_scale, causal, block_q, block_k,
                    interpret, need_mask, have_lengths):
    o, lse = _fwd(q, k, v, lens, sm_scale, causal, block_q, block_k,
                  interpret, need_mask, have_lengths)
    return o, (q, k, v, o, lse, lens)


def _flash_core_bwd(sm_scale, causal, block_q, block_k, interpret, need_mask,
                    have_lengths, res, do):
    q, k, v, o, lse, lens = res
    dq, dk, dv = _bwd(q, k, v, o, lse, lens, do, sm_scale, causal,
                      block_q, block_k, interpret, need_mask)
    import numpy as onp

    dlens = onp.zeros(lens.shape, jax.dtypes.float0)
    return dq, dk, dv, dlens


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# attention matrices up to this many bytes take the XLA path under
# impl="auto" — XLA's own fusion pipeline is flash-like and measured
# faster than the pallas kernel on-chip (T=4096 f32: ~12 ms vs ~15 ms;
# T=16384: ~76 ms vs ~3.3 s); past the cliff XLA fails to compile the
# T² buffer (T=32768 f32 → 34 GB) and the streaming pallas kernel is
# the only option.
_XLA_ATTN_BYTES_LIMIT = 2 << 30


def _xla_attention(q, k, v, lengths, causal, sm_scale, layout="bhtd"):
    """Same semantics as the pallas kernel, expressed as plain jnp ops —
    XLA fuses the softmax(QKᵀ)V pipeline itself.

    `layout="bthd"` contracts directly from the projection layout
    (batch, seq, heads, head_dim) — the head/seq "transpose" folds into
    the dot_general instead of materializing a relayout copy of the
    (B, T, C)-sized tensor (measured ~13 ms/step of `copy` ops in the
    seq-512 BERT profile with explicit transposes)."""
    if layout == "bthd":
        b, tq, h, d = q.shape
        tk = k.shape[1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
        neg = jnp.asarray(jnp.finfo(s.dtype).min / 2, s.dtype)
        if causal:
            mask = jnp.tril(jnp.ones((tq, tk), bool))
            s = jnp.where(mask, s, neg)
        if lengths is not None:
            lens = jnp.asarray(lengths, jnp.int32).reshape(b)
            kmask = jnp.arange(tk)[None, :] < lens[:, None]
            s = jnp.where(kmask[:, None, None, :], s, neg)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        if lengths is not None:
            qmask = jnp.arange(tq)[None, :] < lens[:, None]
            o = jnp.where(qmask[:, :, None, None], o, 0.0)
        return o
    b, h, tq, d = q.shape
    tk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    # finite mask constant in the score dtype: -1e30 would overflow f16/
    # bf16 to -inf and give NaN softmax rows (and NaN grads) on padded
    # sequences — same finite-NEG_INF discipline as the pallas kernel
    neg = jnp.asarray(jnp.finfo(s.dtype).min / 2, s.dtype)
    if causal:
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        s = jnp.where(mask, s, neg)
    if lengths is not None:
        lens = jnp.asarray(lengths, jnp.int32).reshape(b)
        kmask = jnp.arange(tk)[None, :] < lens[:, None]      # (B, Tk)
        s = jnp.where(kmask[:, None, None, :], s, neg)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    if lengths is not None:
        qmask = jnp.arange(tq)[None, :] < lens[:, None]      # (B, Tq)
        o = jnp.where(qmask[:, None, :, None], o, 0.0)
    return o


def flash_attention(q, k, v, lengths=None, causal=False, sm_scale=None,
                    block_q=512, block_k=512, interpret=None, impl="auto",
                    layout="bhtd"):
    """Fused scaled-dot-product attention.

    - `layout`: "bhtd" (B, H, T, D) or "bthd" (B, T, H, D — the natural
      output of a fused qkv projection; the XLA path contracts it
      directly so no head transpose is ever materialized, and the
      output comes back in (B, T, H, D) ready to collapse to (B, T, C)).
    - `lengths`: optional (B,) int32 valid sequence lengths (key padding AND
      query-row masking, self-attention semantics — the flash replacement
      for `npx.masked_softmax` with a valid_length mask).
    - `causal`: lower-triangular masking for decoder/LM use.
    - `impl`: "auto" picks the XLA-fused path while the T² attention
      matrix fits (see `_XLA_ATTN_BYTES_LIMIT`) and the O(T)-memory
      pallas streaming kernel beyond; "xla"/"pallas" force a path.
    - Differentiable on both paths (pallas via custom_vjp backward
      kernels, XLA via ordinary autodiff of the fused graph).
    """
    if layout == "bthd":
        b, t_q, h, d = q.shape
        t_k = k.shape[1]
    else:
        b, h, t_q, d = q.shape
        t_k = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if impl == "auto":
        attn_bytes = b * h * t_q * t_k * jnp.dtype(q.dtype).itemsize
        impl = "xla" if attn_bytes <= _XLA_ATTN_BYTES_LIMIT else "pallas"
    if impl == "xla":
        return _xla_attention(q, k, v, lengths, bool(causal),
                              float(sm_scale), layout=layout)
    if impl != "pallas":
        raise ValueError(f"flash_attention: unknown impl {impl!r}")
    if layout == "bthd":
        # the streaming kernel wants heads-major blocks; one relayout is
        # noise next to the O(T²) compute that forces the pallas path
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        o = flash_attention(q, k, v, lengths=lengths, causal=causal,
                            sm_scale=sm_scale, block_q=block_q,
                            block_k=block_k, interpret=interpret,
                            impl="pallas", layout="bhtd")
        return o.transpose(0, 2, 1, 3)
    tq = t_q
    tk = t_k
    if interpret is None:
        interpret = _interpret_default()

    block_q = min(block_q, _round_up(tq, 8))
    block_k = min(block_k, _round_up(tk, 8))
    tq_pad = _round_up(tq, block_q)
    tk_pad = _round_up(tk, block_k)

    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    if tq_pad != tq:
        qr = jnp.pad(qr, ((0, 0), (0, tq_pad - tq), (0, 0)))
    if tk_pad != tk:
        kr = jnp.pad(kr, ((0, 0), (0, tk_pad - tk), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, tk_pad - tk), (0, 0)))

    if lengths is None:
        lens = jnp.full((b,), tk, jnp.int32)
    else:
        lens = jnp.asarray(lengths, jnp.int32).reshape(b)
    lens = jnp.repeat(lens, h)                         # (BH,)

    need_mask = bool(causal) or lengths is not None or tk_pad != tk
    o = _flash_core(qr, kr, vr, lens, float(sm_scale), bool(causal),
                    int(block_q), int(block_k), bool(interpret),
                    need_mask, lengths is not None)
    return o[:, :tq].reshape(b, h, tq, d)


def mha_flash(q, k, v, lengths=None, causal=False, sm_scale=None):
    """(B*H, T, D)-layout convenience wrapper matching `npx.batch_dot`
    attention code: caller flattens heads; lengths must already be per
    (B*H) row or None."""
    bh, t, d = q.shape
    o = flash_attention(q[:, None], k[:, None], v[:, None],
                        lengths=lengths, causal=causal, sm_scale=sm_scale)
    return o[:, 0]
