"""Custom pallas TPU kernels — the perf-critical fused ops.

Reference role: the hand-fused kernels the reference gets from
oneDNN/cuDNN subgraph properties (`src/operator/subgraph/dnnl/
dnnl_transformer_qk_property.h`, `dnnl_conv.cc`) and NVRTC pointwise
fusion (`src/operator/fusion/fused_op.cc`). On TPU, XLA already fuses
elementwise epilogues; pallas covers what XLA cannot schedule well by
itself — memory-linear (flash) attention over long sequences.
"""
from .flash_attention import flash_attention, mha_flash

__all__ = ["flash_attention", "mha_flash"]
