"""Fused LayerNorm as pallas TPU kernels (forward + backward).

Why a kernel: XLA lowers layer norm to a stats reduction followed by a
broadcast-consuming normalize — two full HBM passes over the activation
forward and four-plus backward (measured ~0.7 ms per LN on a
(32, 512, 768) bf16 BERT activation; 24 LNs ≈ 17 ms of a 143 ms train
step, the single largest non-matmul block after the funnel fusions).
The reference has the same fusion as a handwritten CPU/GPU kernel
(`src/operator/nn/layer_norm.cc` LayerNormCompute, with the oneDNN and
GPU fused paths); the TPU-native answer keeps a row-block of the
activation in VMEM, computes mean/variance there, and writes the
normalized output in the same pass — ONE read + ONE write forward.

Backward recomputes x̂ from the saved (mean, rstd) row stats — tiny
(R,) f32 residuals instead of a second activation-sized buffer — and
emits dx in one fused pass plus per-block partial sums for
dgamma/dbeta (summed by a cheap XLA reduce over the block axis).

Layout contract: normalization over the LAST axis, feature size a
multiple of 128 (the VPU lane width); anything else falls back to the
XLA path in `npx.layer_norm`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default():
    return jax.default_backend() != "tpu"


def supports(shape, axis, feat):
    """Kernel eligibility: last-axis norm, lane-aligned feature dim."""
    ndim = len(shape)
    if axis not in (-1, ndim - 1):
        return False
    return feat % 128 == 0 and feat <= 8192


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, m_ref, r_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                 # (bR, C)
    c = x.shape[1]
    mean = jnp.sum(x, axis=1, keepdims=True) / c
    xc = x - mean
    var = jnp.sum(xc * xc, axis=1, keepdims=True) / c
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    m_ref[...] = mean
    r_ref[...] = rstd


def _fwd(x2d, gamma, beta, eps, block_r, interpret):
    rows, feat = x2d.shape
    n_blocks = rows // block_r
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((block_r, feat), lambda i: (i, 0)),
            pl.BlockSpec((1, feat), lambda i: (0, 0)),
            pl.BlockSpec((1, feat), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_r, feat), lambda i: (i, 0)),
            pl.BlockSpec((block_r, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_r, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, feat), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, gamma.reshape(1, feat), beta.reshape(1, feat))
    return y, mean, rstd


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_kernel(x_ref, dy_ref, m_ref, r_ref, g_ref,
                dx_ref, dgb_ref, acc_scr, *, n_blocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mean, rstd = m_ref[...], r_ref[...]
    g = g_ref[...].astype(jnp.float32)
    c = x.shape[1]
    xhat = (x - mean) * rstd
    wdy = dy * g
    c1 = jnp.sum(wdy, axis=1, keepdims=True) / c
    c2 = jnp.sum(wdy * xhat, axis=1, keepdims=True) / c
    dx = (wdy - c1 - xhat * c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # param-grad partials accumulate in VMEM across the (sequential) grid:
    # row 0 holds dgamma, row 1 dbeta; spilled to HBM once at the end
    acc_scr[0:1, :] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    acc_scr[1:2, :] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == n_blocks - 1)
    def _fini():
        dgb_ref[...] = acc_scr[...]


def _bwd(x2d, dy2d, mean, rstd, gamma, block_r, interpret):
    rows, feat = x2d.shape
    n_blocks = rows // block_r
    dx, dgb = pl.pallas_call(
        functools.partial(_bwd_kernel, n_blocks=n_blocks),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((block_r, feat), lambda i: (i, 0)),
            pl.BlockSpec((block_r, feat), lambda i: (i, 0)),
            pl.BlockSpec((block_r, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_r, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, feat), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_r, feat), lambda i: (i, 0)),
            pl.BlockSpec((8, feat), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, feat), x2d.dtype),
            jax.ShapeDtypeStruct((8, feat), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((8, feat), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2d, dy2d, mean, rstd, gamma.reshape(1, feat))
    return dx, dgb[0], dgb[1]


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln_core(x2d, gamma, beta, eps, block_r, interpret):
    y, _, _ = _fwd(x2d, gamma, beta, eps, block_r, interpret)
    return y


def _ln_core_fwd(x2d, gamma, beta, eps, block_r, interpret):
    y, mean, rstd = _fwd(x2d, gamma, beta, eps, block_r, interpret)
    return y, (x2d, gamma, mean, rstd)


def _ln_core_bwd(eps, block_r, interpret, res, dy):
    x2d, gamma, mean, rstd = res
    dx, dg, db = _bwd(x2d, dy, mean, rstd, gamma, block_r, interpret)
    return dx, dg.astype(gamma.dtype), db.astype(gamma.dtype)


_ln_core.defvjp(_ln_core_fwd, _ln_core_bwd)


def layer_norm(x, gamma, beta, eps=1e-5, block_r=256, interpret=None):
    """Fused last-axis layer norm over an arbitrary-rank tensor.

    Leading axes collapse to rows; rows pad up to the block size (padded
    rows normalize garbage that is sliced away — their stats never touch
    real rows). Differentiable via the fused backward kernels.
    """
    if interpret is None:
        interpret = _interpret_default()
    shape = x.shape
    feat = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    x2d = x.reshape(rows, feat)
    block = min(block_r, rows) if rows else block_r
    pad = (-rows) % block if block else 0
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    y = _ln_core(x2d, gamma, beta, float(eps), int(block), bool(interpret))
    if pad:
        y = y[:rows]
    return y.reshape(shape)
