// C++ frontend (reference: `cpp-package/include/mxnet-cpp/MxNetCpp.h` —
// NDArray/Context/Predictor over the C API, ~10.7k LoC).
//
// TPU-native design: the reference's C++ frontend wraps libmxnet's C ABI;
// this build's runtime is the Python/jax/XLA stack, so the C++ frontend
// EMBEDS the CPython runtime (stable documented API only) and drives the
// same framework objects a Python user gets — one implementation, no
// drift between language frontends. Compute still runs on the TPU via
// XLA; the embedding only crosses the API boundary, never the math.
//
// Scope (documented): inference + NDArray math + training.
//   - Runtime        : interpreter lifecycle (RAII)
//   - Context        : cpu()/tpu() device handles
//   - NDArray        : construct / arithmetic / Dot / Sum / Argmax /
//                      Softmax / CopyTo host
//   - Predictor      : gluon model_zoo model (+ optional .params file) or
//                      an exported SymbolBlock artifact; Forward()
//   - Net/Optimizer/Trainer : training from C++ (reference:
//                      cpp-package optimizer.hpp/executor.hpp) — the
//                      gluon autograd/Trainer loop via the `_cpp_train`
//                      bridge; see example/mlp_train.cc
//
// Build: g++ -std=c++17 app.cc $(python3-config --embed --cflags --ldflags)
#ifndef MXNET_CPP_MXNETCPP_H_
#define MXNET_CPP_MXNETCPP_H_

#include <Python.h>

#include <cstddef>
#include <stdexcept>
#include <string>
#include <vector>

namespace mxnet {
namespace cpp {

inline void _throw_py(const std::string& where) {
  PyErr_Print();
  throw std::runtime_error("mxnet-cpp: python failure in " + where);
}

class Runtime {
 public:
  // module_path: directory holding the incubator_mxnet_tpu package
  explicit Runtime(const std::string& module_path = "") {
    if (!Py_IsInitialized()) {
      Py_Initialize();
    }
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    if (!module_path.empty()) {
      PyObject* p = PyUnicode_FromString(module_path.c_str());
      PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
    mx_ = PyImport_ImportModule("incubator_mxnet_tpu");
    if (!mx_) _throw_py("import incubator_mxnet_tpu");
    np_ = PyObject_GetAttrString(mx_, "np");
    if (!np_) _throw_py("mx.np");
  }

  ~Runtime() {
    Py_XDECREF(np_);
    Py_XDECREF(mx_);
    // Py_Finalize is deliberately NOT called: the jax/XLA runtime keeps
    // background dispatch threads that make interpreter finalization
    // unsafe (fatal "_Py_GetConfig without GIL" on teardown). Process
    // exit reclaims everything — the policy most embedding hosts use.
  }

  PyObject* mx() const { return mx_; }
  PyObject* np() const { return np_; }

  static Runtime& Get() {
    static Runtime rt;
    return rt;
  }

 private:
  PyObject* mx_ = nullptr;
  PyObject* np_ = nullptr;
};

class Context {
 public:
  static Context cpu() { return Context("cpu"); }
  static Context tpu() { return Context("tpu"); }
  static Context gpu() { return Context("tpu"); }  // alias: accelerator
  const std::string& type() const { return type_; }

 private:
  explicit Context(std::string t) : type_(std::move(t)) {}
  std::string type_;
};

class NDArray {
 public:
  NDArray() = default;
  // takes ownership of a framework NDArray PyObject
  explicit NDArray(PyObject* obj) : obj_(obj) {}
  NDArray(const NDArray& o) : obj_(o.obj_) { Py_XINCREF(obj_); }
  NDArray& operator=(const NDArray& o) {
    if (this != &o) {
      Py_XDECREF(obj_);
      obj_ = o.obj_;
      Py_XINCREF(obj_);
    }
    return *this;
  }
  NDArray(NDArray&& o) noexcept : obj_(o.obj_) { o.obj_ = nullptr; }
  NDArray& operator=(NDArray&& o) noexcept {
    if (this != &o) {
      Py_XDECREF(obj_);
      obj_ = o.obj_;
      o.obj_ = nullptr;
    }
    return *this;
  }
  ~NDArray() { Py_XDECREF(obj_); }

  // host data -> device array
  NDArray(const std::vector<float>& data, const std::vector<size_t>& shape) {
    PyObject* list = PyList_New(static_cast<Py_ssize_t>(data.size()));
    for (size_t i = 0; i < data.size(); ++i)
      PyList_SET_ITEM(list, static_cast<Py_ssize_t>(i),
                      PyFloat_FromDouble(data[i]));
    PyObject* flat =
        PyObject_CallMethod(Runtime::Get().np(), "array", "O", list);
    Py_DECREF(list);
    if (!flat) _throw_py("np.array");
    PyObject* shp = PyTuple_New(static_cast<Py_ssize_t>(shape.size()));
    for (size_t i = 0; i < shape.size(); ++i)
      PyTuple_SET_ITEM(shp, static_cast<Py_ssize_t>(i),
                       PyLong_FromSize_t(shape[i]));
    obj_ = PyObject_CallMethod(flat, "reshape", "(O)", shp);
    Py_DECREF(flat);
    Py_DECREF(shp);
    if (!obj_) _throw_py("reshape");
  }

  static NDArray Zeros(const std::vector<size_t>& shape) {
    return FromFactory("zeros", shape);
  }
  static NDArray Ones(const std::vector<size_t>& shape) {
    return FromFactory("ones", shape);
  }

  std::vector<size_t> Shape() const {
    PyObject* shp = PyObject_GetAttrString(obj_, "shape");
    if (!shp) _throw_py("shape");
    std::vector<size_t> out(PyTuple_Size(shp));
    for (size_t i = 0; i < out.size(); ++i)
      out[i] = PyLong_AsSize_t(
          PyTuple_GetItem(shp, static_cast<Py_ssize_t>(i)));
    Py_DECREF(shp);
    return out;
  }

  size_t Size() const {
    size_t n = 1;
    for (size_t s : Shape()) n *= s;
    return n;
  }

  // synchronize + copy to host
  void CopyTo(std::vector<float>* out) const {
    PyObject* np_arr = PyObject_CallMethod(obj_, "asnumpy", nullptr);
    if (!np_arr) _throw_py("asnumpy");
    PyObject* flat = PyObject_CallMethod(np_arr, "ravel", nullptr);
    Py_DECREF(np_arr);
    PyObject* lst = PyObject_CallMethod(flat, "tolist", nullptr);
    Py_DECREF(flat);
    if (!lst) _throw_py("tolist");
    Py_ssize_t n = PyList_Size(lst);
    out->resize(static_cast<size_t>(n));
    for (Py_ssize_t i = 0; i < n; ++i)
      (*out)[static_cast<size_t>(i)] =
          static_cast<float>(PyFloat_AsDouble(PyList_GetItem(lst, i)));
    Py_DECREF(lst);
  }

  NDArray Binary(const char* op, const NDArray& rhs) const {
    PyObject* r = PyObject_CallMethod(Runtime::Get().np(),
                                      op, "OO", obj_, rhs.obj_);
    if (!r) _throw_py(op);
    return NDArray(r);
  }

  NDArray operator+(const NDArray& r) const { return Binary("add", r); }
  NDArray operator-(const NDArray& r) const { return Binary("subtract", r); }
  NDArray operator*(const NDArray& r) const { return Binary("multiply", r); }
  NDArray operator/(const NDArray& r) const { return Binary("divide", r); }
  NDArray Dot(const NDArray& r) const { return Binary("dot", r); }

  NDArray Sum() const { return Unary("sum"); }
  NDArray Exp() const { return Unary("exp"); }
  NDArray AsType(const std::string& dtype) const {
    PyObject* r = PyObject_CallMethod(obj_, "astype", "s", dtype.c_str());
    if (!r) _throw_py("astype");
    return NDArray(r);
  }
  NDArray ArgmaxChannel() const {
    PyObject* r = PyObject_CallMethod(Runtime::Get().np(), "argmax", "Oi",
                                      obj_, -1);
    if (!r) _throw_py("argmax");
    return NDArray(r);
  }

  float Scalar() const {
    std::vector<float> v;
    CopyTo(&v);
    if (v.empty()) throw std::runtime_error("empty array");
    return v[0];
  }

  void WaitToRead() const {
    PyObject* r = PyObject_CallMethod(obj_, "wait_to_read", nullptr);
    if (!r) _throw_py("wait_to_read");
    Py_DECREF(r);
  }

  PyObject* handle() const { return obj_; }

 private:
  NDArray Unary(const char* op) const {
    PyObject* r = PyObject_CallMethod(Runtime::Get().np(), op, "O", obj_);
    if (!r) _throw_py(op);
    return NDArray(r);
  }

  static NDArray FromFactory(const char* fn,
                             const std::vector<size_t>& shape) {
    PyObject* shp = PyTuple_New(static_cast<Py_ssize_t>(shape.size()));
    for (size_t i = 0; i < shape.size(); ++i)
      PyTuple_SET_ITEM(shp, static_cast<Py_ssize_t>(i),
                       PyLong_FromSize_t(shape[i]));
    // "(O)" so the shape TUPLE arrives as one argument (a bare "O"
    // tuple would be unpacked as the whole argument list)
    PyObject* r =
        PyObject_CallMethod(Runtime::Get().np(), fn, "(O)", shp);
    Py_DECREF(shp);
    if (!r) _throw_py(fn);
    return NDArray(r);
  }

  PyObject* obj_ = nullptr;
};

// Inference driver (reference: cpp-package Predictor examples):
// either a gluon model_zoo architecture (+ optional trained .params), or
// a SymbolBlock artifact produced by HybridBlock.export.
class Predictor {
 public:
  static Predictor FromModelZoo(const std::string& name,
                                const std::string& params_file = "") {
    Runtime& rt = Runtime::Get();
    PyObject* gluon = PyObject_GetAttrString(rt.mx(), "gluon");
    PyObject* zoo = PyObject_GetAttrString(gluon, "model_zoo");
    PyObject* vision = PyObject_GetAttrString(zoo, "vision");
    PyObject* net = PyObject_CallMethod(vision, "get_model", "s",
                                        name.c_str());
    Py_DECREF(vision);
    Py_DECREF(zoo);
    Py_DECREF(gluon);
    if (!net) _throw_py("get_model");
    if (params_file.empty()) {
      PyObject* r = PyObject_CallMethod(net, "initialize", nullptr);
      if (!r) _throw_py("initialize");
      Py_DECREF(r);
    } else {
      PyObject* r = PyObject_CallMethod(net, "load_parameters", "s",
                                        params_file.c_str());
      if (!r) _throw_py("load_parameters");
      Py_DECREF(r);
    }
    return Predictor(net);
  }

  // exported artifact: `net.export(path)` wrote path-symbol.json (+params)
  static Predictor FromExport(const std::string& symbol_json,
                              const std::string& params_file = "") {
    Runtime& rt = Runtime::Get();
    PyObject* gluon = PyObject_GetAttrString(rt.mx(), "gluon");
    PyObject* sb = PyObject_GetAttrString(gluon, "SymbolBlock");
    Py_DECREF(gluon);
    PyObject* net;
    if (params_file.empty())
      net = PyObject_CallMethod(sb, "imports", "s", symbol_json.c_str());
    else
      net = PyObject_CallMethod(sb, "imports", "sOs", symbol_json.c_str(),
                                Py_None, params_file.c_str());
    Py_DECREF(sb);
    if (!net) _throw_py("SymbolBlock.imports");
    return Predictor(net);
  }

  // any python-side model factory, e.g. ("incubator_mxnet_tpu.models.gpt",
  // "gpt_tiny") — for architectures outside the vision zoo
  static Predictor FromFactory(const std::string& module,
                               const std::string& factory,
                               const std::string& params_file = "") {
    Runtime::Get();
    PyObject* mod = PyImport_ImportModule(module.c_str());
    if (!mod) _throw_py("import " + module);
    PyObject* net = PyObject_CallMethod(mod, factory.c_str(), nullptr);
    Py_DECREF(mod);
    if (!net) _throw_py(factory);
    PyObject* r = params_file.empty()
        ? PyObject_CallMethod(net, "initialize", nullptr)
        : PyObject_CallMethod(net, "load_parameters", "s",
                              params_file.c_str());
    if (!r) _throw_py(params_file.empty() ? "initialize"
                                          : "load_parameters");
    Py_DECREF(r);
    return Predictor(net);
  }

  // KV-cache text generation (serving path, `models/decoding.py`): the
  // wrapped net must expose .generate, e.g. GPTModel. One compiled XLA
  // program per shape signature; greedy unless do_sample.
  NDArray Generate(const NDArray& tokens, int max_new_tokens,
                   bool do_sample = false, int top_k = 0,
                   double temperature = 1.0, long seed = -1) const {
    PyObject* kwargs = PyDict_New();
    PyDict_SetItemString(kwargs, "do_sample",
                         do_sample ? Py_True : Py_False);
    if (top_k > 0) {
      PyObject* k = PyLong_FromLong(top_k);
      PyDict_SetItemString(kwargs, "top_k", k);
      Py_DECREF(k);
    }
    PyObject* t = PyFloat_FromDouble(temperature);
    PyDict_SetItemString(kwargs, "temperature", t);
    Py_DECREF(t);
    if (seed >= 0) {
      PyObject* s = PyLong_FromLong(seed);
      PyDict_SetItemString(kwargs, "seed", s);
      Py_DECREF(s);
    }
    PyObject* meth = PyObject_GetAttrString(net_, "generate");
    if (!meth) { Py_DECREF(kwargs); _throw_py("generate"); }
    PyObject* args = Py_BuildValue("(Oi)", tokens.handle(),
                                   max_new_tokens);
    PyObject* out = PyObject_Call(meth, args, kwargs);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    Py_DECREF(meth);
    if (!out) _throw_py("generate");
    return NDArray(out);
  }

  NDArray Forward(const NDArray& input) const {
    PyObject* out = PyObject_CallFunctionObjArgs(net_, input.handle(),
                                                 nullptr);
    if (!out) _throw_py("forward");
    if (PyTuple_Check(out)) {  // multi-output heads: take the first
      PyObject* first = PyTuple_GetItem(out, 0);
      Py_INCREF(first);
      Py_DECREF(out);
      return NDArray(first);
    }
    return NDArray(out);
  }

  void Hybridize() const {
    PyObject* r = PyObject_CallMethod(net_, "hybridize", nullptr);
    if (!r) _throw_py("hybridize");
    Py_DECREF(r);
  }

  ~Predictor() { Py_XDECREF(net_); }
  Predictor(const Predictor& o) : net_(o.net_) { Py_XINCREF(net_); }
  Predictor& operator=(const Predictor&) = delete;
  Predictor(Predictor&& o) noexcept : net_(o.net_) { o.net_ = nullptr; }

 private:
  explicit Predictor(PyObject* net) : net_(net) {}
  PyObject* net_ = nullptr;
};

// ---------------------------------------------------------------------------
// Training surface (reference: cpp-package optimizer.hpp / executor.hpp —
// full C++ training over Symbol/Executor/Optimizer). Here the gluon
// autograd/Trainer loop is driven through the `_cpp_train` bridge module:
// one training implementation for both language frontends.
// ---------------------------------------------------------------------------

// Optimizer spec (reference: OptimizerRegistry::Find("sgd") +
// SetParam("lr", ...)). Any registered framework optimizer name works
// ("sgd", "adam", "adamw", "lamb", ...). The name is validated against
// the registry AT CONSTRUCTION (reference parity: OptimizerRegistry::
// Find returns nullptr immediately) — a typo throws here, not minutes
// later when the Trainer takes its first step.
class Optimizer {
 public:
  Optimizer(const std::string& name, double learning_rate)
      : name_(name), lr_(learning_rate) {
    Runtime::Get();
    PyObject* bridge = PyImport_ImportModule("incubator_mxnet_tpu._cpp_train");
    if (!bridge) _throw_py("import _cpp_train");
    PyObject* ok = PyObject_CallMethod(bridge, "check_optimizer", "s",
                                       name.c_str());
    Py_DECREF(bridge);
    if (!ok) _throw_py("unknown optimizer '" + name + "'");
    Py_DECREF(ok);
  }
  const std::string& name() const { return name_; }
  double lr() const { return lr_; }

 private:
  std::string name_;
  double lr_;
};

// Trainable network handle: built from any Python-side factory
// (module, fn, int args...), e.g. the bridge's make_mlp(hidden, classes).
class Net {
 public:
  Net(const std::string& module, const std::string& factory,
      const std::vector<long>& int_args) {
    Runtime::Get();
    PyObject* mod = PyImport_ImportModule(module.c_str());
    if (!mod) _throw_py("import " + module);
    PyObject* args = PyTuple_New(static_cast<Py_ssize_t>(int_args.size()));
    for (size_t i = 0; i < int_args.size(); ++i)
      PyTuple_SET_ITEM(args, static_cast<Py_ssize_t>(i),
                       PyLong_FromLong(int_args[i]));
    PyObject* fn = PyObject_GetAttrString(mod, factory.c_str());
    Py_DECREF(mod);
    if (!fn) { Py_DECREF(args); _throw_py(factory); }
    net_ = PyObject_Call(fn, args, nullptr);
    Py_DECREF(fn);
    Py_DECREF(args);
    if (!net_) _throw_py(factory);
  }

  NDArray Forward(const NDArray& x) const {
    PyObject* out = PyObject_CallFunctionObjArgs(net_, x.handle(), nullptr);
    if (!out) _throw_py("forward");
    return NDArray(out);
  }

  void SaveParameters(const std::string& path) const {
    PyObject* r = PyObject_CallMethod(net_, "save_parameters", "s",
                                      path.c_str());
    if (!r) _throw_py("save_parameters");
    Py_DECREF(r);
  }

  void LoadParameters(const std::string& path) const {
    PyObject* r = PyObject_CallMethod(net_, "load_parameters", "s",
                                      path.c_str());
    if (!r) _throw_py("load_parameters");
    Py_DECREF(r);
  }

  PyObject* handle() const { return net_; }

  ~Net() { Py_XDECREF(net_); }
  Net(const Net& o) : net_(o.net_) { Py_XINCREF(net_); }
  Net& operator=(const Net&) = delete;
  Net(Net&& o) noexcept : net_(o.net_) { o.net_ = nullptr; }

 private:
  PyObject* net_ = nullptr;
};

// gluon.Trainer + SoftmaxCrossEntropyLoss driven from C++ (reference:
// the cpp-package training loop: exec->Forward/Backward + opt->Update).
class Trainer {
 public:
  Trainer(const Net& net, const Optimizer& opt) : net_(net.handle()) {
    Py_XINCREF(net_);
    bridge_ = PyImport_ImportModule("incubator_mxnet_tpu._cpp_train");
    if (!bridge_) _throw_py("import _cpp_train");
    PyObject* pair = PyObject_CallMethod(
        bridge_, "make_trainer", "Osd", net_, opt.name().c_str(), opt.lr());
    if (!pair) _throw_py("make_trainer");
    trainer_ = PyTuple_GetItem(pair, 0);
    loss_fn_ = PyTuple_GetItem(pair, 1);
    Py_INCREF(trainer_);
    Py_INCREF(loss_fn_);
    Py_DECREF(pair);
  }

  // one fwd+bwd+update step; returns the mean loss
  double Step(const NDArray& x, const NDArray& y, long batch_size) const {
    PyObject* loss = PyObject_CallMethod(
        bridge_, "train_step", "OOOOOl", net_, trainer_, loss_fn_,
        x.handle(), y.handle(), batch_size);
    if (!loss) _throw_py("train_step");
    double v = PyFloat_AsDouble(loss);
    Py_DECREF(loss);
    return v;
  }

  ~Trainer() {
    Py_XDECREF(loss_fn_);
    Py_XDECREF(trainer_);
    Py_XDECREF(bridge_);
    Py_XDECREF(net_);
  }
  Trainer(const Trainer&) = delete;
  Trainer& operator=(const Trainer&) = delete;

 private:
  PyObject* net_ = nullptr;
  PyObject* bridge_ = nullptr;
  PyObject* trainer_ = nullptr;
  PyObject* loss_fn_ = nullptr;
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_CPP_MXNETCPP_H_
