// C++ frontend end-to-end check (reference:
// `cpp-package/example/mlp_cpu.cpp` shape): NDArray math + model_zoo
// inference through the embedded runtime. Prints PASS lines consumed by
// tests/test_cpp_package.py.
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "mxnet-cpp/MxNetCpp.h"

using mxnet::cpp::NDArray;
using mxnet::cpp::Predictor;
using mxnet::cpp::Runtime;

int main(int argc, char** argv) {
  const char* repo = argc > 1 ? argv[1] : ".";
  Runtime rt(repo);

  // --- NDArray math ---
  NDArray a({1.f, 2.f, 3.f, 4.f}, {2, 2});
  NDArray b = NDArray::Ones({2, 2});
  NDArray c = a.Dot(b) + a;
  std::vector<float> host;
  c.CopyTo(&host);
  // a@ones + a = [[3,3],[7,7]] + [[1,2],[3,4]] = [[4,5],[10,11]]
  if (host.size() == 4 && host[0] == 4.f && host[1] == 5.f &&
      host[2] == 10.f && host[3] == 11.f) {
    std::printf("PASS ndarray_math\n");
  } else {
    std::printf("FAIL ndarray_math %f %f %f %f\n", host[0], host[1],
                host[2], host[3]);
    return 1;
  }
  float s = a.Sum().Scalar();
  if (s == 10.f) {
    std::printf("PASS ndarray_sum\n");
  } else {
    std::printf("FAIL ndarray_sum %f\n", s);
    return 1;
  }

  // --- model_zoo inference ---
  Predictor net = Predictor::FromModelZoo("mobilenetv2_0.25");
  NDArray x = NDArray::Zeros({1, 3, 32, 32});
  NDArray out = net.Forward(x);
  std::vector<size_t> shape = out.Shape();
  if (shape.size() == 2 && shape[0] == 1 && shape[1] == 1000) {
    std::printf("PASS model_zoo_forward\n");
  } else {
    std::printf("FAIL model_zoo_forward\n");
    return 1;
  }
  // --- KV-cache text generation (serving path) ---
  Predictor gpt = Predictor::FromFactory(
      "incubator_mxnet_tpu.models.gpt", "gpt_tiny");
  NDArray prompt =
      NDArray({1.f, 2.f, 3.f, 4.f}, {1, 4}).AsType("int32");
  NDArray seq = gpt.Generate(prompt, 6);
  std::vector<size_t> gshape = seq.Shape();
  if (gshape.size() == 2 && gshape[0] == 1 && gshape[1] == 10) {
    std::printf("PASS gpt_generate\n");
  } else {
    std::printf("FAIL gpt_generate\n");
    return 1;
  }

  std::printf("ALL OK\n");
  return 0;
}
