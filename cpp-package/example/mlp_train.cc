// C++ TRAINING example (reference: cpp-package/example/mlp.cpp — builds
// an MLP from Symbols and trains it with Executor + Optimizer; here the
// same loop drives gluon autograd/Trainer through the embedded runtime).
//
//   mlp_train <repo_root>
//
// Trains a 2-layer MLP on deterministic synthetic 4-class data and
// prints PASS lines the test asserts on (loss must drop >30% and final
// train accuracy must beat 0.9).
#include <cstdio>
#include <stdexcept>
#include <vector>

#include "mxnet-cpp/MxNetCpp.h"

using mxnet::cpp::NDArray;
using mxnet::cpp::Net;
using mxnet::cpp::Optimizer;
using mxnet::cpp::Runtime;
using mxnet::cpp::Trainer;

int main(int argc, char** argv) {
  Runtime rt(argc > 1 ? argv[1] : "");

  const long kHidden = 32, kClasses = 4, kN = 256, kDim = 16;
  Net net("incubator_mxnet_tpu._cpp_train", "make_mlp",
          {kHidden, kClasses});

  // synthetic separable data from the bridge (no dataset egress)
  PyObject* bridge = PyImport_ImportModule("incubator_mxnet_tpu._cpp_train");
  if (!bridge) return 1;
  PyObject* pair = PyObject_CallMethod(bridge, "toy_classification",
                                       "llll", kN, kDim, kClasses, 0L);
  if (!pair) { PyErr_Print(); return 1; }
  NDArray x(PyTuple_GetItem(pair, 0));
  NDArray y(PyTuple_GetItem(pair, 1));
  Py_INCREF(x.handle());
  Py_INCREF(y.handle());
  Py_DECREF(pair);
  Py_DECREF(bridge);

  // fail-fast contract: a typo'd optimizer name must throw at Optimizer
  // CONSTRUCTION, not at the first training step
  bool threw = false;
  try {
    Optimizer bogus("definitely_not_an_optimizer", 0.1);
  } catch (const std::runtime_error&) {
    threw = true;
    PyErr_Clear();
  }
  if (threw) std::printf("PASS optimizer_failfast\n");

  Trainer trainer(net, Optimizer("sgd", 0.1));
  double first = 0, last = 0;
  for (int epoch = 0; epoch < 30; ++epoch) {
    last = trainer.Step(x, y, kN);
    if (epoch == 0) first = last;
  }
  std::printf("loss %.4f -> %.4f\n", first, last);
  if (last < 0.7 * first) std::printf("PASS train_loss_drops\n");

  // train accuracy through the C++ forward path
  NDArray pred = net.Forward(x).ArgmaxChannel().AsType("int32");
  std::vector<float> p, t;
  pred.CopyTo(&p);
  y.CopyTo(&t);
  int hit = 0;
  for (size_t i = 0; i < p.size(); ++i) hit += (p[i] == t[i]);
  double acc = static_cast<double>(hit) / static_cast<double>(p.size());
  std::printf("train accuracy %.3f\n", acc);
  if (acc > 0.9) std::printf("PASS train_accuracy\n");

  // checkpoint round-trip from C++
  net.SaveParameters("/tmp/mlp_train_cpp.params");
  Net net2("incubator_mxnet_tpu._cpp_train", "make_mlp",
           {kHidden, kClasses});
  // deferred init: one forward before loading shaped parameters
  net2.Forward(x);
  net2.LoadParameters("/tmp/mlp_train_cpp.params");
  NDArray pred2 = net2.Forward(x).ArgmaxChannel().AsType("int32");
  std::vector<float> p2;
  pred2.CopyTo(&p2);
  bool same = p2.size() == p.size();
  for (size_t i = 0; same && i < p.size(); ++i) same = (p[i] == p2[i]);
  if (same) std::printf("PASS params_roundtrip\n");

  std::printf("ALL OK\n");
  return 0;
}
