"""Contrib operator corpus depth tests (reference:
`src/operator/contrib/` — transformer interleaved matmuls, Longformer
sliding-window attention, CTC, Hawkes, count_sketch, STE, index ops).

CTC is validated against torch.nn.functional.ctc_loss (an independent
implementation of the same recursion); the attention ops against
plain-numpy einsum oracles.
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np, npx


def _r(*shape, seed=0, scale=1.0):
    return np.array((onp.random.RandomState(seed)
                     .uniform(-1, 1, shape) * scale).astype("float32"))


def test_quadratic():
    x = _r(2, 3)
    out = npx.quadratic(x, a=2.0, b=-1.0, c=0.5)
    xn = x.asnumpy()
    onp.testing.assert_allclose(out.asnumpy(), 2 * xn * xn - xn + 0.5,
                                rtol=1e-6)


def test_index_copy():
    old = np.zeros((5, 3))
    new = _r(2, 3)
    idx = np.array(onp.array([1, 3], "int32"))
    out = npx.index_copy(old, idx, new)
    expect = onp.zeros((5, 3), "float32")
    expect[[1, 3]] = new.asnumpy()
    onp.testing.assert_allclose(out.asnumpy(), expect)


def test_index_array():
    x = np.zeros((2, 3))
    out = npx.index_array(x)
    assert out.shape == (2, 3, 2)
    assert out.asnumpy()[1, 2].tolist() == [1, 2]
    out2 = npx.index_array(x, axes=(1,))
    assert out2.shape == (2, 3, 1)
    assert out2.asnumpy()[1, 2, 0] == 2


def test_gradientmultiplier_scales_grad_only():
    x = _r(3)
    x.attach_grad()
    with autograd.record():
        y = npx.gradientmultiplier(x, scalar=-0.5)
        loss = (y * y).sum()
    loss.backward()
    onp.testing.assert_allclose(y.asnumpy(), x.asnumpy(), rtol=1e-6)
    onp.testing.assert_allclose(x.grad.asnumpy(), -0.5 * 2 * x.asnumpy(),
                                rtol=1e-5)


def test_ste_ops():
    x = np.array(onp.array([-1.4, -0.2, 0.6, 2.3], "float32"))
    x.attach_grad()
    with autograd.record():
        y = npx.round_ste(x)
        y.backward()
    onp.testing.assert_allclose(y.asnumpy(), [-1, 0, 1, 2])
    onp.testing.assert_allclose(x.grad.asnumpy(), onp.ones(4))
    x.attach_grad()
    with autograd.record():
        z = npx.sign_ste(x)
        z.backward()
    onp.testing.assert_allclose(z.asnumpy(), [-1, -1, 1, 1])
    onp.testing.assert_allclose(x.grad.asnumpy(), onp.ones(4))


def test_count_sketch():
    d, od = 6, 4
    x = _r(3, d)
    h = np.array(onp.array([0, 1, 1, 3, 0, 2], "int32"))
    s = np.array(onp.array([1, -1, 1, 1, -1, 1], "float32"))
    out = npx.count_sketch(x, h, s, out_dim=od)
    expect = onp.zeros((3, od), "float32")
    xn, hn, sn = x.asnumpy(), h.asnumpy(), s.asnumpy()
    for j in range(d):
        expect[:, hn[j]] += sn[j] * xn[:, j]
    onp.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5,
                                atol=1e-6)


def test_all_finite():
    ok = npx.all_finite(_r(3, 3))
    assert float(ok.asnumpy()[0]) == 1.0
    bad = np.array(onp.array([1.0, onp.inf], "float32"))
    assert float(npx.all_finite(bad).asnumpy()[0]) == 0.0
    both = npx.multi_all_finite([_r(2), bad])
    assert float(both.asnumpy()[0]) == 0.0


def test_dynamic_reshape():
    x = _r(2, 6)
    shp = np.array(onp.array([3, 4], "int64"))
    assert npx.dynamic_reshape(x, shp).shape == (3, 4)


def test_softsign_pad_norm_slice_add_n():
    x = _r(2, 3)
    onp.testing.assert_allclose(
        npx.softsign(x).asnumpy(),
        x.asnumpy() / (1 + onp.abs(x.asnumpy())), rtol=1e-6)
    p = npx.pad(_r(1, 1, 2, 2), mode="constant",
                pad_width=(0, 0, 0, 0, 1, 1, 1, 1), constant_value=9.0)
    assert p.shape == (1, 1, 4, 4)
    assert p.asnumpy()[0, 0, 0, 0] == 9.0
    n = npx.norm(x, ord=2, axis=1)
    onp.testing.assert_allclose(
        n.asnumpy(), onp.linalg.norm(x.asnumpy(), axis=1), rtol=1e-5)
    s = npx.slice(x, begin=(0, 1), end=(2, 3))
    onp.testing.assert_allclose(s.asnumpy(), x.asnumpy()[0:2, 1:3])
    parts = npx.slice_channel(_r(2, 4), num_outputs=2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (2, 2)
    tot = npx.add_n(x, x, x)
    onp.testing.assert_allclose(tot.asnumpy(), 3 * x.asnumpy(), rtol=1e-6)


def test_adaptive_avg_pooling2d():
    x = _r(1, 2, 6, 6)
    out = npx.adaptive_avg_pooling2d(x, output_size=2)
    assert out.shape == (1, 2, 2, 2)
    onp.testing.assert_allclose(
        out.asnumpy()[0, 0, 0, 0],
        x.asnumpy()[0, 0, :3, :3].mean(), rtol=1e-5)
    # global pooling
    g = npx.adaptive_avg_pooling2d(x, output_size=1)
    onp.testing.assert_allclose(
        g.asnumpy()[0, 1, 0, 0], x.asnumpy()[0, 1].mean(), rtol=1e-5)


def test_bilinear_resize2d_align_corners():
    x = np.array(onp.arange(4, dtype="float32").reshape(1, 1, 2, 2))
    out = npx.bilinear_resize2d(x, height=3, width=3)
    expect = onp.array([[0, .5, 1], [1, 1.5, 2], [2, 2.5, 3]], "float32")
    onp.testing.assert_allclose(out.asnumpy()[0, 0], expect, rtol=1e-5)


def test_interleaved_matmul_selfatt_roundtrip():
    t, b, h, hd = 5, 2, 3, 4
    qkv = _r(t, b, 3 * h * hd)
    att = npx.interleaved_matmul_selfatt_qk(qkv, heads=h)
    assert att.shape == (b * h, t, t)
    # oracle
    x = qkv.asnumpy().reshape(t, b, h, 3, hd)
    q, k, v = x[..., 0, :], x[..., 1, :], x[..., 2, :]
    expect = onp.einsum("tbhd,sbhd->bhts", q, k) / onp.sqrt(hd)
    onp.testing.assert_allclose(att.asnumpy(),
                                expect.reshape(b * h, t, t), rtol=1e-4,
                                atol=1e-5)
    out = npx.interleaved_matmul_selfatt_valatt(qkv, att, heads=h)
    assert out.shape == (t, b, h * hd)
    ctx = onp.einsum("bhts,sbhd->tbhd", expect, v).reshape(t, b, h * hd)
    onp.testing.assert_allclose(out.asnumpy(), ctx, rtol=1e-4, atol=1e-5)


def test_interleaved_matmul_encdec():
    tq, tk, b, h, hd = 4, 6, 2, 2, 3
    q = _r(tq, b, h * hd)
    kv = _r(tk, b, 2 * h * hd, seed=1)
    att = npx.interleaved_matmul_encdec_qk(q, kv, heads=h)
    assert att.shape == (b * h, tq, tk)
    qn = q.asnumpy().reshape(tq, b, h, hd)
    kvn = kv.asnumpy().reshape(tk, b, h, 2, hd)
    expect = onp.einsum("tbhd,sbhd->bhts", qn,
                        kvn[..., 0, :]) / onp.sqrt(hd)
    onp.testing.assert_allclose(att.asnumpy(),
                                expect.reshape(b * h, tq, tk),
                                rtol=1e-4, atol=1e-5)
    out = npx.interleaved_matmul_encdec_valatt(kv, att, heads=h)
    assert out.shape == (tq, b, h * hd)


def test_div_sqrt_dim():
    x = _r(2, 8)
    onp.testing.assert_allclose(npx.div_sqrt_dim(x).asnumpy(),
                                x.asnumpy() / onp.sqrt(8), rtol=1e-6)


def _sldwin_oracle_score(q, k, dil, w, symmetric):
    b, t, h, hd = q.shape
    wl = 2 * w + 1 if symmetric else w + 1
    out = onp.zeros((b, t, h, wl), "float32")
    for bi in range(b):
        for i in range(t):
            for hi in range(h):
                for j in range(wl):
                    pos = i + (j - w) * dil[hi]
                    if 0 <= pos < t:
                        out[bi, i, hi, j] = q[bi, i, hi] @ k[bi, pos, hi]
    return out


@pytest.mark.parametrize("symmetric", [True, False])
def test_sldwin_atten(symmetric):
    b, t, h, hd, w = 2, 7, 2, 3, 2
    q, k, v = _r(b, t, h, hd), _r(b, t, h, hd, seed=1), \
        _r(b, t, h, hd, seed=2)
    dil = np.array(onp.array([1, 2], "int32"))
    score = npx.sldwin_atten_score(q, k, dil, w=w, symmetric=symmetric)
    expect = _sldwin_oracle_score(q.asnumpy(), k.asnumpy(),
                                  dil.asnumpy(), w, symmetric)
    onp.testing.assert_allclose(score.asnumpy(), expect, rtol=1e-4,
                                atol=1e-5)
    ctx = npx.sldwin_atten_context(score, v, dil, w=w,
                                   symmetric=symmetric)
    assert ctx.shape == (b, t, h, hd)
    # oracle context
    wl = score.shape[-1]
    exp_ctx = onp.zeros((b, t, h, hd), "float32")
    vn, sn = v.asnumpy(), score.asnumpy()
    for bi in range(b):
        for i in range(t):
            for hi in range(h):
                for j in range(wl):
                    pos = i + (j - w) * int(dil.asnumpy()[hi])
                    if 0 <= pos < t:
                        exp_ctx[bi, i, hi] += sn[bi, i, hi, j] * \
                            vn[bi, pos, hi]
    onp.testing.assert_allclose(ctx.asnumpy(), exp_ctx, rtol=1e-4,
                                atol=1e-5)
    mask = npx.sldwin_atten_mask_like(
        score, dil, np.array(onp.array([t, t - 2], "int32")), w=w,
        symmetric=symmetric)
    assert mask.shape == score.shape
    mn = mask.asnumpy()
    # reference mask formula spot checks: row 0 head 0 masks the w left
    # out-of-range slots; rows past valid_length are fully masked
    assert mn[0, 0, 0, 0] == 0.0
    assert mn[1, t - 1].max() == 0.0


def test_ctc_loss_vs_torch():
    torch = pytest.importorskip("torch")
    t, b, c, l = 8, 3, 5, 3
    rng = onp.random.RandomState(0)
    logits = rng.uniform(-2, 2, (t, b, c)).astype("float32")
    labels = rng.randint(1, c, (b, l)).astype("int32")  # blank='first'=0
    out = npx.ctc_loss(np.array(logits), np.array(labels))
    tl = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(logits), dim=-1),
        torch.tensor(labels.astype("int64")),
        input_lengths=torch.full((b,), t, dtype=torch.long),
        target_lengths=torch.full((b,), l, dtype=torch.long),
        blank=0, reduction="none")
    onp.testing.assert_allclose(out.asnumpy(), tl.numpy(), rtol=1e-4,
                                atol=1e-4)


def test_ctc_loss_variable_lengths_vs_torch():
    torch = pytest.importorskip("torch")
    t, b, c, l = 10, 2, 6, 4
    rng = onp.random.RandomState(1)
    logits = rng.uniform(-2, 2, (t, b, c)).astype("float32")
    labels = rng.randint(1, c, (b, l)).astype("int32")
    dlen = onp.array([10, 7], "int32")
    llen = onp.array([4, 2], "int32")
    out = npx.ctc_loss(np.array(logits), np.array(labels),
                       data_lengths=np.array(dlen),
                       label_lengths=np.array(llen),
                       use_data_lengths=True, use_label_lengths=True)
    tl = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(logits), dim=-1),
        torch.tensor(labels.astype("int64")),
        input_lengths=torch.tensor(dlen.astype("int64")),
        target_lengths=torch.tensor(llen.astype("int64")),
        blank=0, reduction="none")
    onp.testing.assert_allclose(out.asnumpy(), tl.numpy(), rtol=1e-4,
                                atol=1e-4)


def test_ctc_loss_grad_flows():
    x = _r(6, 2, 5, scale=2.0)
    lab = np.array(onp.array([[1, 2], [3, 1]], "int32"))
    x.attach_grad()
    with autograd.record():
        loss = npx.ctc_loss(x, lab).sum()
    loss.backward()
    g = x.grad.asnumpy()
    assert onp.isfinite(g).all() and onp.abs(g).max() > 0


def test_hawkesll_matches_loop_oracle():
    n, t, k = 2, 4, 3
    rng = onp.random.RandomState(0)
    mu = rng.uniform(0.5, 1.5, (n, k)).astype("float32")
    alpha = rng.uniform(0.1, 0.4, (k,)).astype("float32")
    beta = rng.uniform(0.5, 2.0, (k,)).astype("float32")
    state = onp.zeros((n, k), "float32")
    lags = rng.uniform(0.1, 0.6, (n, t)).astype("float32")
    marks = rng.randint(0, k, (n, t)).astype("int32")
    vlen = onp.array([4, 2], "float32")
    mtime = onp.array([3.0, 2.5], "float32")

    ll, out_state = npx.hawkesll(
        np.array(mu), np.array(alpha), np.array(beta), np.array(state),
        np.array(lags), np.array(marks), np.array(vlen), np.array(mtime))

    # direct port of hawkes_ll-inl.h:120 as the oracle
    exp_ll = onp.zeros(n)
    exp_state = state.copy().astype("float64")
    for i in range(n):
        last = onp.zeros(k)
        tt = 0.0
        for j in range(int(vlen[i])):
            ci = marks[i, j]
            tt += lags[i, j]
            d = tt - last[ci]
            ed = onp.exp(-beta[ci] * d)
            lam = mu[i, ci] + alpha[ci] * beta[ci] * exp_state[i, ci] * ed
            comp = mu[i, ci] * d + alpha[ci] * exp_state[i, ci] * (1 - ed)
            exp_ll[i] += onp.log(lam) - comp
            exp_state[i, ci] = 1 + exp_state[i, ci] * ed
            last[ci] = tt
        d = mtime[i] - last
        ed = onp.exp(-beta * d)
        exp_ll[i] -= (mu[i] * d + alpha * exp_state[i] * (1 - ed)).sum()
        exp_state[i] *= ed
    onp.testing.assert_allclose(ll.asnumpy(), exp_ll, rtol=1e-4)
    onp.testing.assert_allclose(out_state.asnumpy(), exp_state,
                                rtol=1e-4)


def test_batch_norm_with_relu_and_sync_alias():
    x = _r(4, 3)
    gamma, beta = np.ones((3,)), np.zeros((3,))
    rm, rv = np.zeros((3,)), np.ones((3,))
    out = npx.batch_norm_with_relu(x, gamma, beta, rm, rv)
    assert float(out.min().asnumpy()) >= 0.0
    out2 = npx.sync_batch_norm(x, gamma, beta, rm, rv)
    assert out2.shape == x.shape
