"""Pluggable KVStore backends (reference: `python/mxnet/kvstore/base.py:74`
registry + `python/mxnet/kvstore/horovod.py:27` — an out-of-tree backend
class that Trainer-facing code can `create()` by type string), plus the
documented `KVStoreDevice` reduce contract (VERDICT r2 weak #9)."""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import kv, np
from incubator_mxnet_tpu.kvstore.base import KVStoreBase


@KVStoreBase.register
class HorovodLike(KVStoreBase):
    """Out-of-tree backend in the reference's horovod.py shape: stateless
    pushpull/broadcast, no optimizer offload, its own allreduce
    implementation (here: host-side mean over simulated worker copies)."""

    def __init__(self):
        self.pushpull_calls = 0

    def broadcast(self, key, value, out):   # noqa: ARG002
        outs = out if isinstance(out, (list, tuple)) else [out]
        src = value if not isinstance(value, (list, tuple)) else value[0]
        for o in outs:
            o._set_data(src._data)

    def pushpull(self, key, value, out=None, priority=0):  # noqa: ARG002
        self.pushpull_calls += 1
        vs = value if isinstance(value, (list, tuple)) else [value]
        acc = vs[0].asnumpy()
        for v in vs[1:]:
            acc = acc + v.asnumpy()
        red = np.array(acc)
        if out is None:
            vs[0]._set_data(red._data)
            return
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            o._set_data(red._data)

    @staticmethod
    def is_capable(capability):
        return False   # like horovod: no optimizer-on-kvstore


def test_custom_backend_create_and_roundtrip():
    store = kv.create("horovodlike")
    assert isinstance(store, HorovodLike)
    assert store.num_workers == 1 and store.rank == 0
    assert not store.is_capable(KVStoreBase.OPTIMIZER)

    a = np.array(onp.ones((4, 4), "float32"))
    b = np.array(onp.full((4, 4), 2.0, "float32"))
    out = np.array(onp.zeros((4, 4), "float32"))
    store.pushpull("w0", [a, b], out=out)
    onp.testing.assert_allclose(out.asnumpy(), 3.0 * onp.ones((4, 4)))
    assert store.pushpull_calls == 1

    dst = np.array(onp.zeros((4, 4), "float32"))
    store.broadcast("w0", a, dst)
    onp.testing.assert_allclose(dst.asnumpy(), a.asnumpy())


def test_trainer_runs_on_custom_backend():
    """gluon.Trainer with update_on_kvstore=False drives any backend that
    only implements pushpull (the horovod contract)."""
    from incubator_mxnet_tpu import autograd, gluon

    net = gluon.nn.Dense(4, in_units=8)
    net.initialize()
    store = kv.create("horovodlike")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1},
                            kvstore=store, update_on_kvstore=False)
    x = np.array(onp.random.RandomState(0)
                 .uniform(-1, 1, (16, 8)).astype("float32"))
    w_before = net.weight.data().asnumpy().copy()
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    trainer.step(16)
    assert not onp.allclose(net.weight.data().asnumpy(), w_before)


def test_kvstore_device_identity_reduce_contract():
    """Pins the documented contract (kvstore.py KVStoreDevice._reduce):
    a SINGLE logical array reduces to itself (already globally consistent
    on the mesh), while LIST-valued pushes aggregate by summation."""
    store = kv.create("device")
    single = np.array(onp.full((3, 3), 5.0, "float32"))
    # identity: _reduce returns the very same logical value
    red = store._reduce(single)
    onp.testing.assert_array_equal(red.asnumpy(), single.asnumpy())

    store.init("k", np.array(onp.zeros((3, 3), "float32")))
    copies = [np.array(onp.full((3, 3), float(i), "float32"))
              for i in (1, 2, 4)]
    out = np.array(onp.zeros((3, 3), "float32"))
    store.pushpull("k", copies, out=out)
    onp.testing.assert_allclose(out.asnumpy(), 7.0 * onp.ones((3, 3)))


def test_dist_async_warns_sync_degradation():
    """`create('dist_async')` must tell the user their straggler
    semantics changed (reference ASyncMode applies pushes immediately;
    here every update is a synchronous collective)."""
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        try:
            kv.create("dist_async")
        except Exception:
            pass  # dist init may fail single-process; the warning fires first
    assert any("synchronous" in str(x.message) for x in w)


def test_horovod_local_rank_env(monkeypatch):
    """local_rank honors the launcher's per-host rank env (our
    tools/launch.py exports MXNET_LOCAL_RANK) instead of echoing the
    global rank."""
    store = kv.create("horovod")
    monkeypatch.setenv("MXNET_LOCAL_RANK", "3")
    assert store.local_rank == 3
    for name in ("MXNET_LOCAL_RANK", "HOROVOD_LOCAL_RANK",
                 "OMPI_COMM_WORLD_LOCAL_RANK", "LOCAL_RANK"):
        monkeypatch.delenv(name, raising=False)
    assert store.local_rank == store.rank
