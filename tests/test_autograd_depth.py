"""Autograd depth: recording scopes, train/predict modes, custom
Functions, head gradients, retain/create_graph, multi-output and
mutation interactions (reference: `tests/python/unittest/
test_autograd.py` + `test_higher_order_grad.py` patterns)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np

RNG = onp.random.RandomState(43)


def _a(*shape):
    return np.array(RNG.uniform(0.5, 2.0, shape).astype("float32"))


# -- recording scopes --------------------------------------------------------

def test_no_record_no_grad():
    x = _a(3)
    x.attach_grad()
    y = (x * x).sum()
    with pytest.raises(Exception):
        y.backward()


def test_is_recording_flag():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
    assert not autograd.is_recording()


def test_is_training_flag():
    with autograd.record():
        assert autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()


def test_pause_stops_taping():
    x = _a(3)
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = y * 10          # not taped
        w = (y + z).sum()
    w.backward()
    # dz/dx contributes nothing: grad = d(y)/dx = 2
    onp.testing.assert_allclose(x.grad.asnumpy(), 2.0, rtol=1e-6)


def test_train_mode_inside_predict():
    with autograd.record(train_mode=False):
        with autograd.train_mode():
            assert autograd.is_training()
        assert not autograd.is_training()


def test_predict_mode_scope():
    with autograd.record():
        with autograd.predict_mode():
            assert not autograd.is_training()
        assert autograd.is_training()


# -- backward mechanics ------------------------------------------------------

def test_head_gradient():
    x = _a(3)
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(np.array(onp.array([1.0, 2.0, 3.0], "float32")))
    onp.testing.assert_allclose(
        x.grad.asnumpy(),
        2 * x.asnumpy() * onp.array([1.0, 2.0, 3.0]), rtol=1e-5)


def test_backward_twice_without_retain_fresh_graphs():
    x = _a(3)
    x.attach_grad()
    for _ in range(2):           # two separate records: both must work
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy(),
                                rtol=1e-5)


def test_grad_add_accumulates_across_backwards():
    x = _a(3)
    x.attach_grad(grad_req="add")
    with autograd.record():
        y = (x * 2).sum()
    y.backward()
    with autograd.record():
        z = (x * 3).sum()
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 5.0, rtol=1e-5)


def test_multi_output_op_backward():
    x = _a(4)
    x.attach_grad()
    with autograd.record():
        a, b = np.split(x, 2)
        s = (a * 2).sum() + (b * 3).sum()
    s.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [2, 2, 3, 3], rtol=1e-5)


def test_diamond_graph_sums_paths():
    x = _a(3)
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = (y + y * y).sum()    # two paths through y
    z.backward()
    ref = 2 + 8 * x.asnumpy()    # d/dx (2x + 4x²)
    onp.testing.assert_allclose(x.grad.asnumpy(), ref, rtol=1e-5)


def test_grad_of_intermediate_via_autograd_grad():
    x = _a(3)
    x.attach_grad()
    with autograd.record():
        y = x * x
        g = autograd.grad(y.sum(), [x], create_graph=False)[0]
    onp.testing.assert_allclose(g.asnumpy(), 2 * x.asnumpy(), rtol=1e-5)


def test_higher_order_via_create_graph():
    x = _a(3)
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
        gx = autograd.grad(y, [x], create_graph=True)[0]
        s = gx.sum()
    s.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 6 * x.asnumpy(),
                                rtol=1e-4)


def test_stop_gradient_detach():
    x = _a(3)
    x.attach_grad()
    with autograd.record():
        y = x * 2
        d = y.detach()
        z = (d * x).sum()
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy(),
                                rtol=1e-5)


# -- custom Function ---------------------------------------------------------

def test_custom_function_fwd_bwd():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    x = _a(4)
    x.attach_grad()
    f = Square()
    with autograd.record():
        y = f(x).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy(),
                                rtol=1e-5)


def test_custom_function_multi_input():
    class Mul(autograd.Function):
        def forward(self, a, b):
            self.save_for_backward(a, b)
            return a * b

        def backward(self, dy):
            a, b = self.saved_tensors
            return dy * b, dy * a

    a, b = _a(3), _a(3)
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = Mul()(a, b).sum()
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), b.asnumpy(), rtol=1e-5)
    onp.testing.assert_allclose(b.grad.asnumpy(), a.asnumpy(), rtol=1e-5)


# -- mutation interactions ---------------------------------------------------

def test_setitem_then_backward():
    x = _a(4)
    x.attach_grad()
    with autograd.record():
        y = x * 3
        s = y.sum()
    # mutate x AFTER the graph is built; grads still flow to the old value
    s.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 3.0, rtol=1e-6)


def test_inplace_add_outside_record():
    x = _a(3)
    x.attach_grad()
    x += 1.0                      # eager mutation, no tape
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy(),
                                rtol=1e-5)


# -- shape/dtype propagation through grads -----------------------------------

def test_grad_dtype_matches_input():
    x = _a(3).astype("float16")
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert "float16" in str(x.grad.dtype)


def test_grad_through_reshape_transpose():
    x = _a(2, 6)
    x.attach_grad()
    with autograd.record():
        y = x.reshape(3, 4).T
        s = (y * 2).sum()
    s.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2.0, rtol=1e-6)


def test_grad_through_concat():
    a, b = _a(2, 3), _a(2, 3)
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = np.concatenate([a * 1.0, b * 2.0], axis=0).sum()
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), 1.0, rtol=1e-6)
    onp.testing.assert_allclose(b.grad.asnumpy(), 2.0, rtol=1e-6)


def test_grad_through_broadcasting_chain():
    a = _a(1, 4)
    b = _a(3, 1)
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = (a * b).sum()
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(),
                                onp.full((1, 4), b.asnumpy().sum()),
                                rtol=1e-5)
    onp.testing.assert_allclose(b.grad.asnumpy(),
                                onp.full((3, 1), a.asnumpy().sum()),
                                rtol=1e-5)


def test_mark_variables_api():
    x = np.array(onp.ones(3, "float32"))
    g = np.zeros((3,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x * 4).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 4.0, rtol=1e-6)


def test_grad_none_for_untouched_input():
    x = _a(3)
    z = _a(3)
    x.attach_grad()
    z.attach_grad()
    with autograd.record():
        y = (x * 2).sum()         # z not involved
    y.backward()
    g = z.grad
    assert g is None or not g.asnumpy().any()