"""np.linalg depth: factorizations round-trip, solves, norms, spectra —
golden against numpy.linalg (reference: `src/operator/numpy/linalg/` +
test_numpy_op.py linalg blocks)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.numpy import linalg

RNG = onp.random.RandomState(37)


def _m(n=4, batch=None):
    shape = (n, n) if batch is None else (batch, n, n)
    return RNG.uniform(-1, 1, shape).astype("float32")


def _spd(n=4):
    a = _m(n)
    return a @ a.T + n * onp.eye(n, dtype="float32")


def test_norm_fro():
    a = _m()
    got = float(linalg.norm(np.array(a)).asnumpy())
    assert got == pytest.approx(float(onp.linalg.norm(a)), rel=1e-5)


def test_norm_orders():
    a = _m()
    for ordv in (1, 2, onp.inf, "fro"):
        got = float(linalg.norm(np.array(a), ord=ordv).asnumpy())
        assert got == pytest.approx(float(onp.linalg.norm(a, ord=ordv)),
                                    rel=1e-4)


def test_vector_norm_axis():
    a = _m()
    got = linalg.norm(np.array(a), axis=1).asnumpy()
    onp.testing.assert_allclose(got, onp.linalg.norm(a, axis=1), rtol=1e-5)


def test_det_and_slogdet_consistent():
    a = _spd()
    d = float(linalg.det(np.array(a)).asnumpy())
    sign, logdet = linalg.slogdet(np.array(a))
    assert d == pytest.approx(float(onp.linalg.det(a)), rel=1e-3)
    assert float(sign.asnumpy()) * onp.exp(float(logdet.asnumpy())) == \
        pytest.approx(d, rel=1e-3)


def test_inv_roundtrip():
    a = _spd()
    inv = linalg.inv(np.array(a)).asnumpy()
    onp.testing.assert_allclose(a @ inv, onp.eye(4), atol=1e-3)


def test_pinv_rectangular():
    a = RNG.uniform(-1, 1, (5, 3)).astype("float32")
    p = linalg.pinv(np.array(a)).asnumpy()
    onp.testing.assert_allclose(a @ p @ a, a, atol=1e-3)


def test_solve_matches_numpy():
    a = _spd()
    b = RNG.uniform(-1, 1, (4, 2)).astype("float32")
    x = linalg.solve(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(a @ x, b, atol=1e-3)


def test_lstsq_overdetermined():
    a = RNG.uniform(-1, 1, (6, 3)).astype("float32")
    b = RNG.uniform(-1, 1, (6,)).astype("float32")
    x = linalg.lstsq(np.array(a), np.array(b), rcond=None)[0].asnumpy()
    ref = onp.linalg.lstsq(a, b, rcond=None)[0]
    onp.testing.assert_allclose(x, ref, atol=1e-3)


def test_cholesky_roundtrip():
    a = _spd()
    chol = linalg.cholesky(np.array(a)).asnumpy()
    onp.testing.assert_allclose(chol @ chol.T, a, rtol=1e-3, atol=1e-3)
    assert onp.allclose(chol, onp.tril(chol))


def test_qr_roundtrip_orthonormal():
    a = _m()
    qm, r = linalg.qr(np.array(a))
    qv, rv = qm.asnumpy(), r.asnumpy()
    onp.testing.assert_allclose(qv @ rv, a, atol=1e-4)
    onp.testing.assert_allclose(qv.T @ qv, onp.eye(4), atol=1e-4)
    assert onp.allclose(rv, onp.triu(rv), atol=1e-5)


def test_svd_roundtrip_and_singular_values():
    a = RNG.uniform(-1, 1, (5, 3)).astype("float32")
    u, s, vt = linalg.svd(np.array(a))
    uv, sv, vtv = u.asnumpy(), s.asnumpy(), vt.asnumpy()
    onp.testing.assert_allclose((uv[:, :3] * sv) @ vtv, a, atol=1e-4)
    onp.testing.assert_allclose(sv, onp.linalg.svd(a, compute_uv=False),
                                rtol=1e-4)


def test_eigh_reconstruction():
    a = _spd()
    w, v = linalg.eigh(np.array(a))
    wv, vv = w.asnumpy(), v.asnumpy()
    onp.testing.assert_allclose(vv @ onp.diag(wv) @ vv.T, a, atol=1e-3)
    ref = onp.linalg.eigvalsh(a)
    onp.testing.assert_allclose(onp.sort(wv), onp.sort(ref), rtol=1e-4)


def test_eigvalsh_matches():
    a = _spd()
    got = linalg.eigvalsh(np.array(a)).asnumpy()
    onp.testing.assert_allclose(onp.sort(got),
                                onp.sort(onp.linalg.eigvalsh(a)),
                                rtol=1e-4)


def test_matrix_rank():
    a = onp.zeros((4, 4), "float32")
    a[0, 0] = a[1, 1] = 1.0
    assert int(linalg.matrix_rank(np.array(a)).asnumpy()) == 2


def test_matrix_power():
    a = _m(3)
    got = linalg.matrix_power(np.array(a), 3).asnumpy()
    onp.testing.assert_allclose(got, a @ a @ a, rtol=1e-3, atol=1e-4)


def test_multi_dot():
    a, b, c = _m(3), _m(3), _m(3)
    got = linalg.multi_dot([np.array(a), np.array(b),
                            np.array(c)]).asnumpy()
    onp.testing.assert_allclose(got, a @ b @ c, rtol=1e-4, atol=1e-4)


def test_batched_inv():
    a = onp.stack([_spd(), _spd()])
    inv = linalg.inv(np.array(a)).asnumpy()
    for i in range(2):
        onp.testing.assert_allclose(a[i] @ inv[i], onp.eye(4), atol=1e-3)


def test_batched_cholesky():
    a = onp.stack([_spd(), _spd()])
    c = linalg.cholesky(np.array(a)).asnumpy()
    for i in range(2):
        onp.testing.assert_allclose(c[i] @ c[i].T, a[i], atol=1e-3)


def test_tensorsolve_tensorinv_if_present():
    if not hasattr(linalg, "tensorsolve"):
        pytest.skip("tensorsolve not exposed")
    a = RNG.uniform(-1, 1, (2, 2, 2, 2)).astype("float32") \
        + onp.eye(4).reshape(2, 2, 2, 2).astype("float32") * 2
    b = RNG.uniform(-1, 1, (2, 2)).astype("float32")
    x = linalg.tensorsolve(np.array(a), np.array(b)).asnumpy()
    ref = onp.linalg.tensorsolve(a, b)
    onp.testing.assert_allclose(x, ref, atol=1e-3)


def test_solve_grad_flows():
    from incubator_mxnet_tpu import autograd

    a = np.array(_spd())
    b = np.array(RNG.uniform(-1, 1, (4,)).astype("float32"))
    a.attach_grad()
    with autograd.record():
        x = linalg.solve(a, b)
        s = np.sum(x)
    s.backward()
    assert a.grad is not None
    assert onp.isfinite(a.grad.asnumpy()).all()


def test_norm_grad_unit_direction():
    from incubator_mxnet_tpu import autograd

    v = np.array(onp.array([3.0, 4.0], "float32"))
    v.attach_grad()
    with autograd.record():
        n = linalg.norm(v)
    n.backward()
    onp.testing.assert_allclose(v.grad.asnumpy(), [0.6, 0.8], rtol=1e-5)