"""Native threaded prefetch pipeline tests (reference model:
tests/python/unittest/test_io.py prefetcher behavior)."""
import os

import pytest

from incubator_mxnet_tpu import recordio
from incubator_mxnet_tpu._native import rtio


pytestmark = pytest.mark.skipif(rtio() is None,
                                reason="librtio unavailable")


@pytest.fixture
def rec_file(tmp_path):
    path = str(tmp_path / "data.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(37):
        w.write(f"record-{i:03d}".encode() * (i % 5 + 1))
    w.close()
    return path


def test_prefetcher_yields_all_batches_in_order(rec_file):
    it = recordio.MXRecordIOPrefetcher(rec_file, batch_size=5,
                                       num_threads=3, drop_last=True)
    assert len(it) == 7  # 37 // 5
    seen = []
    for batch in it:
        assert len(batch) == 5
        seen.extend(batch)
    assert len(seen) == 35
    # single-dispenser ordering: batches arrive in index order per epoch
    assert seen[0].startswith(b"record-000")
    assert seen[5].startswith(b"record-005")
    it.close()


def test_prefetcher_keep_last(rec_file):
    it = recordio.MXRecordIOPrefetcher(rec_file, batch_size=10,
                                       drop_last=False)
    sizes = [len(b) for b in it]
    assert sizes == [10, 10, 10, 7]
    it.close()


def test_prefetcher_shuffle_reshuffles_per_epoch(rec_file):
    it = recordio.MXRecordIOPrefetcher(rec_file, batch_size=37,
                                       shuffle=True, seed=5,
                                       drop_last=False)
    epoch1 = [r for b in it for r in b]
    epoch2 = [r for b in it for r in b]
    assert sorted(epoch1) == sorted(epoch2)
    assert epoch1 != epoch2  # different epoch seed → different order
    it.close()


def test_prefetcher_indices_subset(rec_file):
    it = recordio.MXRecordIOPrefetcher(rec_file, batch_size=2,
                                       indices=[0, 2, 4, 6],
                                       drop_last=True)
    got = [r for b in it for r in b]
    assert got[0].startswith(b"record-000")
    assert got[1].startswith(b"record-002")
    assert len(got) == 4
    it.close()


def test_prefetcher_multiple_epochs(rec_file):
    it = recordio.MXRecordIOPrefetcher(rec_file, batch_size=10)
    for _ in range(3):  # iterating again re-creates the pipeline
        n = sum(len(b) for b in it)
        assert n == 30
    it.close()


def test_prefetcher_early_break_restarts_epoch(rec_file):
    """Breaking out of an epoch mid-stream must not leak leftover batches
    into the next iteration."""
    it = recordio.MXRecordIOPrefetcher(rec_file, batch_size=5)
    for batch in it:
        first_of_epoch1 = batch[0]
        break
    # a fresh, full epoch follows the truncated one
    count = 0
    for i, batch in enumerate(it):
        if i == 0:
            assert batch[0] == first_of_epoch1  # unshuffled → same start
        count += 1
    assert count == 7
    it.close()


def test_nd_flatten_keeps_batch_dim():
    import numpy as onp

    import incubator_mxnet_tpu as mx

    x = mx.nd.array(onp.ones((4, 3, 5, 5), onp.float32))
    assert mx.nd.Flatten(x).shape == (4, 75)


def test_closed_pipeline_len_is_zero(rec_file):
    from incubator_mxnet_tpu._native import (NativePrefetchPipeline,
                                             NativeRecordFile)

    f = NativeRecordFile(rec_file)
    p = NativePrefetchPipeline(f, batch_size=5)
    assert len(p) > 0
    p.close()
    assert len(p) == 0  # no segfault, defined value
    f.close()


def test_prefetcher_close_during_iteration_is_safe(rec_file):
    it = recordio.MXRecordIOPrefetcher(rec_file, batch_size=5)
    for _ in it:
        it.close()
        break  # GeneratorExit cleanup must not raise on closed state
    assert len(it) == 0
    assert list(it) == []


def test_prefetcher_payloads_match_sequential(rec_file):
    r = recordio.MXRecordIO(rec_file, "r")
    seq = []
    while True:
        item = r.read()
        if item is None:
            break
        seq.append(item)
    r.close()
    it = recordio.MXRecordIOPrefetcher(rec_file, batch_size=4,
                                       drop_last=False, num_threads=4)
    got = [rec for b in it for rec in b]
    assert got == seq
    it.close()
