"""Random sampling statistical tests (reference model:
tests/python/unittest/test_random.py — moment checks per distribution)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu import random as mxrand


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


N = 20000


@pytest.fixture(autouse=True)
def _seed():
    mxrand.seed(7)


def test_uniform_moments():
    s = A(mnp.random.uniform(-2.0, 4.0, (N,)))
    assert abs(s.mean() - 1.0) < 0.1
    assert abs(s.var() - 36.0 / 12) < 0.2
    assert s.min() >= -2.0 and s.max() < 4.0


def test_normal_moments():
    s = A(mnp.random.normal(3.0, 2.0, (N,)))
    assert abs(s.mean() - 3.0) < 0.1
    assert abs(s.std() - 2.0) < 0.1


def test_gamma_moments():
    s = A(mnp.random.gamma(4.0, 2.0, (N,)))
    assert abs(s.mean() - 8.0) < 0.3          # k*theta
    assert abs(s.var() - 16.0) < 2.0          # k*theta^2


def test_exponential_moments():
    s = A(mnp.random.exponential(2.0, (N,)))
    assert abs(s.mean() - 2.0) < 0.1


def test_poisson_moments():
    s = A(mnp.random.poisson(5.0, (N,)))
    assert abs(s.mean() - 5.0) < 0.15
    assert abs(s.var() - 5.0) < 0.5


def test_randint_range_and_uniformity():
    s = A(mnp.random.randint(0, 10, (N,)))
    assert s.min() == 0 and s.max() == 9
    counts = onp.bincount(s.astype(onp.int64), minlength=10)
    assert (abs(counts / N - 0.1) < 0.02).all()


def test_bernoulli_mean():
    s = A(mnp.random.bernoulli(0.3, size=(N,)))
    assert abs(s.mean() - 0.3) < 0.02


def test_multinomial_counts():
    p = onp.array([0.2, 0.3, 0.5], onp.float32)
    s = A(mnp.random.multinomial(N, p))
    onp.testing.assert_allclose(s / N, p, atol=0.02)


def test_shuffle_is_permutation():
    x = mnp.array(onp.arange(100, dtype=onp.float32))
    mnp.random.shuffle(x)
    got = onp.sort(A(x))
    onp.testing.assert_array_equal(got, onp.arange(100))


def test_seed_reproducibility():
    mxrand.seed(123)
    a = A(mnp.random.normal(0, 1, (50,)))
    mxrand.seed(123)
    b = A(mnp.random.normal(0, 1, (50,)))
    onp.testing.assert_array_equal(a, b)
    c = A(mnp.random.normal(0, 1, (50,)))
    assert not onp.array_equal(b, c)


def test_beta_moments():
    a, b = 2.0, 5.0
    s = A(mnp.random.beta(a, b, (N,)))
    assert abs(s.mean() - a / (a + b)) < 0.02
    assert s.min() >= 0 and s.max() <= 1


def test_laplace_moments():
    s = A(mnp.random.laplace(1.0, 2.0, (N,)))
    assert abs(s.mean() - 1.0) < 0.15
    assert abs(s.var() - 8.0) < 1.0
