"""Finite-difference gradient checks for the structured ops the round-1
verdict flagged as never numerically checked: conv, pooling, BN/LN, RNN,
CTC (reference: `tests/python/unittest/test_operator.py` check_numeric_
gradient usage), plus bf16/fp16 dtype sweeps."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import np, npx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.test_utils import check_numeric_gradient

RNG = onp.random.RandomState(11)


def _arr(*shape):
    return np.array(RNG.randn(*shape).astype("float32") * 0.5)


def test_grad_conv2d():
    x = _arr(2, 3, 8, 8)
    w = _arr(4, 3, 3, 3)
    check_numeric_gradient(
        lambda x, w: (npx.convolution(x, w, kernel=(3, 3), num_filter=4,
                                      no_bias=True) ** 2).sum(),
        [x, w], eps=1e-2, rtol=5e-2, atol=2e-2)


def test_grad_pooling():
    rng = onp.random.RandomState(3)
    x = np.array(rng.randn(2, 2, 6, 6).astype("float32") * 0.5)
    check_numeric_gradient(
        lambda x: (npx.pooling(x, kernel=(2, 2), stride=(2, 2),
                               pool_type="avg") ** 2).sum(),
        [x], eps=1e-2, rtol=5e-2, atol=5e-3)
    # max pool: keep in-window gaps >> eps so perturbations can't flip the
    # argmax (which would corrupt the finite difference)
    base = rng.permutation(2 * 2 * 6 * 6).astype("float32").reshape(2, 2, 6, 6)
    xm = np.array(base)  # all values ≥1 apart, eps=1e-2 can't create ties
    check_numeric_gradient(
        lambda x: npx.pooling(x, kernel=(2, 2), stride=(2, 2),
                              pool_type="max").sum(),
        [xm], eps=1e-2, rtol=5e-2, atol=5e-3)


def test_grad_batch_norm():
    # sum(out²) of batch-normalized values is near-invariant (grads ~1e-6,
    # under f32 finite-difference noise), so weight the objective to make
    # the gradient through the batch statistics O(1)
    x = _arr(4, 3, 5, 5)
    w = np.array(RNG.randn(4, 3, 5, 5).astype("float32"))
    gamma, beta = np.ones((3,)), np.zeros((3,))
    mean, var = np.zeros((3,)), np.ones((3,))
    check_numeric_gradient(
        lambda x: (npx.batch_norm(x, gamma, beta, mean, var) * w).sum(),
        [x], eps=1e-2, rtol=5e-2, atol=5e-3)


def test_grad_layer_norm():
    x = _arr(4, 6)
    g, b = np.ones((6,)), np.zeros((6,))
    check_numeric_gradient(
        lambda x: (npx.layer_norm(x, g, b, axis=-1) ** 2).sum(),
        [x], rtol=3e-2, atol=2e-3)


def test_grad_softmax_logsoftmax():
    x = _arr(3, 7)
    check_numeric_gradient(
        lambda x: (npx.softmax(x) ** 2).sum(), [x], eps=1e-2,
        rtol=5e-2, atol=2e-3)
    check_numeric_gradient(
        lambda x: (npx.log_softmax(x) * npx.log_softmax(x)).sum(), [x],
        eps=1e-2, rtol=5e-2, atol=5e-3)


def test_grad_rnn_lstm():
    T, B, I, H = 3, 2, 4, 5
    x = _arr(T, B, I)
    n_params = 4 * H * (I + H + 2)
    params = np.array(RNG.randn(n_params).astype("float32") * 0.1)
    state = np.zeros((1, B, H))
    cell = np.zeros((1, B, H))

    def fn(x, params):
        out = npx.rnn(data=x, parameters=params, state=state,
                      state_cell=cell, mode="lstm", state_size=H,
                      num_layers=1)
        return (out ** 2).sum()

    check_numeric_gradient(fn, [x, params], rtol=4e-2, atol=2e-3)


def test_grad_ctc_loss():
    T, B, C = 6, 2, 5
    logits = _arr(T, B, C)
    labels = np.array(onp.array([[1, 2], [3, 4]], "int32"))

    def fn(logits):
        return gluon.loss.CTCLoss(layout="TNC")(logits, labels).sum()

    check_numeric_gradient(fn, [logits], rtol=5e-2, atol=5e-3)


def test_grad_embedding_dense():
    w = _arr(10, 4)
    idx = np.array(onp.array([1, 3, 3], "int32"))
    check_numeric_gradient(
        lambda w: (npx.embedding(idx, w, input_dim=10, output_dim=4)
                   ** 2).sum(),
        [w], rtol=2e-2, atol=1e-3)


# -- dtype sweeps -------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
def test_dense_forward_dtypes(dtype):
    net = gluon.nn.Dense(8)
    net.initialize()
    net.cast(dtype)
    x = np.array(RNG.randn(2, 4).astype("float32")).astype(dtype)
    y = net(x)
    assert onp.dtype(y.dtype) == onp.dtype(getattr(
        __import__("ml_dtypes"), "bfloat16") if dtype == "bfloat16"
        else dtype)
    assert onp.isfinite(y.asnumpy().astype("float32")).all()


@pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
def test_conv_bn_forward_dtypes(dtype):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1), gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"))
    net.initialize()
    net.cast(dtype)
    x = np.ones((1, 3, 8, 8)).astype(dtype)
    y = net(x)
    import ml_dtypes

    want = onp.dtype(ml_dtypes.bfloat16 if dtype == "bfloat16" else dtype)
    assert onp.dtype(y.dtype) == want


def test_backward_float32():
    from incubator_mxnet_tpu import autograd

    x = np.array(RNG.randn(3, 3).astype("float32"))
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert onp.dtype(x.grad.dtype) == onp.float32
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy(),
                                rtol=1e-5)


def test_float64_degrades_to_float32():
    # Documented TPU-native divergence: without jax x64 mode, float64
    # requests execute in float32 (the TPU has no f64 units).
    x = np.array(onp.ones((2, 2), "float64"))
    assert onp.dtype(x.dtype) == onp.float32
