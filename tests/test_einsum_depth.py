"""Einsum contraction corpus + gradients (reference:
`src/operator/numpy/np_einsum_op.cc` and the einsum block of
`test_numpy_op.py`)."""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np

RNG = onp.random.RandomState(17)


def _a(*shape):
    return RNG.uniform(-1, 1, shape).astype("float32")


def _check(spec, *ops, rtol=1e-4, atol=1e-5):
    got = np.einsum(spec, *[np.array(o) for o in ops]).asnumpy()
    ref = onp.einsum(spec, *[o.astype("float64") for o in ops])
    onp.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)


def _check_grad(spec, *ops):
    arrs = [np.array(o) for o in ops]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        y = np.einsum(spec, *arrs)
        s = np.sum(y)
    s.backward()
    eps = 1e-2
    for i, o in enumerate(ops):
        flat = o.reshape(-1)
        for j in (0, flat.size // 2, flat.size - 1):
            pert = [x.copy() for x in ops]
            pert[i].reshape(-1)[j] += eps
            up = onp.einsum(spec, *[x.astype("float64")
                                    for x in pert]).sum()
            pert[i].reshape(-1)[j] -= 2 * eps
            dn = onp.einsum(spec, *[x.astype("float64")
                                    for x in pert]).sum()
            num = (up - dn) / (2 * eps)
            got = arrs[i].grad.asnumpy().reshape(-1)[j]
            onp.testing.assert_allclose(got, num, rtol=5e-2, atol=5e-3)


# -- single operand ----------------------------------------------------------

def test_einsum_trace():
    _check("ii", _a(5, 5))


def test_einsum_diag():
    _check("ii->i", _a(5, 5))


def test_einsum_transpose():
    _check("ij->ji", _a(3, 4))


def test_einsum_sum_all():
    _check("ij->", _a(3, 4))


def test_einsum_sum_axis0():
    _check("ij->j", _a(3, 4))


def test_einsum_sum_axis1():
    _check("ij->i", _a(3, 4))


def test_einsum_identity():
    _check("ij->ij", _a(3, 4))


def test_einsum_3d_partial_sum():
    _check("ijk->ik", _a(2, 3, 4))


def test_einsum_3d_transpose():
    _check("ijk->kji", _a(2, 3, 4))


# -- two operands ------------------------------------------------------------

def test_einsum_matmul():
    _check("ij,jk->ik", _a(3, 4), _a(4, 5))


def test_einsum_matmul_transposed_out():
    _check("ij,jk->ki", _a(3, 4), _a(4, 5))


def test_einsum_inner():
    _check("i,i->", _a(6), _a(6))


def test_einsum_outer():
    _check("i,j->ij", _a(3), _a(4))


def test_einsum_matvec():
    _check("ij,j->i", _a(3, 4), _a(4))


def test_einsum_vecmat():
    _check("i,ij->j", _a(3), _a(3, 4))


def test_einsum_hadamard():
    _check("ij,ij->ij", _a(3, 4), _a(3, 4))


def test_einsum_hadamard_sum():
    _check("ij,ij->", _a(3, 4), _a(3, 4))


def test_einsum_batch_matmul():
    _check("bij,bjk->bik", _a(2, 3, 4), _a(2, 4, 5))


def test_einsum_batch_matmul_broadcast_free():
    _check("bij,jk->bik", _a(2, 3, 4), _a(4, 5))


def test_einsum_attention_scores():
    _check("nqd,nkd->nqk", _a(2, 5, 8), _a(2, 7, 8))


def test_einsum_attention_context():
    _check("nqk,nkd->nqd", _a(2, 5, 7), _a(2, 7, 8))


def test_einsum_bilinear():
    _check("ik,jkl->ijl", _a(2, 3), _a(4, 3, 5))


def test_einsum_tensordot_style():
    _check("ijk,kl->ijl", _a(2, 3, 4), _a(4, 5))


def test_einsum_contraction_over_two_axes():
    _check("ijk,ijl->kl", _a(2, 3, 4), _a(2, 3, 5))


def test_einsum_row_contract_keep_batch():
    _check("bi,bi->b", _a(4, 6), _a(4, 6))


# -- three operands ----------------------------------------------------------

def test_einsum_three_matmul_chain():
    _check("ij,jk,kl->il", _a(2, 3), _a(3, 4), _a(4, 5))


def test_einsum_three_mixed():
    _check("ij,kj,kl->il", _a(2, 3), _a(4, 3), _a(4, 5))


def test_einsum_three_hadamard_contract():
    _check("ij,ij,ij->", _a(3, 4), _a(3, 4), _a(3, 4))


# -- ellipsis ----------------------------------------------------------------

def test_einsum_ellipsis_identity():
    _check("...i->...i", _a(2, 3, 4))


def test_einsum_ellipsis_sum_last():
    _check("...i->...", _a(2, 3, 4))


def test_einsum_ellipsis_matmul():
    _check("...ij,...jk->...ik", _a(2, 3, 4), _a(2, 4, 5))


def test_einsum_ellipsis_transpose():
    _check("...ij->...ji", _a(2, 3, 4))


# -- gradients ---------------------------------------------------------------

def test_einsum_matmul_grad():
    _check_grad("ij,jk->ik", _a(3, 4), _a(4, 3))


def test_einsum_batch_matmul_grad():
    _check_grad("bij,bjk->bik", _a(2, 3, 3), _a(2, 3, 3))


def test_einsum_inner_grad():
    _check_grad("i,i->", _a(5), _a(5))


def test_einsum_trace_grad():
    _check_grad("ii", _a(4, 4))


def test_einsum_sum_grad():
    _check_grad("ij->", _a(3, 4))


def test_einsum_attention_grad():
    _check_grad("nqd,nkd->nqk", _a(2, 3, 4), _a(2, 3, 4))


# -- dtype handling ----------------------------------------------------------

def test_einsum_bf16():
    a, b = _a(4, 8), _a(8, 4)
    got = np.einsum("ij,jk->ik",
                    np.array(a).astype("bfloat16"),
                    np.array(b).astype("bfloat16"))
    assert "bfloat16" in str(got.dtype)
    onp.testing.assert_allclose(got.astype("float32").asnumpy(), a @ b,
                                rtol=0.05, atol=0.05)


def test_einsum_int32():
    a = onp.arange(6, dtype="int32").reshape(2, 3)
    b = onp.arange(12, dtype="int32").reshape(3, 4)
    got = np.einsum("ij,jk->ik", np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_array_equal(got, onp.einsum("ij,jk->ik", a, b))


# -- tensordot / kron cousins ------------------------------------------------

def test_tensordot_axes_int():
    a, b = _a(3, 4, 5), _a(5, 4, 2)
    got = np.tensordot(np.array(a), np.array(b), axes=1).asnumpy()
    onp.testing.assert_allclose(got, onp.tensordot(a, b, axes=1),
                                rtol=1e-4, atol=1e-5)


def test_tensordot_axes_pairs():
    a, b = _a(3, 4, 5), _a(4, 3, 2)
    got = np.tensordot(np.array(a), np.array(b),
                       axes=([0, 1], [1, 0])).asnumpy()
    onp.testing.assert_allclose(
        got, onp.tensordot(a, b, axes=([0, 1], [1, 0])), rtol=1e-4,
        atol=1e-5)


def test_kron():
    a, b = _a(2, 3), _a(3, 2)
    got = np.kron(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(got, onp.kron(a, b), rtol=1e-5)


def test_outer_fn():
    a, b = _a(4), _a(5)
    got = np.outer(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(got, onp.outer(a, b), rtol=1e-5)


def test_inner_fn():
    a, b = _a(3, 4), _a(5, 4)
    got = np.inner(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(got, onp.inner(a, b), rtol=1e-4, atol=1e-5)


def test_vdot_flattens():
    a, b = _a(3, 4), _a(3, 4)
    got = float(np.vdot(np.array(a), np.array(b)).asnumpy())
    onp.testing.assert_allclose(got, onp.vdot(a, b), rtol=1e-4)


def test_cross_3d():
    a, b = _a(4, 3), _a(4, 3)
    got = np.cross(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(got, onp.cross(a, b), rtol=1e-4, atol=1e-5)