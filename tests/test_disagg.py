"""serve.disagg — disaggregated prefill/decode serving (ISSUE 19).

Stub-engine logic tests (pure host arithmetic over REAL
PageAllocator/PrefixCache — the test_gateway.py recipe, so a request
prefilled on replica A and adopted on replica B must continue the same
arithmetic token run) cover: role threading through
``ModelRegistry.add(prefill_replicas=, decode_replicas=)``, the
migration pump's refcount handoff and byte audit, the
``page_migration`` chaos seam's co-located fallback with ZERO page
leak, the decode-side page-exhausted fallback, role-aware elastic
crash replacement, and the preserved gateway invariants (priority
preemption, dispatch scoping). The real-engine test is the acceptance
gate: a request prefilled on replica A and decoded on replica B
produces BIT-IDENTICAL greedy tokens to a single-replica
``role="both"`` pod, with the decode replica's compile ledger showing
zero prefill families.
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, serve
from incubator_mxnet_tpu.fault import injection
from incubator_mxnet_tpu.models.gpt import gpt_tiny
from incubator_mxnet_tpu.serve import disagg
from incubator_mxnet_tpu.serve.engine import (PageAllocator,
                                              PagePoolExhausted,
                                              PrefixCache)
from incubator_mxnet_tpu.telemetry import registry

VOCAB = 97


@pytest.fixture(autouse=True)
def _clear_schedule():
    injection.clear_injection()
    yield
    injection.clear_injection()


class _StubSlots:
    """Paged-interface stand-in (same recipe as test_gateway.py): the
    final prefill chunk emits the prompt's length as the first token,
    decode increments — so the tokens of a request that migrated
    mid-flight must be the same arithmetic run ``[plen, plen+1, ...]``
    as one served co-located. ``page_bytes`` makes the migration byte
    audit exact."""

    def __init__(self, max_slots=2, max_len=64, page_tokens=16,
                 prefill_chunk=64, n_pages=None, page_bytes=2048):
        self.max_slots = max_slots
        self.max_len = max_len
        self.page_tokens = page_tokens
        self.prefill_chunk = prefill_chunk
        self.page_bytes = page_bytes
        pages_per_slot = -(-max_len // page_tokens)
        self.allocator = PageAllocator(
            n_pages if n_pages is not None
            else max_slots * pages_per_slot + 1, page_tokens)
        self.prefix_cache = PrefixCache(self.allocator)
        self.released = False

    def set_slot_pages(self, slot, pages):
        pass

    def clear_slot(self, slot):
        pass

    def prefill_chunk_step(self, slot, chunk_tokens, t_start, key,
                           temperature=1.0):
        n = len(chunk_tokens)
        return int(t_start) + n, n, 0

    def decode_step(self, last_tok, pos, active, key, temperature):
        return onp.where(active, last_tok + 1, last_tok).astype(onp.int32)

    def xla_program_count(self):
        return 0

    def release(self):
        self.released = True


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


def _disagg_gateway(n_prefill=1, n_decode=1, decode_pages=None,
                    prefill_pages=None, **gw_kwargs):
    """1-model disaggregated gateway over prebuilt stubs: the first
    `n_prefill` stubs take the prefill role."""
    stubs = ([_StubSlots(n_pages=prefill_pages)
              for _ in range(n_prefill)]
             + [_StubSlots(n_pages=decode_pages)
                for _ in range(n_decode)])
    reg = serve.ModelRegistry()
    reg.add("m", stubs, prefill_replicas=n_prefill,
            decode_replicas=n_decode)
    return serve.Gateway(reg, **gw_kwargs), stubs


def _drive(gw, handles, steps=400):
    for _ in range(steps):
        gw.step()
        if all(h.done for h in handles):
            return
    raise AssertionError(
        f"requests not done: {[h.state for h in handles]}")


def _counter(name):
    rep = registry.report()
    return rep.get(name, {}).get("value", 0) or 0


def _free_pages(stub):
    return stub.allocator.free_pages


# ---------------------------------------------------------------------------
# registry role threading (quick)
# ---------------------------------------------------------------------------

def test_registry_disagg_validation():
    reg = serve.ModelRegistry()
    with pytest.raises(ValueError):                 # pair, not half
        reg.add("a", _StubSlots(), prefill_replicas=1)
    with pytest.raises(ValueError):
        reg.add("b", _StubSlots(), decode_replicas=1)
    with pytest.raises(ValueError):                 # mutually exclusive
        reg.add("c", [_StubSlots(), _StubSlots()], replicas=2,
                prefill_replicas=1, decode_replicas=1)
    with pytest.raises(ValueError):                 # >= 1 of each role
        reg.add("d", [_StubSlots()], prefill_replicas=1,
                decode_replicas=0)
    # prebuilt count must equal the role sum
    reg2 = serve.ModelRegistry()
    reg2.add("m", [_StubSlots(), _StubSlots(), _StubSlots()],
             prefill_replicas=1, decode_replicas=1)
    with pytest.raises(ValueError) as ei:
        serve.Gateway(reg2)
    assert "pre-built" in str(ei.value)
    # a single prebuilt engine cannot be disaggregated
    reg3 = serve.ModelRegistry()
    reg3.add("m", _StubSlots(), prefill_replicas=1, decode_replicas=1)
    with pytest.raises(ValueError):
        serve.Gateway(reg3)


def test_registry_disagg_page_split():
    reg = serve.ModelRegistry(total_pages=100)
    reg.add("m", object(), prefill_replicas=1, decode_replicas=2)
    per_p, per_d = reg.rebalance_pages_disagg("m", 1, 2)
    # the prefill sliver: ~25% of the cut; decode gets the rest
    assert per_p == 25 and per_d == 37
    assert per_p + 2 * per_d <= 100
    with pytest.raises(PagePoolExhausted):
        reg.rebalance_pages_disagg("m", 1, 100)
    with pytest.raises(ValueError):
        reg.rebalance_pages_disagg("nope", 1, 1)
    # no joint budget: engines size their own pools
    assert serve.ModelRegistry().add(
        "m", object(), prefill_replicas=1,
        decode_replicas=1).rebalance_pages_disagg("m", 1, 1) == (None,
                                                                None)


def test_roles_assigned_and_dispatch_scoped():
    gw, _stubs = _disagg_gateway(n_prefill=1, n_decode=2)
    try:
        m = gw._models["m"]
        assert m.disagg
        assert [r.role for r in m.replicas] == ["prefill", "decode",
                                                "decode"]
        assert [r.label for r in m.replicas] == ["m#0", "m#1", "m#2"]
        # dispatch (and preemption-victim search) never targets a
        # decode replica
        assert [r.role for r in gw._dispatch_reps(m)] == ["prefill"]
        # a homogeneous model is untouched by the scoping
        reg = serve.ModelRegistry()
        reg.add("h", _StubSlots())
        gw2 = serve.Gateway(reg)
        try:
            hm = gw2._models["h"]
            assert not hm.disagg
            assert gw2._dispatch_reps(hm) is hm.replicas
        finally:
            gw2.shutdown(drain=False)
    finally:
        gw.shutdown(drain=False)


def test_mxnet_disagg_env_knob_defaults_roles():
    from incubator_mxnet_tpu.test_utils import environment

    with environment({"MXNET_DISAGG": "1",
                      "MXNET_SERVE_PREFILL_REPLICAS": "1",
                      "MXNET_SERVE_DECODE_REPLICAS": "2"}):
        net = gpt_tiny(vocab_size=VOCAB, max_length=64, dropout=0.0)
        net.initialize()
        reg = serve.ModelRegistry()
        reg.add("m", net, max_slots=2, max_len=64)
        gw = serve.Gateway(reg)
        try:
            roles = [r.role for r in gw._models["m"].replicas]
            assert roles == ["prefill", "decode", "decode"]
        finally:
            gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# the migration plane (stub engines, quick)
# ---------------------------------------------------------------------------

def test_migrated_request_continues_token_run_and_audits_bytes():
    gw, stubs = _disagg_gateway()
    try:
        pages0 = _counter('mx_serve_page_migration_pages_total'
                          '{model="m"}')
        bytes0 = _counter('mx_serve_page_migration_bytes_total'
                          '{model="m"}')
        h = gw.submit("m", _prompt(20), 6)
        _drive(gw, [h])
        # the stub run is arithmetic: first token = prompt length, then
        # +1 per decode step — ONE unbroken run across the migration
        assert h.state == "done"
        assert h.tokens == list(range(20, 26))
        # the request finished on the decode replica
        m = gw._models["m"]
        assert h.replica == "m#1"
        # pages moved = the prompt's content pages (20 tokens / 16-token
        # pages = 2); bytes = pages × page_bytes EXACTLY
        moved = _counter('mx_serve_page_migration_pages_total'
                         '{model="m"}') - pages0
        assert moved == 2
        assert (_counter('mx_serve_page_migration_bytes_total'
                         '{model="m"}') - bytes0
                == moved * stubs[0].page_bytes)
        # refcount handoff: the source side keeps only its prefix-cache
        # refs (the prompt's FULL pages stay warm for future prefills:
        # floor(20/16) = 1); the request itself holds no source pages
        assert stubs[0].prefix_cache.cached_pages == 1
        # decode side: the migration registered the prompt's full pages
        # there too + the request released its own refs at retire
        assert stubs[1].prefix_cache.cached_pages == 1
        for rep in m.replicas:
            assert rep.sched.idle and not rep.live
    finally:
        gw.shutdown(drain=False)
    # shutdown clears the prefix caches: every page ref returns
    for s in stubs:
        assert _free_pages(s) == s.allocator.usable_pages


def test_prefill_pool_is_not_the_submit_viability_bar():
    # prefill pool: 3 usable pages (prompt fits), decode pool: plenty —
    # the old replica-0 check would have rejected this request
    gw, _stubs = _disagg_gateway(prefill_pages=4, decode_pages=12)
    try:
        h = gw.submit("m", _prompt(20), 40)      # 4 decode-side pages
        _drive(gw, [h])
        assert h.state == "done" and len(h.tokens) == 40
    finally:
        gw.shutdown(drain=False)
    # ... and a request that fits NO decode pool is still loudly
    # rejected at submit (4 pages needed, 3 usable decode-side)
    gw2, _ = _disagg_gateway(decode_pages=4)
    try:
        with pytest.raises(PagePoolExhausted):
            gw2.submit("m", _prompt(40), 24)
    finally:
        gw2.shutdown(drain=False)


def test_page_migration_fault_falls_back_colocated_no_leak():
    gw, stubs = _disagg_gateway()
    try:
        pages0 = _counter('mx_serve_page_migration_pages_total'
                          '{model="m"}')
        injection.configure_injection("page_migration:1.0:0:1")
        h = gw.submit("m", _prompt(20), 6)
        _drive(gw, [h])
        injection.clear_injection()
        # the token run is STILL unbroken — the request finished
        # co-located on its prefill replica
        assert h.state == "done"
        assert h.tokens == list(range(20, 26))
        assert h.replica == "m#0"
        # the aborted handoff moved nothing
        assert _counter('mx_serve_page_migration_pages_total'
                        '{model="m"}') == pages0
        # NO page leak: the decode side's trial allocation rolled back
        # to a completely free pool
        assert _free_pages(stubs[1]) == stubs[1].allocator.usable_pages
        # source side holds only the prompt's full-page prefix refs
        assert stubs[0].prefix_cache.cached_pages == 1
        stubs[0].prefix_cache.clear()
        assert _free_pages(stubs[0]) == stubs[0].allocator.usable_pages
    finally:
        injection.clear_injection()
        gw.shutdown(drain=False)


def test_decode_exhausted_falls_back_colocated():
    # the decode pool fits EITHER request statically (so submit admits
    # both) but not both at once: the second migration aborts at the
    # page-exhaustion check and the prefill replica finishes that
    # request itself, co-located
    gw, _stubs = _disagg_gateway(decode_pages=6)  # 5 usable pages
    try:
        hs = [gw.submit("m", _prompt(20, seed=i), 20)  # 3 pages each
              for i in range(2)]
        _drive(gw, hs)
        for h in hs:
            assert h.state == "done"
            assert h.tokens == list(range(20, 40))
        # exactly one migrated, the other fell back to its prefill home
        assert sorted(h.replica for h in hs) == ["m#0", "m#1"]
    finally:
        gw.shutdown(drain=False)


def test_migration_feeds_decode_prefix_warmth():
    """Two identical prompts: the second request's migration lands on a
    decode replica already holding the prompt's page digests — the
    content-addressed fill made the migration idempotent."""
    gw, stubs = _disagg_gateway(n_decode=2)
    try:
        h1 = gw.submit("m", _prompt(32, seed=3), 4)
        _drive(gw, [h1])
        warm = [stubs[1 + i].prefix_cache.shared_tokens(
            _prompt(32, seed=3)) for i in range(2)]
        # exactly one warm side (a proper-prefix probe: 1 of 2 pages)
        assert sorted(warm) == [0, 16]
        h2 = gw.submit("m", _prompt(32, seed=3), 4)
        _drive(gw, [h2])
        assert h2.tokens == h1.tokens == list(range(32, 36))
        # prefix affinity routed the second migration to the warm side
        assert h2.replica == h1.replica
    finally:
        gw.shutdown(drain=False)


def test_preemption_and_tiers_preserved_under_disagg():
    """Priority preemption still works — scoped to the prefill side, so
    the victim search never lands a prefill submit on a decode
    replica."""
    gw, _stubs = _disagg_gateway(prefill_pages=9)  # 2 slots, 8 pages
    try:
        pre0 = gw.preemptions_total
        # two long-prompt lows fill the prefill replica's two slots
        lows = [gw.submit("m", _prompt(60, seed=i), 2, tenant="crawl",
                          priority="low") for i in range(2)]
        for _ in range(2):
            gw.step()
        high = gw.submit("m", _prompt(8, seed=9), 2, tenant="acme",
                         priority="high")
        _drive(gw, lows + [high])
        assert high.state == "done"
        assert {r.state for r in lows} == {"done"}
        for r in lows:                 # preempted or not, full budget
            assert len(r.tokens) == 2
        assert gw.preemptions_total >= pre0
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# elastic role-awareness (stub engines, quick)
# ---------------------------------------------------------------------------

def test_elastic_replaces_dead_decode_replica_with_decode_role():
    gw, _stubs = _disagg_gateway(n_decode=2)
    ctl = gw.enable_elastic(
        factories={"m": lambda n_pages: _StubSlots(n_pages=n_pages)},
        min_replicas=1, max_replicas=4)
    try:
        m = gw._models["m"]
        assert [r.role for r in m.replicas] == ["prefill", "decode",
                                                "decode"]
        # kill replica index 1 (a decode replica) via the chaos seam
        injection.configure_injection("replica_crash@1:1.0:0:1")
        gw.step()
        injection.clear_injection()
        roles = sorted(r.role for r in m.replicas)
        assert roles == ["decode", "decode", "prefill"]
        replaced = [r for r in m.replicas if r.index >= 3]
        assert replaced and replaced[0].role == "decode"
        # the warmed replacement never compiled a prefill program: its
        # decode-only warmup drained fully
        assert replaced[0].sched.idle
        # traffic still flows end-to-end through the repaired pod
        h = gw.submit("m", _prompt(20), 4)
        _drive(gw, [h])
        assert h.tokens == list(range(20, 24))
    finally:
        injection.clear_injection()
        gw.shutdown(drain=False)
    assert ctl is not None


def test_elastic_scale_up_adds_decode_and_floor_guards_roles():
    gw, _stubs = _disagg_gateway()
    gw.enable_elastic(
        factories={"m": lambda n_pages: _StubSlots(n_pages=n_pages)},
        min_replicas=1, max_replicas=4)
    ctl = gw._elastic
    try:
        m = gw._models["m"]
        added = ctl.scale_up("m")
        assert [r.role for r in added] == ["decode"]
        # scale-down never drains the last replica of a role: with
        # 1 prefill + 2 decode, two scale-downs leave 1+1, not 0+2
        ctl.scale_down("m", n=3)
        alive = [r for r in m.replicas if not r.draining]
        assert sorted(r.role for r in alive) == ["decode", "prefill"]
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# mixed-length trace preset (quick)
# ---------------------------------------------------------------------------

def _loadgen():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)
    return loadgen


def test_mixed_length_trace_preset():
    loadgen = _loadgen()
    ev = loadgen.mixed_length_trace(40, "m", seed=3, long_frac=0.25,
                                    long_prompt=48)
    assert len(ev) == 40
    # seeded determinism
    ev2 = loadgen.mixed_length_trace(40, "m", seed=3, long_frac=0.25,
                                     long_prompt=48)
    assert [e.to_dict() for e in ev] == [e.to_dict() for e in ev2]
    tenants = {e.tenant for e in ev}
    assert tenants == {"archive", "chat"}
    longs = [e for e in ev if e.tenant == "archive"]
    chats = [e for e in ev if e.tenant == "chat"]
    assert len(longs) == 10
    # the two populations stress opposite ends: long prompts dwarf the
    # chat ones on average (the tails may brush — lognormal jitter)
    mean = lambda xs: sum(xs) / len(xs)  # noqa: E731
    assert (mean([e.prompt_len for e in longs])
            > 2 * mean([e.prompt_len for e in chats]))
    assert all(e.priority == "high" for e in chats)
    assert ev == sorted(ev, key=lambda e: e.t)


def test_mixed_length_replay_on_disagg_pod():
    """The acceptance trace end-to-end on a stub pod: every request
    completes, migrations happened, and decode-side residency exceeds
    the prefill side's (the disaggregation point)."""
    loadgen = _loadgen()
    gw, _stubs = _disagg_gateway(n_decode=2, decode_pages=24)
    try:
        ev = loadgen.mixed_length_trace(
            12, "m", seed=5, duration_s=0.3, long_prompt=48,
            long_new_range=(2, 4), chat_new_range=(2, 6))
        p0 = _counter('mx_serve_page_migration_pages_total{model="m"}')
        rep = loadgen.replay(gw, ev, VOCAB, timeout=60.0)
        assert not rep["failed"] and rep["completed"] == len(ev)
        assert _counter('mx_serve_page_migration_pages_total'
                        '{model="m"}') > p0
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# real engines: migrated-page parity + the decode-ledger gate
# ---------------------------------------------------------------------------

def _spicy_net(weight_seed):
    """Non-degenerate random weights, same recipe as test_gateway.py."""
    mx.random.seed(11)
    m = gpt_tiny(vocab_size=VOCAB, max_length=64, dropout=0.0)
    m.initialize()
    r = onp.random.RandomState(weight_seed)
    for _name, p in m.collect_params().items():
        if p.shape and len(p.shape) >= 2:
            p.set_data(np.array(
                r.normal(0, 0.35, p.shape).astype("float32")))
    return m


def test_migrated_page_parity_real_engines():
    """THE acceptance gate: prefilled on replica A, decoded on replica
    B → BIT-IDENTICAL greedy tokens vs a single-replica ``role="both"``
    pod, pages/bytes audited, zero prefill families on the decode
    side, zero steady-state recompiles on BOTH sides."""
    prompts = [(_prompt(21, seed=1), 6), (_prompt(7, seed=2), 8),
               (_prompt(33, seed=3), 5)]

    # baseline: one homogeneous replica
    reg_b = serve.ModelRegistry(total_pages=40)
    reg_b.add("gpt", _spicy_net(42), max_slots=2, max_len=64)
    gw_b = serve.Gateway(reg_b)
    try:
        base = []
        for p, n in prompts:
            h = gw_b.submit("gpt", p, n)
            gw_b._drive_until([h], timeout=120.0)
            base.append(list(h.tokens))
    finally:
        gw_b.shutdown(drain=False)

    # disaggregated pod: same weights, 1 prefill + 1 decode replica
    reg = serve.ModelRegistry(total_pages=40)
    reg.add("gpt", _spicy_net(42), prefill_replicas=1,
            decode_replicas=1, max_slots=2, max_len=64)
    gw = serve.Gateway(reg)
    try:
        m = gw._models["gpt"]
        assert [r.role for r in m.replicas] == ["prefill", "decode"]
        # the decode side got the bigger page cut (the disagg point:
        # HBM that would fund prefill working sets funds pages)
        assert (m.replicas[1].slots.allocator.usable_pages
                > m.replicas[0].slots.allocator.usable_pages)
        p0 = _counter('mx_serve_page_migration_pages_total'
                      '{model="gpt"}')
        b0 = _counter('mx_serve_page_migration_bytes_total'
                      '{model="gpt"}')
        got = []
        for p, n in prompts:
            h = gw.submit("gpt", p, n)
            gw._drive_until([h], timeout=120.0)
            assert h.replica == "gpt#1"        # finished on decode side
            got.append(list(h.tokens))
        # BIT-IDENTICAL greedy parity across the migration
        assert got == base
        # zero steady-state recompiles on BOTH sides: the first pass
        # warmed every prefill chunk bucket; a second pass of fresh
        # prompts at the SAME lengths (and its migrations) must not
        # compile anything new anywhere
        programs = gw.xla_program_counts(per_replica=True)
        for i, (p, n) in enumerate(prompts):
            h = gw.submit("gpt", _prompt(p.size, seed=50 + i), n)
            gw._drive_until([h], timeout=120.0)
            assert h.state == "done"
        assert gw.xla_program_counts(per_replica=True) == programs
        moved = _counter('mx_serve_page_migration_pages_total'
                         '{model="gpt"}') - p0
        # both passes migrated every request's content pages
        assert moved == 2 * sum(-(-p.size // 16) for p, _ in prompts)
        # the byte audit: EXACTLY pages moved × per-page pool bytes
        assert (_counter('mx_serve_page_migration_bytes_total'
                         '{model="gpt"}') - b0
                == moved * m.replicas[0].slots.page_bytes)
        # the ledger gate: the decode replica NEVER compiled a prefill
        # program (live program caches + instrumented compile ledger)
        assert disagg.decode_prefill_families(gw, "gpt") == {}
        assert m.replicas[1].slots._prefill_jit is None
        assert m.replicas[1].slots._decode_jit is not None
    finally:
        gw.shutdown(drain=False)
