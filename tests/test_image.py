"""Image pipeline tests: augmenters, CreateAugmenter, ImageIter, im2rec
(reference: `tests/python/unittest/test_image.py`)."""
import os
import subprocess
import sys

import numpy as onp
import pytest

from incubator_mxnet_tpu import image
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def _img(h=32, w=32, c=3, seed=0):
    rng = onp.random.RandomState(seed)
    return rng.randint(0, 255, (h, w, c)).astype(onp.uint8)


def test_resize_and_crops():
    src = NDArray(_img(40, 60))
    out = image.imresize(src, 30, 20)
    assert out.shape == (20, 30, 3)
    short = image.resize_short(src, 24)
    assert min(short.shape[:2]) == 24
    crop, rect = image.center_crop(src, (20, 20))
    assert crop.shape[:2] == (20, 20) and rect[2:] == (20, 20)
    crop, _ = image.random_crop(src, (16, 16))
    assert crop.shape[:2] == (16, 16)
    crop, _ = image.random_size_crop(src, (16, 16), (0.3, 1.0), (0.75, 1.33))
    assert crop.shape[:2] == (16, 16)


def test_scale_down_and_border():
    assert image.scale_down((30, 40), (50, 50)) == (30, 30)
    out = image.copyMakeBorder(NDArray(_img(10, 10)), 2, 3, 4, 5)
    assert out.shape == (15, 19, 3)


def test_augmenter_suite_shapes():
    src = _img(48, 48).astype(onp.float32)
    augs = [image.BrightnessJitterAug(0.3), image.ContrastJitterAug(0.3),
            image.SaturationJitterAug(0.3), image.HueJitterAug(0.3),
            image.ColorJitterAug(0.2, 0.2, 0.2),
            image.LightingAug(0.1, onp.array([55.46, 4.794, 1.148]),
                              onp.eye(3)),
            image.RandomGrayAug(1.0), image.HorizontalFlipAug(1.0),
            image.CastAug(), image.ColorNormalizeAug(
                onp.array([123.0, 117.0, 104.0]),
                onp.array([58.0, 57.0, 57.0]))]
    for aug in augs:
        out = aug.apply_np(src.copy())
        assert out.shape == src.shape, type(aug).__name__
        assert onp.isfinite(out).all(), type(aug).__name__


def test_horizontal_flip_flips():
    src = onp.arange(12, dtype=onp.float32).reshape(2, 2, 3)
    out = image.HorizontalFlipAug(1.0).apply_np(src)
    onp.testing.assert_array_equal(out, src[:, ::-1])


def test_random_gray_is_gray():
    out = image.RandomGrayAug(1.0).apply_np(_img().astype(onp.float32))
    onp.testing.assert_allclose(out[..., 0], out[..., 1], rtol=1e-5)


def test_create_augmenter_pipeline():
    augs = image.CreateAugmenter((3, 24, 24), resize=28, rand_crop=True,
                                 rand_mirror=True, mean=True, std=True,
                                 brightness=0.1, contrast=0.1,
                                 saturation=0.1, hue=0.1, pca_noise=0.05,
                                 rand_gray=0.1)
    src = _img(64, 48).astype(onp.float32)
    for aug in augs:
        src = aug.apply_np(src)
    assert src.shape == (24, 24, 3)
    assert src.dtype == onp.float32


def test_augmenter_dumps():
    s = image.ResizeAug(28).dumps()
    assert "ResizeAug" in s


def _write_npy_tree(root, n_per_class=3):
    for cls in ("cat", "dog"):
        os.makedirs(os.path.join(root, cls), exist_ok=True)
        for i in range(n_per_class):
            onp.save(os.path.join(root, cls, f"{i}.npy"), _img(seed=i))


def test_imageiter_from_imglist(tmp_path):
    _write_npy_tree(str(tmp_path))
    imglist = [(0, "cat/0.npy"), (0, "cat/1.npy"), (1, "dog/0.npy"),
               (1, "dog/1.npy"), (1, "dog/2.npy")]
    it = image.ImageIter(batch_size=2, data_shape=(3, 24, 24),
                         imglist=imglist, path_root=str(tmp_path),
                         aug_list=[image.CastAug()])
    batches = list(it)
    assert len(batches) == 3  # 5 images, pad to 6
    assert batches[0].data[0].shape == (2, 3, 24, 24)
    assert batches[-1].pad == 1
    it.reset()
    assert len(list(it)) == 3


def test_im2rec_roundtrip(tmp_path):
    _write_npy_tree(str(tmp_path / "imgs"))
    prefix = str(tmp_path / "data")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "im2rec.py")
    r = subprocess.run([sys.executable, tool, prefix, str(tmp_path / "imgs"),
                        "--list"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".lst")
    r = subprocess.run([sys.executable, tool, prefix, str(tmp_path / "imgs")],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec")
    assert os.path.exists(prefix + ".idx")

    it = image.ImageIter(batch_size=3, data_shape=(3, 32, 32),
                         path_imgrec=prefix + ".rec",
                         aug_list=[image.CastAug()])
    batch = next(it)
    assert batch.data[0].shape == (3, 3, 32, 32)
    labels = set()
    it.reset()
    for b in it:
        labels.update(b.label[0].asnumpy().ravel().tolist())
    assert labels == {0.0, 1.0}


def test_imageiter_tiny_dataset_pads(tmp_path):
    # dataset smaller than batch_size: pad must wrap with modulo, not crash
    _write_npy_tree(str(tmp_path), n_per_class=1)
    imglist = [(0, "cat/0.npy"), (1, "dog/0.npy")]
    it = image.ImageIter(batch_size=8, data_shape=(3, 16, 16),
                         imglist=imglist, path_root=str(tmp_path),
                         aug_list=[image.CastAug()])
    batch = next(it)
    assert batch.data[0].shape == (8, 3, 16, 16)
    assert batch.pad == 6


def test_imageiter_bad_data_shape():
    with pytest.raises(ValueError, match="data_shape"):
        image.ImageIter(batch_size=2, data_shape=(3, 224), imglist=[])


def test_resize_np_matches_jax():
    from incubator_mxnet_tpu.image import _resize_np

    src = _img(17, 23).astype(onp.float32)
    host = _resize_np(src, 11, 9)
    dev = image.imresize(NDArray(src), 11, 9).asnumpy()
    onp.testing.assert_allclose(host, dev, rtol=1e-4, atol=1e-3)


def test_pretrained_roundtrip_via_model_store(tmp_path, monkeypatch):
    from incubator_mxnet_tpu import np as mnp
    from incubator_mxnet_tpu.gluon.model_zoo import model_store
    from incubator_mxnet_tpu.gluon.model_zoo.vision import get_model

    monkeypatch.setenv("MXNET_HOME", str(tmp_path))
    net = get_model("resnet18_v1", classes=4)
    net.initialize()
    x = mnp.random.uniform(size=(1, 3, 32, 32))
    y0 = net(x)
    model_store.export_to_store(net, "resnet18_v1")
    net2 = get_model("resnet18_v1", classes=4, pretrained=True)
    onp.testing.assert_allclose(net2(x).asnumpy(), y0.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_model_store_roundtrip(tmp_path, monkeypatch):
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.model_zoo import model_store

    monkeypatch.setenv("MXNET_HOME", str(tmp_path))
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    from incubator_mxnet_tpu import np as mnp

    net(mnp.zeros((1, 3)))
    path = model_store.export_to_store(net, "tiny")
    assert os.path.exists(path)
    found = model_store.get_model_file("tiny")
    assert found == path
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(4))
    net2.load_parameters(found)
    onp.testing.assert_allclose(net2(mnp.zeros((1, 3))).asnumpy(),
                                net(mnp.zeros((1, 3))).asnumpy())
    # corrupt → checksum error
    with open(found, "r+b") as f:
        f.seek(0)
        f.write(b"x")
    with pytest.raises(ValueError, match="checksum"):
        model_store.get_model_file("tiny")
    model_store.purge()
    with pytest.raises(FileNotFoundError):
        model_store.get_model_file("tiny")


def test_inception_v3_forward():
    from incubator_mxnet_tpu import np as mnp
    from incubator_mxnet_tpu.gluon.model_zoo.vision import inception_v3

    net = inception_v3(classes=10)
    net.initialize()
    x = mnp.random.uniform(size=(1, 3, 299, 299))
    y = net(x)
    assert y.shape == (1, 10)


def test_imageiter_fast_path_honors_dtype(tmp_path):
    """uint8 fast path (geometric augs + trailing CastAug) must still
    deliver the iterator's requested dtype."""
    import numpy as onp

    from incubator_mxnet_tpu import recordio
    from incubator_mxnet_tpu.image import CreateAugmenter, ImageIter

    rec = str(tmp_path / "a.rec")
    idx = str(tmp_path / "a.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = onp.random.RandomState(0)
    for i in range(8):
        img = rng.randint(0, 255, (32, 32, 3), dtype=onp.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=90))
    w.close()
    it = ImageIter(batch_size=4, data_shape=(3, 28, 28), path_imgrec=rec,
                   aug_list=CreateAugmenter((3, 28, 28), rand_crop=True),
                   dtype="float16")
    assert it._device_cast is not None   # fast path engaged
    batch = next(it)
    assert str(batch.data[0].dtype) == "float16"
    assert batch.data[0].shape == (4, 3, 28, 28)
    it.close()
