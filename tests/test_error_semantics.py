"""Error-surfacing semantics (reference model:
tests/python/unittest/test_exc_handling.py — exceptions propagate through
the async engine to sync points; the TPU build surfaces shape/type errors
eagerly at dispatch, which is the jax analogue of WaitForVar rethrow)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def test_shape_mismatch_raises_at_dispatch():
    a = mnp.ones((2, 3))
    b = mnp.ones((4, 5))
    with pytest.raises(Exception):
        mnp.dot(a, b)


def test_invalid_axis_raises():
    with pytest.raises(Exception):
        mnp.sum(mnp.ones((2, 2)), axis=5)


def test_concat_rank_mismatch_raises():
    with pytest.raises(Exception):
        mnp.concatenate([mnp.ones((2, 2)), mnp.ones((2,))], axis=0)


def test_backward_without_record_raises():
    a = NDArray(onp.ones((2,), onp.float32))
    a.attach_grad()
    out = a * 2.0
    with pytest.raises(Exception):
        out.backward()


def test_grad_of_nondiff_path_is_error_or_zero():
    a = NDArray(onp.ones((2,), onp.float32))
    a.attach_grad()
    with autograd.record():
        out = (a > 0.5).astype("float32").sum()
    try:
        out.backward()
    except Exception:
        return  # raising is acceptable (reference: non-diff op error)
    # if backward succeeds, the gradient MUST be zero
    assert float(onp.abs(a.grad.asnumpy()).sum()) == 0.0


def test_load_missing_params_file_raises():
    net = gluon.nn.Dense(4)
    net.initialize()
    with pytest.raises(Exception):
        net.load_parameters("/no/such/file.params")


def test_symbolblock_bad_format_raises(tmp_path):
    import json

    f = tmp_path / "bad-symbol.json"
    f.write_text(json.dumps({"format": "something-else"}))
    with pytest.raises(ValueError, match="unsupported format"):
        gluon.SymbolBlock.imports(str(f))


def test_hybridized_wrong_arity_raises():
    net = gluon.nn.Dense(3)
    net.initialize()
    net.hybridize()
    net(mnp.ones((2, 4)))  # build cache
    with pytest.raises(Exception):
        net(mnp.ones((2, 4)), mnp.ones((2, 4)))


def test_trainer_step_before_backward_is_detectable():
    net = gluon.nn.Dense(2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    with autograd.record():
        loss = net(mnp.ones((1, 3))).sum()
    loss.backward()
    before = net.weight.data().asnumpy().copy()
    trainer.step(1)
    after = net.weight.data().asnumpy()
    assert not onp.allclose(before, after)


def test_mxnet_error_is_catchable_base():
    with pytest.raises(mx.MXNetError):
        raise mx.error.InternalError("boom")


def test_kvstore_unknown_type_raises():
    with pytest.raises(Exception):
        mx.kv.create("definitely-not-a-kvstore")


def test_symbol_executor_missing_binding_raises():
    from incubator_mxnet_tpu import sym

    a, b = sym.Variable("a"), sym.Variable("b")
    with pytest.raises(ValueError, match="missing"):
        (a + b).bind(args={"a": NDArray(onp.ones((1,), onp.float32))})
