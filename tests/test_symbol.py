"""Symbol API tests (reference test model: tests/python/unittest/test_symbol.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def test_variable_and_compose():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    assert c.list_arguments() == ["a", "b"]
    d = c(b=a * 2.0)
    assert d.list_arguments() == ["a"]
    out = d.eval(a=onp.full((2, 2), 3.0, onp.float32))[0]
    onp.testing.assert_allclose(A(out), onp.full((2, 2), 9.0), rtol=1e-6)


def test_arithmetic_scalars_and_ops():
    a = sym.Variable("a")
    expr = (2.0 * a + 1.0) ** 2 / 4.0 - a
    x = onp.array([[1.0, 2.0]], onp.float32)
    out = expr.eval(a=x)[0]
    onp.testing.assert_allclose(A(out), (2 * x + 1) ** 2 / 4 - x, rtol=1e-6)


def test_infer_shape_and_type():
    a, b = sym.Variable("a"), sym.Variable("b")
    c = sym.dot(a, b)
    arg_shapes, out_shapes, aux = c.infer_shape(a=(5, 3), b=(3, 7))
    assert out_shapes == [(5, 7)]
    assert arg_shapes == [(5, 3), (3, 7)]
    assert aux == []
    arg_types, out_types, _ = c.infer_type(a="float32", b="float32")
    assert out_types[0] == onp.float32


def test_executor_forward_backward():
    a, w = sym.Variable("a"), sym.Variable("w")
    loss = (sym.dot(a, w)).sum()
    ex = loss.simple_bind(grad_req="write", a=(2, 3), w=(3, 4))
    av = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    wv = onp.ones((3, 4), onp.float32)
    ex.forward(is_train=True, a=av, w=wv)
    onp.testing.assert_allclose(A(ex.outputs[0]), (av @ wv).sum(), rtol=1e-6)
    ex.backward()
    onp.testing.assert_allclose(A(ex.grad_dict["w"]),
                                onp.repeat(av.sum(0)[:, None], 4, 1), rtol=1e-6)
    onp.testing.assert_allclose(A(ex.grad_dict["a"]),
                                onp.full((2, 3), 4.0), rtol=1e-6)


def test_executor_grad_req_add_and_null():
    a, b = sym.Variable("a"), sym.Variable("b")
    loss = (a * b).sum()
    ex = loss.bind(args={"a": NDArray(onp.ones((2,), onp.float32)),
                         "b": NDArray(onp.full((2,), 3.0, onp.float32))},
                   args_grad={"a": NDArray(onp.zeros((2,), onp.float32))},
                   grad_req={"a": "add", "b": "null"})
    ex.forward(is_train=True)
    ex.backward()
    ex.backward()
    onp.testing.assert_allclose(A(ex.grad_dict["a"]), onp.full((2,), 6.0))
    assert "b" not in ex.grad_dict


def test_json_roundtrip():
    a, b = sym.Variable("a"), sym.Variable("b")
    c = sym.relu(sym.dot(a, b) + 0.5)
    js = c.tojson()
    c2 = sym.fromjson(js)
    assert c2.list_arguments() == ["a", "b"]
    av = onp.random.RandomState(0).randn(2, 3).astype(onp.float32)
    bv = onp.random.RandomState(1).randn(3, 2).astype(onp.float32)
    onp.testing.assert_allclose(A(c.eval(a=av, b=bv)[0]),
                                A(c2.eval(a=av, b=bv)[0]), rtol=1e-6)


def test_save_load(tmp_path):
    a = sym.Variable("a")
    s = sym.exp(a)
    f = str(tmp_path / "sym.json")
    s.save(f)
    s2 = sym.load(f)
    x = onp.array([0.0, 1.0], onp.float32)
    onp.testing.assert_allclose(A(s2.eval(a=x)[0]), onp.exp(x), rtol=1e-6)


def test_group_and_getitem():
    a = sym.Variable("a")
    g = sym.Group([a + 1.0, a * 2.0])
    assert g.num_outputs == 2
    outs = g.eval(a=onp.ones((2,), onp.float32))
    onp.testing.assert_allclose(A(outs[0]), [2.0, 2.0])
    onp.testing.assert_allclose(A(outs[1]), [2.0, 2.0])
    first = g[0]
    onp.testing.assert_allclose(A(first.eval(a=onp.ones((2,), onp.float32))[0]),
                                [2.0, 2.0])


def test_multi_output_getitem():
    a = sym.Variable("a")
    s = sym.split(a, 2, axis=0)
    part = s[0] + 10.0
    out = part.eval(a=onp.arange(4, dtype=onp.float32))[0]
    onp.testing.assert_allclose(A(out), [10.0, 11.0])


def test_method_forwarding():
    a = sym.Variable("a")
    s = a.reshape((4,)).sum()
    out = s.eval(a=onp.ones((2, 2), onp.float32))[0]
    assert float(A(out)) == 4.0


def test_attr_scope_and_attrs():
    with mx.AttrScope(group="fc"):
        a = sym.Variable("a")
        b = a + 1.0
    assert a.attr("group") == "fc"
    assert b.attr("group") == "fc"
    assert "a" in b.attr_dict()


def test_name_manager_prefix():
    from incubator_mxnet_tpu import name as nm

    with nm.Prefix("enc_"):
        a = sym.Variable("x") + 1.0
    assert a.name.startswith("enc_")


def test_list_arg_ops_concatenate():
    a, b = sym.Variable("a"), sym.Variable("b")
    c = sym.concatenate([a, b], axis=0)
    out = c.eval(a=onp.ones((1, 2), onp.float32),
                 b=onp.zeros((1, 2), onp.float32))[0]
    onp.testing.assert_allclose(A(out), [[1, 1], [0, 0]])


def test_npx_ops_in_symbol():
    x = sym.Variable("x")
    s = sym.softmax(x)
    v = onp.array([[1.0, 2.0, 3.0]], onp.float32)
    ref = onp.exp(v) / onp.exp(v).sum()
    onp.testing.assert_allclose(A(s.eval(x=v)[0]), ref, rtol=1e-5)


def test_random_namespace_symbol():
    s = sym.random.normal(0.0, 1.0, (64, 64))
    out = s.eval()[0]
    assert out.shape == (64, 64)
    assert abs(float(A(out).mean())) < 0.5


def test_unbound_argument_raises():
    a, b = sym.Variable("a"), sym.Variable("b")
    c = a + b
    with pytest.raises(ValueError, match="not bound"):
        c.eval(a=onp.ones((1,), onp.float32))


def test_symbolblock_from_symbol():
    from incubator_mxnet_tpu import gluon

    data = sym.Variable("data")
    w = sym.Variable("w")
    net_sym = sym.relu(sym.dot(data, w))
    blk = gluon.SymbolBlock(net_sym, inputs=[data],
                            params={"w": onp.ones((3, 2), onp.float32)})
    x = NDArray(onp.ones((1, 3), onp.float32))
    out = blk(x)
    onp.testing.assert_allclose(A(out), [[3.0, 3.0]], rtol=1e-6)
    # trains like any block
    from incubator_mxnet_tpu import autograd

    with autograd.record():
        loss = blk(x).sum()
    loss.backward()
    g = blk.collect_params()["w"].grad()
    onp.testing.assert_allclose(A(g), onp.ones((3, 2)), rtol=1e-6)


def test_backward_reuses_forward_rng_key():
    """Gradients must differentiate the SAME stochastic realization as the
    reported loss (dropout/random ops)."""
    x = sym.Variable("x")
    s = (x * sym.random.normal(0.0, 1.0, (64,))).sum()
    ex = s.bind(args={"x": NDArray(onp.ones((64,), onp.float32))},
                args_grad={"x": NDArray(onp.zeros((64,), onp.float32))},
                grad_req="write")
    out = ex.forward(is_train=True)[0]
    ex.backward()
    # d/dx sum(x*n) = n, and loss = sum(n) for x=1 → grad sum == loss
    onp.testing.assert_allclose(float(A(ex.grad_dict["x"]).sum()),
                                float(A(out)), rtol=1e-5)


def test_attr_scope_reuse_no_leak():
    scope = mx.AttrScope(lr_mult="2")
    with mx.AttrScope(ctx_group="dev1"):
        with scope:
            pass
    with scope:
        v = sym.Variable("v_leakcheck")
    assert v.attr("ctx_group") is None
    assert v.attr("lr_mult") == "2"


def test_fromjson_ignores_ambient_attr_scope():
    a = sym.Variable("a")
    js = (a + 1.0).tojson()
    with mx.AttrScope(ctx_group="dev9"):
        s2 = sym.fromjson(js)
    assert all("ctx_group" not in attrs for attrs in
               ([n._attrs for n in s2._topo()]))


def test_variable_declared_shape_used_by_infer():
    a = sym.Variable("a", shape=(3, 4), dtype="float32")
    b = sym.Variable("b", shape=(4, 2))
    _, outs, _ = sym.dot(a, b).infer_shape()
    assert outs == [(3, 2)]


def test_tojson_rejects_array_static():
    a = sym.Variable("a")
    s = sym.dot(a, onp.ones((2, 2), onp.float32))
    with pytest.raises(ValueError, match="not serializable"):
        s.tojson()


def test_infer_type_propagates_errors():
    a, b = sym.Variable("a", shape=(2, 3)), sym.Variable("b", shape=(4, 5))
    with pytest.raises(Exception):
        sym.dot(a, b).infer_type()


def test_backward_matches_forward_train_mode():
    """backward() must differentiate the same (train/eval) graph as the
    preceding forward."""
    x = sym.Variable("x")
    s = sym.dropout(x, 0.5).sum()
    ex = s.bind(args={"x": NDArray(onp.ones((1000,), onp.float32))},
                args_grad={"x": NDArray(onp.zeros((1000,), onp.float32))})
    ex.forward(is_train=False)
    ex.backward()
    # eval-mode dropout is identity → grads are exactly 1
    onp.testing.assert_array_equal(A(ex.grad_dict["x"]),
                                   onp.ones((1000,), onp.float32))


def test_aux_variable_alignment():
    a = sym.Variable("a")
    stat = sym.Variable("stat", aux=True)
    w = sym.Variable("w")
    s = sym.dot(a + stat, w)
    assert s.list_arguments() == ["a", "w"]
    assert s.list_auxiliary_states() == ["stat"]
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(
        a=(2, 3), stat=(2, 3), w=(3, 4))
    assert dict(zip(s.list_arguments(), arg_shapes)) == \
        {"a": (2, 3), "w": (3, 4)}
    assert aux_shapes == [(2, 3)]
    assert out_shapes == [(2, 4)]
    # executor binds aux but gives it no grad by default
    ex = s.simple_bind(a=(2, 3), stat=(2, 3), w=(3, 4))
    ex.forward(is_train=True)
    ex.backward()
    assert "stat" not in ex.grad_dict and "w" in ex.grad_dict


def test_list_op_none_static_preserved():
    a, b = sym.Variable("a"), sym.Variable("b")
    s = sym.concatenate([a, b], None)
    out = s.eval(a=onp.ones((2, 2), onp.float32),
                 b=onp.zeros((2, 2), onp.float32))[0]
    assert out.shape == (8,)  # axis=None flattens


def test_fromjson_multihead_ignores_attr_scope():
    a = sym.Variable("a")
    js = sym.Group([a + 1.0, a * 2.0]).tojson()
    with mx.AttrScope(ctx_group="dev9"):
        g2 = sym.fromjson(js)
    assert all("ctx_group" not in n._attrs for n in g2._topo())
    outs = g2.eval(a=onp.ones((2,), onp.float32))
    assert len(outs) == 2


def test_positional_none_static_preserved():
    a = sym.Variable("a")
    s = sym.sum(a, None)  # numpy-style positional axis=None
    out = s.eval(a=onp.ones((2, 3), onp.float32))[0]
    assert float(A(out)) == 6.0
    # survives a json roundtrip (SLOT sentinel vs literal None)
    s2 = sym.fromjson(s.tojson())
    assert float(A(s2.eval(a=onp.ones((2, 3), onp.float32))[0])) == 6.0


def test_symbol_kwarg_rejected():
    a, b = sym.Variable("a"), sym.Variable("b")
    with pytest.raises(TypeError, match="positional"):
        sym.dot(a, b=b)


def test_bind_list_form_with_aux():
    a = sym.Variable("a")
    stat = sym.Variable("stat", aux=True)
    w = sym.Variable("w")
    s = sym.dot(a + stat, w)
    ex = s.bind(args=[onp.ones((2, 3), onp.float32),
                      onp.ones((3, 4), onp.float32)],
                aux_states=[onp.zeros((2, 3), onp.float32)])
    out = ex.forward()[0]
    onp.testing.assert_allclose(A(out), onp.full((2, 4), 3.0))


def test_eval_consistency_with_imperative():
    """Symbolic and imperative paths share the funnel — results identical."""
    from incubator_mxnet_tpu import np as mnp

    rs = onp.random.RandomState(7)
    av = rs.randn(4, 5).astype(onp.float32)
    a = sym.Variable("a")
    s = sym.tanh(a) * 2.0
    sym_out = A(s.eval(a=av)[0])
    imp_out = A(mnp.tanh(mnp.array(av)) * 2.0)
    onp.testing.assert_array_equal(sym_out, imp_out)
