"""Preemption-aware checkpointing (`incubator_mxnet_tpu/preemption.py`,
SURVEY §5.4 elastic story): SIGTERM triggers an immediate atomic save; a
kill mid-write never corrupts the last good checkpoint; training resumes
from `latest()`."""
import os
import signal
import subprocess
import sys

import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, np
from incubator_mxnet_tpu.preemption import (CheckpointManager, atomic_save,
                                            clear_preemption_hooks,
                                            on_preemption, preempted,
                                            trigger)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_atomic_save_survives_midwrite_crash(tmp_path):
    path = str(tmp_path / "state.bin")
    atomic_save(path, lambda p: open(p, "wb").write(b"GOOD"))

    class Boom(RuntimeError):
        pass

    def bad_writer(p):
        open(p, "wb").write(b"HALF")
        raise Boom()

    try:
        atomic_save(path, bad_writer)
    except Boom:
        pass
    assert open(path, "rb").read() == b"GOOD"   # old checkpoint intact


def test_manager_cadence_rotation_and_trigger(tmp_path):
    clear_preemption_hooks()
    prefix = str(tmp_path / "run")
    saves = []

    def save_state(p):
        saves.append(p)
        open(p, "wb").write(b"S")

    m = CheckpointManager(prefix, save_state, every_n=10, keep=2,
                          register_signal=True)
    for _ in range(35):
        m.step()
    # cadence saves at 10/20/30, rotation keeps the last 2 (each with its
    # .crc32 checksum sidecar — the fault subsystem's validation trail)
    kept = sorted(f for f in os.listdir(tmp_path) if f.endswith(".ckpt"))
    assert kept == ["run-0000020.ckpt", "run-0000030.ckpt"], kept
    sidecars = sorted(f for f in os.listdir(tmp_path)
                      if f.endswith(".crc32"))
    assert sidecars == ["run-0000020.ckpt.crc32",
                        "run-0000030.ckpt.crc32"], sidecars
    # preemption triggers an immediate save of step 35
    trigger()
    assert preempted()
    assert os.path.exists(m.path_for(35))
    assert m.latest().endswith("run-0000035.ckpt")
    # idempotent: a second signal at the same step adds nothing
    n = len(os.listdir(tmp_path))
    trigger()
    assert len(os.listdir(tmp_path)) == n
    clear_preemption_hooks()


def test_sigterm_saves_checkpoint_subprocess(tmp_path):
    """Real signal path: a training loop in a subprocess gets SIGTERM and
    must leave a resumable checkpoint behind."""
    prefix = str(tmp_path / "job")
    code = f"""
import os, signal, sys, time
sys.path.insert(0, {REPO!r})
import numpy as onp
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, np, autograd
from incubator_mxnet_tpu.preemption import CheckpointManager

net = gluon.nn.Dense(4, in_units=8)
net.initialize()
mgr = CheckpointManager({prefix!r}, net.save_parameters, every_n=10**9)
x = np.array(onp.ones((2, 8), "float32"))
net(x).wait_to_read()
print("READY", flush=True)
while True:          # train "forever" until preempted
    net(x)
    mgr.step()
    time.sleep(0.01)
"""
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip().endswith("READY")
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=60)
    ckpts = [f for f in os.listdir(tmp_path) if f.endswith(".ckpt")]
    assert ckpts, "no checkpoint written on SIGTERM"
    # the checkpoint resumes
    net2 = gluon.nn.Dense(4, in_units=8)
    net2.load_parameters(str(tmp_path / sorted(ckpts)[-1]))
    assert net2.weight.data().shape == (4, 8)


def test_save_now_is_not_reentrant(tmp_path):
    """A signal landing MID-save must not re-enter atomic_save on the same
    tmp path (r3 ADVICE: interleaved writes corrupt the checkpoint)."""
    clear_preemption_hooks()
    prefix = str(tmp_path / "re")
    entered = []

    m = None

    def save_state(p):
        entered.append(p)
        if len(entered) == 1:
            # simulate SIGTERM arriving while the periodic save runs
            result = m.save_now()
            assert result is None          # skipped, not re-entered
        open(p, "wb").write(b"S")

    m = CheckpointManager(prefix, save_state, every_n=1,
                          register_signal=False)
    m.step()
    assert len(entered) == 1               # the writer ran exactly once
    assert os.path.exists(m.path_for(1))
    clear_preemption_hooks()


_TRAIN_SCRIPT = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as onp
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np, optimizer
from incubator_mxnet_tpu.preemption import TrainingCheckpointer

mx.random.seed(0)
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(16, activation="relu", in_units=8),
        gluon.nn.Dense(1, in_units=16))
net.initialize()
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {{"learning_rate": 1e-2}})
l2 = gluon.loss.L2Loss()
rng = onp.random.RandomState(0)
X = np.array(rng.uniform(-1, 1, (64, 8)).astype("float32"))
W = rng.uniform(-1, 1, (8, 1)).astype("float32")
Y = np.array(X.asnumpy() @ W)

ckpt = TrainingCheckpointer({prefix!r}, net, trainer, every_n=5, keep=2)
start = ckpt.resume()
log = open({log!r}, "a")
for step in range(start, {total}):
    with autograd.record():
        loss = l2(net(X), Y)
    loss.backward()
    trainer.step(64)
    val = float(loss.mean().asnumpy())
    print(step, repr(val), file=log, flush=True)
    ckpt.step()
    print("STEP", step, flush=True)
    {sleep}
print("DONE", flush=True)
"""


def _losses(path):
    out = {}
    for line in open(path):
        s, v = line.split()
        out[int(s)] = float(v)
    return out


def test_preemption_resume_roundtrip(tmp_path):
    """Kill a training subprocess with SIGTERM mid-run; the restarted run
    must continue from the saved step and reproduce the uninterrupted
    run's loss trajectory (params + Adam state + step all restored)."""
    import time

    golden_log = str(tmp_path / "golden.log")
    code = _TRAIN_SCRIPT.format(repo=REPO, prefix=str(tmp_path / "g" / "run"),
                                log=golden_log, total=30, sleep="pass")
    os.makedirs(tmp_path / "g")
    subprocess.run([sys.executable, "-c", code], check=True, timeout=600,
                   stdout=subprocess.DEVNULL)
    golden = _losses(golden_log)
    assert len(golden) == 30

    # interrupted run: SIGTERM after a handful of steps
    run_log = str(tmp_path / "resumed.log")
    os.makedirs(tmp_path / "r")
    code = _TRAIN_SCRIPT.format(repo=REPO, prefix=str(tmp_path / "r" / "run"),
                                log=run_log, total=30,
                                sleep="time.sleep(0.05)")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    seen = 0
    for line in proc.stdout:
        if line.startswith("STEP"):
            seen += 1
            if seen == 12:      # past the step-10 periodic checkpoint
                proc.send_signal(signal.SIGTERM)
                break
    proc.wait(timeout=120)
    ckpts = os.listdir(tmp_path / "r")
    assert ckpts, "no checkpoint left by SIGTERM"

    # restart: must resume from the signal-time checkpoint, not step 0
    proc2 = subprocess.run([sys.executable, "-c", code], check=True,
                           timeout=600, capture_output=True, text=True)
    first_resumed = [ln for ln in proc2.stdout.splitlines()
                     if ln.startswith("STEP")][0]
    resumed_from = int(first_resumed.split()[1])
    assert resumed_from >= 11, first_resumed   # not a cold start
    resumed = _losses(run_log)
    assert set(resumed) == set(range(30))
    for s in range(resumed_from, 30):
        onp.testing.assert_allclose(resumed[s], golden[s], rtol=1e-4,
                                    atol=1e-6), s
