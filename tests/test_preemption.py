"""Preemption-aware checkpointing (`incubator_mxnet_tpu/preemption.py`,
SURVEY §5.4 elastic story): SIGTERM triggers an immediate atomic save; a
kill mid-write never corrupts the last good checkpoint; training resumes
from `latest()`."""
import os
import signal
import subprocess
import sys

import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, np
from incubator_mxnet_tpu.preemption import (CheckpointManager, atomic_save,
                                            clear_preemption_hooks,
                                            on_preemption, preempted,
                                            trigger)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_atomic_save_survives_midwrite_crash(tmp_path):
    path = str(tmp_path / "state.bin")
    atomic_save(path, lambda p: open(p, "wb").write(b"GOOD"))

    class Boom(RuntimeError):
        pass

    def bad_writer(p):
        open(p, "wb").write(b"HALF")
        raise Boom()

    try:
        atomic_save(path, bad_writer)
    except Boom:
        pass
    assert open(path, "rb").read() == b"GOOD"   # old checkpoint intact


def test_manager_cadence_rotation_and_trigger(tmp_path):
    clear_preemption_hooks()
    prefix = str(tmp_path / "run")
    saves = []

    def save_state(p):
        saves.append(p)
        open(p, "wb").write(b"S")

    m = CheckpointManager(prefix, save_state, every_n=10, keep=2,
                          register_signal=True)
    for _ in range(35):
        m.step()
    # cadence saves at 10/20/30, rotation keeps the last 2
    kept = sorted(os.listdir(tmp_path))
    assert kept == ["run-0000020.ckpt", "run-0000030.ckpt"], kept
    # preemption triggers an immediate save of step 35
    trigger()
    assert preempted()
    assert os.path.exists(m.path_for(35))
    assert m.latest().endswith("run-0000035.ckpt")
    # idempotent: a second signal at the same step adds nothing
    n = len(os.listdir(tmp_path))
    trigger()
    assert len(os.listdir(tmp_path)) == n
    clear_preemption_hooks()


def test_sigterm_saves_checkpoint_subprocess(tmp_path):
    """Real signal path: a training loop in a subprocess gets SIGTERM and
    must leave a resumable checkpoint behind."""
    prefix = str(tmp_path / "job")
    code = f"""
import os, signal, sys, time
sys.path.insert(0, {REPO!r})
import numpy as onp
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, np, autograd
from incubator_mxnet_tpu.preemption import CheckpointManager

net = gluon.nn.Dense(4, in_units=8)
net.initialize()
mgr = CheckpointManager({prefix!r}, net.save_parameters, every_n=10**9)
x = np.array(onp.ones((2, 8), "float32"))
net(x).wait_to_read()
print("READY", flush=True)
while True:          # train "forever" until preempted
    net(x)
    mgr.step()
    time.sleep(0.01)
"""
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip().endswith("READY")
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=60)
    ckpts = [f for f in os.listdir(tmp_path) if f.endswith(".ckpt")]
    assert ckpts, "no checkpoint written on SIGTERM"
    # the checkpoint resumes
    net2 = gluon.nn.Dense(4, in_units=8)
    net2.load_parameters(str(tmp_path / sorted(ckpts)[-1]))
    assert net2.weight.data().shape == (4, 8)
