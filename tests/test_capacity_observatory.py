"""Capacity observatory (ISSUE 17): time-series ring histories and
windowed queries, Prometheus exposition grammar round-trip,
multi-window burn-rate alerts with hysteresis, the per-tenant cost
ledger's 5 % wall audit through the real serving seams, the observe-only
autoscale advisor over a seeded diurnal trace, and the disarmed-path
dead-branch gate."""
import os
import sys
import time

import numpy as onp
import pytest

from incubator_mxnet_tpu import serve
from incubator_mxnet_tpu.serve.advisor import AutoscaleAdvisor
from incubator_mxnet_tpu.serve.engine import (PageAllocator, PrefixCache)
from incubator_mxnet_tpu.telemetry import (burnrate, capacity, registry,
                                           timeseries)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 97


def _tools():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import capwatch
        import loadgen
    finally:
        sys.path.pop(0)
    return capwatch, loadgen


@pytest.fixture(autouse=True)
def _clean_observatory():
    yield
    timeseries.disable()
    timeseries.reset()
    burnrate.clear()
    capacity.disable()
    capacity.reset()
    registry.reset()


# ---------------------------------------------------------------------------
# time-series layer: rings and windowed queries
# ---------------------------------------------------------------------------

def _series(name, values, dt=1.0):
    """Build a history for gauge `name` on a virtual clock; returns the
    series key and the final virtual timestamp."""
    g = registry.gauge(name, "test series")
    t = 0.0
    for v in values:
        g.set(v)
        timeseries.sample_now(now=t)
        t += dt
    return name, t - dt


def test_ring_wraparound_keeps_newest():
    timeseries.enable(interval_s=1.0, samples=8, thread=False)
    key, _t = _series("t_wrap", range(20))
    hist = timeseries.history(key)
    # capacity-bounded: exactly the newest 8, oldest→newest, timestamps
    # strictly increasing across the wrap seam
    assert [v for _t, v in hist] == [12, 13, 14, 15, 16, 17, 18, 19]
    ts = [t for t, _v in hist]
    assert ts == sorted(ts) and len(set(ts)) == len(ts)
    assert timeseries.last(key) == (19.0, 19.0)


def test_rate_counter_reset_aware():
    timeseries.enable(interval_s=1.0, samples=64, thread=False)
    c = registry.counter("t_rst_total", "test counter")
    vals = [0, 10, 20, 5, 15]       # process restart between 20 and 5
    t = 0.0
    for v in vals:
        c._cell()[0] = v             # set absolute value (restart sim)
        timeseries.sample_now(now=t)
        t += 1.0
    # prometheus convention: a drop restarts from zero, so the post-
    # reset reading IS the increase: 10+10+5+10 = 35 over 4 s
    r = timeseries.rate("t_rst_total", window_s=10.0, now=4.0)
    assert r == pytest.approx(35.0 / 4.0)
    # plain delta is last-first (reset-blind by contract)
    assert timeseries.delta("t_rst_total", 10.0, now=4.0) == \
        pytest.approx(15.0)


def test_rate_needs_two_samples_and_known_series():
    timeseries.enable(interval_s=1.0, samples=8, thread=False)
    assert timeseries.rate("t_nope", 10.0) is None
    _series("t_one", [5])
    assert timeseries.rate("t_one", 10.0, now=0.0) is None
    assert timeseries.last("t_one") == (0.0, 5.0)


def test_percentile_over_time_matches_numpy():
    timeseries.enable(interval_s=1.0, samples=128, thread=False)
    rng = onp.random.RandomState(7)
    vals = rng.uniform(-10, 10, 101)
    key, t_end = _series("t_pct", vals)
    for q in (0, 10, 25, 50, 75, 90, 99, 100):
        got = timeseries.percentile_over_time(key, q, 1000.0, now=t_end)
        want = float(onp.percentile(vals, q, method="nearest"))
        assert got == pytest.approx(want), q


def test_window_frac_and_avg():
    timeseries.enable(interval_s=1.0, samples=64, thread=False)
    key, t_end = _series("t_frac", [0, 1, 1, 1, 0])
    assert timeseries.avg_over_time(key, 100.0, now=t_end) == \
        pytest.approx(0.6)
    assert timeseries.window_frac(key, 100.0, lambda v: v > 0.5,
                                  now=t_end) == pytest.approx(0.6)
    # window narrower than history: only the newest samples count
    assert timeseries.window_frac(key, 1.5, lambda v: v > 0.5,
                                  now=t_end) == pytest.approx(0.5)


def test_histogram_series_expand_to_count_and_sum():
    timeseries.enable(interval_s=1.0, samples=16, thread=False)
    h = registry.histogram("t_cap_lat_seconds", "test latencies")
    h.observe(0.1)
    timeseries.sample_now(now=0.0)
    h.observe(0.3)
    h.observe(0.5)
    timeseries.sample_now(now=1.0)
    assert timeseries.delta("t_cap_lat_seconds:count", 10.0, now=1.0) == 2
    assert timeseries.delta("t_cap_lat_seconds:sum", 10.0, now=1.0) == \
        pytest.approx(0.8)


def test_timeseries_sampler_thread_and_knobs(monkeypatch):
    monkeypatch.setenv("MXNET_TS_INTERVAL", "0.01")
    monkeypatch.setenv("MXNET_TS_SAMPLES", "32")
    registry.counter("t_thr_total", "test").inc()
    timeseries.enable()
    assert timeseries.is_enabled()
    deadline = time.monotonic() + 5.0
    while timeseries.sample_count() < 3:
        assert time.monotonic() < deadline, "sampler thread never ticked"
        time.sleep(0.01)
    timeseries.disable()
    # rings stay queryable after disable (post-run reads); reset drops
    assert timeseries.history("t_thr_total")
    timeseries.reset()
    assert timeseries.history("t_thr_total") is None


def test_timeseries_off_by_default_is_inert():
    assert not timeseries.is_enabled()
    assert timeseries.sample_count() == 0
    assert timeseries.series_names() == []


# ---------------------------------------------------------------------------
# Prometheus exposition: grammar round-trip (satellite 1)
# ---------------------------------------------------------------------------

def test_exposition_grammar_round_trip():
    capwatch, _ = _tools()
    registry.counter("t_rt_total", "a counter", labels={"k": "v"}).inc(3)
    registry.counter("t_rt_total", "a counter",
                     labels={"k": "w\"x\\y\nz"}).inc(2)
    registry.gauge("t_rt_gauge", "a gauge").set(1.5)
    h = registry.histogram("t_rt_seconds", "a histogram")
    for v in (0.002, 0.02, 0.2, 2.0):
        h.observe(v)
    registry.register_pull_gauge("t_rt_pull", lambda: 7.0,
                                 "a pull gauge", labels={"p": "q"})
    text = registry.exposition()

    # every non-comment line parses under the exposition grammar
    samples = capwatch.parse_exposition(text)
    by_key = {}
    for name, labels, value in samples:
        by_key[(name, tuple(sorted(labels.items())))] = value
    assert by_key[("t_rt_total", (("k", "v"),))] == 3
    # escaped label value round-trips to the original string
    assert by_key[("t_rt_total", (("k", 'w"x\\y\nz'),))] == 2
    assert by_key[("t_rt_gauge", ())] == 1.5
    assert by_key[("t_rt_pull", (("p", "q"),))] == 7.0

    # HELP/TYPE discipline: every sample's family announced once, with
    # the right TYPE, contiguously (prometheus requires one block per
    # family)
    lines = text.splitlines()
    types = {}
    for ln in lines:
        if ln.startswith("# TYPE "):
            _h, _t, fam, kind = ln.split(" ", 3)
            assert fam not in types, f"family {fam} announced twice"
            types[fam] = kind
    assert types["t_rt_total"] == "counter"
    assert types["t_rt_gauge"] == "gauge"
    assert types["t_rt_seconds"] == "histogram"
    assert types["t_rt_pull"] == "gauge"

    # histogram exposition: cumulative buckets ending at +Inf == count,
    # and sum/count samples present
    buckets = [(labels["le"], value) for name, labels, value in samples
               if name == "t_rt_seconds_bucket"]
    assert buckets[-1][0] == "+Inf" and buckets[-1][1] == 4
    counts = [v for _le, v in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert by_key[("t_rt_seconds_count", ())] == 4
    assert by_key[("t_rt_seconds_sum", ())] == pytest.approx(2.222)

    # family blocks are contiguous: HELP/TYPE/rows never interleave
    fam_of = []
    for ln in lines:
        if not ln or ln.startswith("#"):
            continue
        name = ln.split("{", 1)[0].split(" ", 1)[0]
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                name = name[:-len(suffix)]
        fam_of.append(name)
    seen, prev = set(), None
    for fam in fam_of:
        if fam != prev:
            assert fam not in seen, f"family {fam} rows not contiguous"
            seen.add(fam)
            prev = fam


# ---------------------------------------------------------------------------
# burn-rate alerts: fast/slow truth table + hysteresis (satellite 4)
# ---------------------------------------------------------------------------

def _burn_series(slo="t"):
    return registry.gauge("mx_slo_error_budget_burn",
                          "error-budget burn", labels={"slo": slo})


def _feed(g, value, t):
    g.set(value)
    timeseries.sample_now(now=t)


def test_burn_alert_fast_window_catches_flash_burst():
    timeseries.enable(interval_s=1.0, samples=512, thread=False)
    g = _burn_series()
    a = burnrate.BurnRateAlert("a", "t", windows=((60.0, 10.0),
                                                 (600.0, 2.0)))
    t = 0.0
    for _ in range(60):              # quiet hour-fragment
        _feed(g, 0.5, t)
        a.evaluate(now=t)
        t += 1.0
    assert not a.firing
    for _ in range(70):              # flash burst: fast window trips
        _feed(g, 25.0, t)
        a.evaluate(now=t)
        t += 1.0
    assert a.firing
    assert registry.gauge("mx_alert_firing",
                          labels={"alert": "a"}).value == 1


def test_burn_alert_slow_window_catches_slow_leak():
    timeseries.enable(interval_s=1.0, samples=2048, thread=False)
    g = _burn_series()
    # burn 3.0 sustained: below the fast 10x factor, above the slow 2x
    a = burnrate.BurnRateAlert("a", "t", windows=((60.0, 10.0),
                                                 (600.0, 2.0)))
    t = 0.0
    fired_at = None
    for _ in range(700):
        _feed(g, 3.0, t)
        a.evaluate(now=t)
        if a.firing and fired_at is None:
            fired_at = t
        t += 1.0
    assert a.firing and fired_at is not None


def test_burn_alert_hysteresis_no_flap_at_boundary():
    timeseries.enable(interval_s=1.0, samples=512, thread=False)
    g = _burn_series()
    a = burnrate.BurnRateAlert("a", "t", windows=((10.0, 10.0),),
                               clear_ratio=0.9, clear_holds=3)
    t = 0.0
    for _ in range(20):
        _feed(g, 20.0, t)
        a.evaluate(now=t)
        t += 1.0
    assert a.firing and a.transitions == 1
    # hover just under the fire threshold but above clear_ratio×factor:
    # a threshold-comparison alert would flap every sample; hysteresis
    # holds it firing with zero transitions
    for _ in range(30):
        _feed(g, 9.5, t)
        a.evaluate(now=t)
        t += 1.0
    assert a.firing and a.transitions == 1
    # drop below clear_ratio×factor: clears only after clear_holds
    # consecutive below evaluations
    for i in range(3):
        _feed(g, 1.0, t)
        a.evaluate(now=t)
        t += 1.0
        # the window average needs time to drain below 9.0 too
    while a.firing and t < 200:
        _feed(g, 1.0, t)
        a.evaluate(now=t)
        t += 1.0
    assert not a.firing and a.transitions == 2


def test_burn_alert_steady_trace_never_flaps():
    timeseries.enable(interval_s=1.0, samples=512, thread=False)
    g = _burn_series()
    a = burnrate.BurnRateAlert("a", "t", windows=((60.0, 10.0),
                                                 (600.0, 2.0)))
    t = 0.0
    for _ in range(300):             # steady nominal burn
        _feed(g, 0.8, t)
        a.evaluate(now=t)
        t += 1.0
    assert not a.firing and a.transitions == 0


def test_burn_alert_unknown_history_freezes_state():
    timeseries.enable(interval_s=1.0, samples=64, thread=False)
    a = burnrate.BurnRateAlert("a", "t")
    st = a.evaluate(now=0.0)         # no samples at all
    assert not st["firing"] and a.transitions == 0


def test_parse_windows_spec_and_defaults():
    assert burnrate.parse_windows("") == burnrate.DEFAULT_WINDOWS
    assert burnrate.parse_windows(None) == burnrate.DEFAULT_WINDOWS
    assert burnrate.parse_windows("120@5,900@1.5") == \
        ((120.0, 5.0), (900.0, 1.5))
    with pytest.raises(ValueError):
        burnrate.parse_windows("120")
    with pytest.raises(ValueError):
        burnrate.parse_windows("a@b")


def test_arm_default_builds_one_alert_per_slo():
    from incubator_mxnet_tpu.telemetry import slo

    timeseries.enable(interval_s=1.0, samples=16, thread=False)
    slo.latency("t_lat", "t_rt_seconds", 0.5)
    slo.latency("t_lat2", "t_rt2_seconds", 0.5)
    try:
        added = burnrate.arm_default()
        names = {f"burn_{s.name}" for s in slo.tracker().slos()}
        assert {a.name for a in burnrate.alerts()} >= names
        assert {a.name for a in added} == names
        # idempotent: a second arm adds nothing
        assert burnrate.arm_default() == []
    finally:
        slo.tracker().remove("t_lat")
        slo.tracker().remove("t_lat2")


# ---------------------------------------------------------------------------
# cost ledger through the REAL serving seams (stub decoder)
# ---------------------------------------------------------------------------

class _StubSlots:
    """Paged-interface stand-in (tests/test_gateway.py recipe)."""

    def __init__(self, max_slots=2, max_len=64, page_tokens=16,
                 prefill_chunk=64):
        self.max_slots = max_slots
        self.max_len = max_len
        self.page_tokens = page_tokens
        self.prefill_chunk = prefill_chunk
        pages_per_slot = -(-max_len // page_tokens)
        self.allocator = PageAllocator(max_slots * pages_per_slot + 1,
                                       page_tokens)
        self.prefix_cache = PrefixCache(self.allocator)

    def set_slot_pages(self, slot, pages):
        pass

    def clear_slot(self, slot):
        pass

    def prefill_chunk_step(self, slot, chunk_tokens, t_start, key,
                           temperature=1.0):
        n = len(chunk_tokens)
        return int(t_start) + n, n, 0

    def decode_step(self, last_tok, pos, active, key, temperature):
        return onp.where(active, last_tok + 1, last_tok).astype(onp.int32)

    def xla_program_count(self):
        return 0

    def release(self):
        pass


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


def _stub_gateway(max_slots=2, **gw_kwargs):
    reg = serve.ModelRegistry()
    reg.add("m", _StubSlots(max_slots=max_slots))
    return serve.Gateway(reg, **gw_kwargs)


def test_ledger_attributes_per_tenant_and_audits_wall():
    capacity.enable()
    capacity.reset()
    gw = _stub_gateway(max_slots=2)
    handles = [gw.submit("m", _prompt(8, seed=i), 6, tenant=tenant)
               for i, tenant in enumerate(["acme", "beta", "acme",
                                           "beta", "crawl"])]
    gw._drive_until(handles, timeout=30)
    led = capacity.ledger_report()
    for tenant in ("acme", "beta", "crawl"):
        row = led["tenants"][tenant]["m"]
        assert row["tokens"] > 0, (tenant, led)
        assert sum(row["device_s"].values()) > 0, (tenant, led)
        assert row["kv_page_s"] > 0, (tenant, led)
        assert "prefill" in row["device_s"], (tenant, led)
        assert "decode" in row["device_s"], (tenant, led)
    # the 5% wall audit (ISSUE 17 acceptance): per-tenant device-
    # seconds sum back to the measured serve wall
    wall = led["measured_wall_s"]
    assert wall > 0
    assert abs(led["device_seconds_sum"] - wall) <= 0.05 * wall, led
    # tokens attributed == tokens generated
    total_tokens = sum(len(h.tokens) for h in handles)
    ledger_tokens = sum(m["tokens"] for t in led["tenants"].values()
                        for m in t.values())
    assert ledger_tokens == total_tokens


def test_queue_wait_tenant_view_and_charge():
    capacity.enable()
    capacity.reset()
    gw = _stub_gateway(max_slots=1)   # force queueing behind 1 slot
    handles = [gw.submit("m", _prompt(8, seed=i), 4, tenant="acme")
               for i in range(4)]
    gw._drive_until(handles, timeout=30)
    rep = registry.report()
    key = 'mx_serve_queue_wait_seconds{tenant="acme"}'
    assert key in rep and rep[key]["count"] == 4, sorted(
        k for k in rep if k.startswith("mx_serve_queue_wait"))
    led = capacity.ledger_report()
    assert led["tenants"]["acme"]["m"]["queue_wait_s"] >= 0


def test_queue_wait_observed_once_despite_preemption():
    capacity.enable()
    capacity.reset()
    gw = _stub_gateway(max_slots=1, tiers="high,low")
    low = gw.submit("m", _prompt(24, seed=1), 12, tenant="bulk",
                    priority="low")
    deadline = time.monotonic() + 10
    while low.state != "dispatched":
        gw.step()
        assert time.monotonic() < deadline
    high = gw.submit("m", _prompt(8, seed=2), 4, tenant="vip",
                     priority="high")
    gw._drive_until([low, high], timeout=30)
    assert low.preemptions >= 1, "victim was never preempted"
    rep = registry.report()
    # the preempted request waited twice but is observed only at its
    # FIRST dispatch — resumes would double-count admission wait
    assert rep['mx_serve_queue_wait_seconds{tenant="bulk"}']["count"] == 1
    assert rep['mx_serve_queue_wait_seconds{tenant="vip"}']["count"] == 1


def test_fleet_report_carries_capacity_rollup():
    from incubator_mxnet_tpu.telemetry import fleet

    capacity.enable()
    capacity.reset()
    capacity.charge_tokens("acme", "m", 5)
    capacity.charge_device_seconds("acme", "m", "decode", 1.25)
    fleet.enable()
    try:
        rep = fleet.fleet_report()
    finally:
        fleet.disable()
    cap = rep["capacity"]
    assert cap["acme"]["m"]["tokens"] == 5
    assert cap["acme"]["m"]["device_s"]["decode"] == pytest.approx(1.25)


def test_charges_are_dead_branch_when_disarmed():
    assert not capacity.is_enabled()
    capacity.charge_tokens("t", "m")
    capacity.charge_device_seconds("t", "m", "decode", 1.0)
    capacity.split_device_seconds(["t"], "m", "prefill", 1.0)
    capacity.charge_kv_page_seconds("t", "m", 1.0)
    capacity.charge_queue_wait("t", "m", 1.0)
    assert capacity.measured_wall_s() == 0.0
    assert "t" not in capacity.ledger_report()["tenants"]
    # disarmed charges never mint series (registry.reset keeps keys
    # from other tests, so look for the tenant only this test used)
    assert not [k for k in registry.report()
                if k.startswith("mx_capacity_") and 'tenant="t"' in k]


# ---------------------------------------------------------------------------
# the disarmed-path <3% gate (satellite 4 / ISSUE acceptance)
# ---------------------------------------------------------------------------

def test_disarmed_observatory_probe_under_3pct():
    """Off-path contract: with the observatory disarmed, the serving
    seams pay one module-attribute load per probe site. Gate that probe
    at <3% of even a single stub decode_step host call — the cheapest
    real unit of serve work it rides on (bench_gpt_serve_timeseries
    measures the armed end-to-end figure)."""
    assert not capacity.is_enabled()
    slots = _StubSlots()
    last = onp.zeros(2, onp.int32)
    pos = onp.zeros(2, onp.int32)
    active = onp.ones(2, bool)
    iters = 2000
    best_step = float("inf")
    best_probe = float("inf")
    for _round in range(3):          # min-of-rounds: reject load spikes
        t0 = time.perf_counter()
        for _ in range(iters):
            slots.decode_step(last, pos, active, None, 1.0)
        best_step = min(best_step,
                        (time.perf_counter() - t0) / iters)
        t0 = time.perf_counter()
        for _ in range(iters):
            if capacity._ENABLED:    # the literal off-path pattern
                pass
        best_probe = min(best_probe,
                         (time.perf_counter() - t0) / iters)
    assert best_probe < 0.03 * best_step, (best_probe, best_step)


# ---------------------------------------------------------------------------
# autoscale advisor: decisions + the seeded diurnal acceptance gate
# ---------------------------------------------------------------------------

def _drive_signals(adv, occ, queue, burn_g, burn, t):
    registry.gauge("mx_serve_slot_occupancy", "occ").set(occ)
    registry.gauge("mx_gateway_queue_depth", "depth",
                   labels={"priority": "normal"}).set(queue)
    burn_g.set(burn)
    timeseries.sample_now(now=t)
    burnrate.evaluate_all(now=t)
    return adv.evaluate(now=t)


def test_advisor_holds_without_history():
    timeseries.enable(interval_s=1.0, samples=64, thread=False)
    adv = AutoscaleAdvisor("m")
    rec = adv.evaluate(now=0.0)
    assert rec["action"] == "hold"
    assert "no history" in rec["reason"]


def test_advisor_scale_up_names_evidence():
    timeseries.enable(interval_s=1.0, samples=256, thread=False)
    burn_g = _burn_series()
    adv = AutoscaleAdvisor("m", fast_window_s=10.0, slow_window_s=30.0)
    t = 0.0
    for _ in range(30):
        rec = _drive_signals(adv, 0.95, 4.0, burn_g, 0.1, t)
        t += 1.0
    assert rec["action"] == "scale_up" and rec["n"] == 1
    assert "mx_serve_slot_occupancy" in rec["reason"]
    assert "mx_gateway_queue_depth" in rec["reason"]
    assert rec["evidence"]["alerts_firing"] == []
    # flash-burst queue depth doubles the ask
    for _ in range(30):
        rec = _drive_signals(adv, 0.99, 40.0, burn_g, 0.1, t)
        t += 1.0
    assert rec["action"] == "scale_up" and rec["n"] == 2


def test_advisor_burn_alert_forces_scale_up():
    timeseries.enable(interval_s=1.0, samples=256, thread=False)
    burn_g = _burn_series()
    burnrate.add("burn_t", "t", windows=((10.0, 5.0),))
    adv = AutoscaleAdvisor("m")
    t = 0.0
    for _ in range(20):              # low occupancy, but budget on fire
        rec = _drive_signals(adv, 0.1, 0.0, burn_g, 50.0, t)
        t += 1.0
    assert rec["action"] == "scale_up"
    assert "burn_t" in rec["reason"]


def test_advisor_scale_down_respects_cooldown():
    timeseries.enable(interval_s=1.0, samples=1024, thread=False)
    burn_g = _burn_series()
    adv = AutoscaleAdvisor("m", fast_window_s=10.0, slow_window_s=30.0,
                           cooldown_s=100.0, log_len=2048)
    t = 0.0
    for _ in range(40):              # surge → scale_up
        _drive_signals(adv, 0.95, 4.0, burn_g, 0.1, t)
        t += 1.0
    # trough right after the surge: within cooldown ⇒ anti-flap hold
    for _ in range(60):
        rec = _drive_signals(adv, 0.05, 0.0, burn_g, 0.1, t)
        t += 1.0
        if t - 40.0 <= 100.0:
            assert rec["action"] != "scale_down", (t, rec)
    # cooldown expired and still idle ⇒ scale_down
    for _ in range(60):
        rec = _drive_signals(adv, 0.05, 0.0, burn_g, 0.1, t)
        t += 1.0
    assert rec["action"] == "scale_down"
    assert "cooldown" not in rec["reason"]


def test_advisor_diurnal_trace_sequence_deterministic():
    """The ISSUE 17 acceptance gate: a seeded `loadgen.diurnal_trace`
    day replayed through a host-side queue model on a VIRTUAL clock
    must produce scale_down in the trough, zero flaps across steady,
    scale_up through the surge/burst — deterministically (no wall
    clock anywhere)."""
    _capwatch, loadgen = _tools()
    events, segments = loadgen.diurnal_trace(
        models={"m": 1.0},
        tenants={"acme": (2.0, "normal"), "beta": (1.0, "normal")},
        seed=7, trough_s=300.0, steady_s=300.0, surge_s=300.0,
        burst_s=120.0, trough_rate=0.2, steady_rate=2.0,
        surge_rate=12.0, burst_rate=60.0)
    assert [s[0] for s in segments] == ["trough", "steady", "surge",
                                       "burst"]

    timeseries.enable(interval_s=5.0, samples=2048, thread=False)
    burn_g = _burn_series()
    adv = AutoscaleAdvisor("m", up_occupancy=0.85, down_occupancy=0.25,
                           fast_window_s=60.0, slow_window_s=300.0,
                           cooldown_s=120.0, burst_queue=16,
                           log_len=4096)
    # host-side queue model: capacity 4 req/s; occupancy = demand/cap
    # clipped, backlog beyond capacity queues; burn follows overload
    cap_rps, dt = 4.0, 5.0
    arrivals = sorted(e.t for e in events)
    i, backlog = 0, 0.0
    t = 0.0
    seg_actions = {name: [] for name, _s, _e in segments}
    end = segments[-1][2]
    while t < end:
        n_arr = 0
        while i < len(arrivals) and arrivals[i] < t + dt:
            n_arr += 1
            i += 1
        served = cap_rps * dt
        demand = backlog + n_arr
        backlog = max(0.0, demand - served)
        occ = min(1.0, demand / served)
        burn = 20.0 if backlog > 30 else (0.5 if occ < 0.9 else 3.0)
        rec = _drive_signals(adv, occ, backlog, burn_g, burn, t)
        for name, s, e in segments:
            if s <= t < e:
                seg_actions[name].append(rec["action"])
        t += dt
    # trough: scale_down recommended, never scale_up
    assert "scale_down" in seg_actions["trough"]
    assert "scale_up" not in seg_actions["trough"]
    # steady: zero flaps — once settled to hold it stays hold
    steady = seg_actions["steady"]
    first_hold = steady.index("hold")
    assert set(steady[first_hold:]) == {"hold"}, steady
    assert "scale_up" not in steady
    # surge and burst: scale_up reached, and never scale_down
    assert "scale_up" in seg_actions["surge"]
    assert "scale_down" not in seg_actions["surge"]
    assert "scale_up" in seg_actions["burst"]
    # collapsed sequence is the canonical diurnal story
    assert adv.recommendations() == ["hold", "scale_down", "hold",
                                     "scale_up"] \
        or adv.recommendations() == ["scale_down", "hold", "scale_up"], \
        adv.recommendations()
    # determinism: the published gauge names the final action
    rep = registry.report()
    assert rep['mx_advisor_recommendation{action="scale_up"}'][
        "value"] == 1


def test_advisor_gateway_arming_via_env(monkeypatch):
    monkeypatch.setenv("MXNET_ADVISOR", "0.0")   # evaluate every step
    gw = _stub_gateway()
    assert set(gw._advisors) == {"m"}
    assert timeseries.is_enabled()
    h = gw.submit("m", _prompt(8), 4, tenant="acme")
    gw._drive_until([h], timeout=30)
    log = gw.advisor_log()
    assert log and all(r["model"] == "m" for r in log)
    assert gw.advisor_log(tail=1)[0] == log[-1]


def test_capwatch_demo_is_reproducible_and_committed():
    import json

    capwatch, _ = _tools()
    rep = capwatch.run_demo()
    assert rep["recommendations"] == ["scale_down", "hold", "scale_up",
                                      "hold"]
    fires = [a for a in rep["alerts"] if a["event"] == "fire"]
    clears = [a for a in rep["alerts"] if a["event"] == "clear"]
    assert len(fires) == 1 and len(clears) == 1
    fixture = os.path.join(REPO, "benchmark", "capwatch_demo.json")
    with open(fixture) as f:
        committed = json.load(f)
    # the virtual clock makes the committed fixture exactly reproducible
    assert committed["recommendations"] == rep["recommendations"]
    assert committed["alerts"] == rep["alerts"]
    assert committed["ledger"]["device_seconds_sum"] == \
        rep["ledger"]["device_seconds_sum"]
    # registry.reset keeps zeroed rows from earlier tests in this
    # process, so compare the fixture's tenant rows as a subset
    for tenant, models in committed["ledger"]["tenants"].items():
        for model, row in models.items():
            assert rep["ledger"]["tenants"][tenant][model] == row, \
                (tenant, model)
