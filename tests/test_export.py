"""HybridBlock.export → StableHLO artifact → SymbolBlock.imports.

Reference parity: gluon/block.py:1480 `export` (model-symbol.json + params)
and gluon/block.py:1713 `SymbolBlock`. Here the "symbol" is a portable
serialized StableHLO program (jax.export), so a model can be reloaded and
run without its original Python class.
"""
import os

import numpy as onp
import pytest

from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.block import SymbolBlock


def _make_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.BatchNorm(), nn.Dense(4))
    net.initialize()
    net.hybridize()
    return net


def test_export_roundtrip_numerics(tmp_path):
    net = _make_net()
    x = np.random.uniform(size=(2, 8))
    y0 = net(x)
    y0 = net(x)  # compiled path
    sym, params = net.export(str(tmp_path / "model"))
    assert os.path.exists(sym)
    assert os.path.exists(params)
    assert os.path.exists(str(tmp_path / "model-symbol.stablehlo"))

    blk = SymbolBlock.imports(sym, ["data"], param_file=params)
    y1 = blk(x)
    onp.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_export_requires_forward(tmp_path):
    net = _make_net()
    with pytest.raises(RuntimeError, match="forward"):
        net.export(str(tmp_path / "model"))


def test_imports_requires_params(tmp_path):
    net = _make_net()
    x = np.random.uniform(size=(2, 8))
    net(x)
    sym, _ = net.export(str(tmp_path / "model"))
    with pytest.raises(ValueError, match="param_file"):
        SymbolBlock.imports(sym, ["data"])


def test_imports_bad_format(tmp_path):
    import json

    p = tmp_path / "bogus-symbol.json"
    p.write_text(json.dumps({"format": "nnvm-json-v1"}))
    with pytest.raises(ValueError, match="unsupported format"):
        SymbolBlock.imports(str(p), ["data"])


def test_symbolblock_collect_params(tmp_path):
    net = _make_net()
    x = np.random.uniform(size=(3, 8))
    net(x)
    sym, params = net.export(str(tmp_path / "model"))
    blk = SymbolBlock.imports(sym, ["data"], param_file=params)
    got = blk.collect_params()
    want = net.collect_params()
    assert set(got) == set(want)
    for k in want:
        onp.testing.assert_allclose(got[k].data().asnumpy(),
                                    want[k].data().asnumpy())
