"""Concurrency-correctness analyzer (`analysis.racecheck`, ISSUE 16):
per-rule seeded-defect fixtures (each RC rule fires on its committed
fixture and stays silent on the clean twin), the REAL two-thread ABBA
the runtime witness must catch *without* deadlocking, the whole-tree
static clean meta-gate, clean-gates over the audited suspect seams,
the off-path <3% overhead gate (disarmed `tracked_lock` returns the
raw `threading` primitive by construction), and the contention
histogram wiring."""
import os
import sys
import threading
import time

import pytest

from incubator_mxnet_tpu import analysis
from incubator_mxnet_tpu.analysis import racecheck_fixtures as fx
from incubator_mxnet_tpu.analysis.racecheck import (racecheck_paths,
                                                    racecheck_report,
                                                    racecheck_source,
                                                    runtime_report)
from incubator_mxnet_tpu.telemetry import locks, registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "incubator_mxnet_tpu")


@pytest.fixture()
def armed_witness():
    """Arm the runtime lock-order witness for one test, then restore."""
    was = locks.is_enabled()
    locks.enable()
    locks.reset()
    yield locks
    locks.reset()
    if not was:
        locks.disable()


# ---------------------------------------------------------------------------
# static tier: every rule fires on its seeded fixture, clean twin passes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule", sorted(fx.STATIC_FIXTURES))
def test_seeded_fixture_fires_exactly_its_rule(rule):
    bad, ok = fx.STATIC_FIXTURES[rule]
    rep = racecheck_source(bad, f"serve/{rule.lower()}_bad.py")
    assert sorted({f.rule for f in rep.findings}) == [rule], rep.summary()
    clean = racecheck_source(ok, f"serve/{rule.lower()}_ok.py")
    assert not clean.findings, clean.summary()


def test_rc001_names_attribute_and_guard():
    rep = racecheck_source(fx.RC001_BAD, "serve/rc001.py")
    (f,) = rep.findings
    assert f.state == "Pump._items"
    assert "._lock" in (f.lock or f.message)
    assert "_worker" in f.message          # the offending thread path


def test_rc002_names_check_then_act_site():
    rep = racecheck_source(fx.RC002_BAD, "serve/rc002.py")
    (f,) = rep.findings
    assert f.state == "Alloc._free"
    assert "take" in f.message and "interleave" in f.message


def test_rc003_names_both_witness_paths():
    rep = racecheck_source(fx.RC003_BAD, "serve/rc003.py")
    (f,) = rep.findings
    # both orders must be cited with their sites — a cycle with one
    # witness is unactionable
    assert f.message.count("->") >= 2
    assert "swap" in f.message and "route" in f.message


def test_rc004_names_blocking_call_and_lock():
    rep = racecheck_source(fx.RC004_BAD, "serve/rc004.py")
    (f,) = rep.findings
    assert ".join()" in f.message
    assert "_lock" in f.message


def test_rc004_sleep_threshold_knob(monkeypatch):
    src = ("import threading\nimport time\n"
           "_LOCK = threading.Lock()\n"
           "def poll():\n"
           "    with _LOCK:\n"
           "        time.sleep(0.02)\n")
    # default threshold 0.05: a 20 ms sleep is below the line
    assert not racecheck_source(src, "serve/poll.py").findings
    monkeypatch.setenv("MXNET_RACECHECK_SLEEP_S", "0.01")
    rep = racecheck_source(src, "serve/poll.py")
    assert [f.rule for f in rep.findings] == ["RC004"]


def test_noqa_escape_suppresses_finding():
    bad = fx.RC001_BAD.replace(
        "self._items.append(object())   # seeded RC001: no self._lock",
        "self._items.append(object())   # noqa: RC001 - drained at join")
    assert not racecheck_source(bad, "serve/rc001_noqa.py").findings


# ---------------------------------------------------------------------------
# runtime tier: the ABBA witness
# ---------------------------------------------------------------------------

def test_abba_witnessed_without_deadlock(armed_witness):
    t0 = time.monotonic()
    a, b = fx.run_abba(prefix="test.abba")
    assert time.monotonic() - t0 < 5.0      # sequenced, never contends
    inv = locks.inversions()
    assert len(inv) == 1
    rec = inv[0]
    assert rec["rule"] == "RC005"
    # both orders carry their own witness stack
    assert rec["witness_fwd"]["stack"] and rec["witness_rev"]["stack"]
    names = {a, b}
    assert set(rec["cycle"]) == names
    # folded into the analysis report
    rep = runtime_report()
    assert [f.rule for f in rep.findings] == ["RC005"]
    assert rep.findings[0].witness
    # counted in the metrics plane
    text = registry.exposition()
    assert "mx_lock_order_inversions_total" in text


def test_consistent_order_is_not_an_inversion(armed_witness):
    a = locks.tracked_lock("test.order.a", kind="lock")
    b = locks.tracked_lock("test.order.b", kind="lock")

    def nested():
        with a:
            with b:
                pass

    threads = [threading.Thread(target=nested) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5.0)
    nested()
    assert not locks.inversions()
    assert (a._tl_name, b._tl_name) in locks.order_graph()


def test_tracked_condition_wait_releases_order_state(armed_witness):
    cv = locks.tracked_lock("test.cv", kind="condition")
    other = locks.tracked_lock("test.cv.other", kind="lock")
    done = []

    def waiter():
        with cv:
            cv.wait(timeout=0.2)
            done.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    # while the waiter sleeps inside wait(), taking other->cv from here
    # must NOT read as an inversion: wait() released the lock
    with other:
        with cv:
            cv.notify_all()
    t.join(timeout=5.0)
    assert done
    assert not [i for i in locks.inversions()
                if "test.cv" in i["pair"] and "other" in i["pair"]]


# ---------------------------------------------------------------------------
# whole-tree meta-gates: the committed control plane analyzes clean
# ---------------------------------------------------------------------------

def test_tree_static_sweep_is_clean():
    rep = racecheck_report(include_runtime=False, name="tree")
    assert not rep.findings, rep.summary()
    assert rep.n_files >= 30
    assert rep.n_entry_points >= 10      # thread targets, hooks, probes
    assert rep.n_shared >= 15            # the map is actually populated


@pytest.mark.parametrize("seam", [
    "serve/gateway.py",     # hot_swap vs dispatch; preempt vs retire
    "serve/api.py",         # PageAllocator refcounts, prefix eviction
    "serve/scheduler.py",   # admission vs retire
    "serve/router.py",      # replica probes vs eviction
    "telemetry/fleet.py",   # flight-recorder fanout from excepthooks
    "fault/injection.py",   # chaos seams fired from worker threads
])
def test_suspect_seam_analyzes_clean(seam):
    rep = racecheck_paths([os.path.join(PKG, seam)], seam)
    assert not rep.findings, rep.summary()


def test_fleet_barrier_mutations_stay_guarded():
    """Regression for the genuine race this pass found: `_exchange_arrival`
    and `reset()` mutate the `_BARRIER` dict that the crash-fanout flight
    context reads from another thread — stripping the guard must re-fire
    RC001."""
    path = os.path.join(PKG, "telemetry", "fleet.py")
    with open(path, encoding="utf-8") as f:
        src = f.read()
    assert src.count("with _LOCK:") >= 3   # reset + arrival + stats
    # strip every guard inside _exchange_arrival and the finding returns
    import re

    broken = re.sub(
        r"(\ndef _exchange_arrival.*?)(\ndef )",
        lambda m: m.group(1).replace("    with _LOCK:", "    if True:")
        + m.group(2),
        src, count=1, flags=re.S)
    assert broken != src
    rep = racecheck_source(broken, "telemetry/fleet.py")
    assert any(f.rule in ("RC001", "RC002") for f in rep.findings), \
        "stripping the _BARRIER guard no longer fires — analyzer regressed"


# ---------------------------------------------------------------------------
# off-path overhead: disarmed tracked_lock is the raw primitive
# ---------------------------------------------------------------------------

def test_disarmed_tracked_lock_is_raw_primitive():
    was = locks.is_enabled()
    locks.disable()
    try:
        lk = locks.tracked_lock("test.offpath.lock", kind="lock")
        rl = locks.tracked_lock("test.offpath.rlock", kind="rlock")
        cv = locks.tracked_lock("test.offpath.cv", kind="condition")
        # zero overhead BY CONSTRUCTION: the factory hands back the raw
        # threading primitive itself, not a wrapper with a dead branch
        assert lk.__class__ is threading.Lock().__class__
        assert rl.__class__ is threading.RLock().__class__
        assert isinstance(cv, threading.Condition)
    finally:
        if was:
            locks.enable()


def test_disarmed_acquire_release_within_3pct():
    """The committed <3% gate. Both sides are the same class when
    disarmed, so this measures measurement noise — min-of-N with the
    two sides INTERLEAVED makes it stable: back-to-back phases let a
    CPU-frequency or scheduler shift land entirely on one side and bias
    the ratio on busy single-core runners."""
    was = locks.is_enabled()
    locks.disable()
    try:
        tracked = locks.tracked_lock("test.offpath.timing", kind="lock")
        raw = threading.Lock()

        def rep(lk):
            acquire, release = lk.acquire, lk.release
            t0 = time.perf_counter()
            for _ in range(50000):
                acquire()
                release()
            return time.perf_counter() - t0

        rep(raw), rep(tracked)              # warm both paths
        # min-of-N converges on the true floor (noise only ever adds
        # time), so a genuine >3% overhead fails every attempt while a
        # scheduler hiccup fails at most one — retry is sound here
        ratio = float("inf")
        for _attempt in range(3):
            best_raw = best_tracked = float("inf")
            for _ in range(9):
                best_raw = min(best_raw, rep(raw))
                best_tracked = min(best_tracked, rep(tracked))
            ratio = min(ratio, best_tracked / best_raw)
            if ratio < 1.03:
                break
        assert ratio < 1.03, f"disarmed overhead ratio {ratio:.4f}"
    finally:
        if was:
            locks.enable()


# ---------------------------------------------------------------------------
# contention telemetry wiring
# ---------------------------------------------------------------------------

def test_contention_histograms_and_table(armed_witness):
    lk = locks.tracked_lock("test.contend", kind="lock")
    stop = threading.Event()

    def holder():
        while not stop.is_set():
            with lk:
                time.sleep(0.001)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    for _ in range(20):
        with lk:
            pass
    stop.set()
    t.join(timeout=5.0)

    rows = locks.contention_table()
    row = rows[lk._tl_name]
    assert row["acquisitions"] >= 20
    assert row["held_sum_s"] > 0
    assert row["wait_max_s"] >= 0
    text = registry.exposition()
    assert "mx_lock_wait_seconds" in text
    assert "mx_lock_held_seconds" in text


def test_long_hold_warning_names_the_lock(armed_witness, monkeypatch,
                                          caplog):
    import logging

    monkeypatch.setenv("MXNET_RACECHECK_HOLD_S", "0.01")
    lk = locks.tracked_lock("test.longhold", kind="lock")
    with caplog.at_level(logging.WARNING):
        with lk:
            time.sleep(0.05)
    assert any("test.longhold" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# report plumbing: env knob, metrics counter, package export
# ---------------------------------------------------------------------------

def test_racecheck_raise_knob(monkeypatch):
    monkeypatch.setenv("MXNET_RACECHECK", "raise")
    from incubator_mxnet_tpu.base import MXNetError

    rep = analysis.RaceReport("seeded")
    with pytest.raises(MXNetError):
        # route a seeded fixture through the reporting path
        racecheck_source(fx.RC001_BAD, "serve/rc001.py", report=rep)
        analysis.racecheck._maybe_escalate(rep)


def test_findings_counter_increments():
    before = _counter_total("mx_racecheck_findings_total")
    rep = analysis.RaceReport("seeded")
    racecheck_source(fx.RC003_BAD, "serve/rc003.py", report=rep)
    analysis.racecheck._count_findings(rep)
    after = _counter_total("mx_racecheck_findings_total")
    assert after == before + 1


def _counter_total(name):
    total = 0.0
    for line in registry.exposition().splitlines():
        if line.startswith(name):
            total += float(line.rsplit(" ", 1)[1])
    return total


def test_package_exports():
    assert analysis.RACE_RULES.keys() == {
        "RC001", "RC002", "RC003", "RC004", "RC005"}
    for name in ("racecheck_report", "racecheck_source", "racecheck_paths",
                 "runtime_report", "RaceFinding", "RaceReport"):
        assert hasattr(analysis, name), name


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_demo_mode(tmp_path):
    import subprocess

    out_json = tmp_path / "rc.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "racecheck.py"),
         "--demo", "--json", str(out_json)],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    assert "RC005" in out.stdout
    import json

    data = json.loads(out_json.read_text())
    assert data["demo"]["runtime"]["rc005"] == 1
    assert all(e["clean_twin_clean"] for e in data["demo"]["static"])
