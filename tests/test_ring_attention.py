"""Ring attention (sequence-parallel exact attention) vs full softmax
attention on the virtual 8-device CPU mesh."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map

from incubator_mxnet_tpu.parallel.mesh import make_mesh, mesh_scope
from incubator_mxnet_tpu.parallel.ring_attention import (ring_attention,
                                                         ring_self_attention)

B, H, T, D = 2, 2, 32, 8
RNG = onp.random.RandomState(5)


def _qkv():
    return (jnp.asarray(RNG.randn(B, H, T, D).astype("float32")),
            jnp.asarray(RNG.randn(B, H, T, D).astype("float32")),
            jnp.asarray(RNG.randn(B, H, T, D).astype("float32")))


def _reference(q, k, v, causal=False):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / onp.sqrt(D)
    if causal:
        mask = onp.tril(onp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.fixture
def sp_mesh():
    mesh = make_mesh({"sp": 8})
    with mesh_scope(mesh):
        yield mesh


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(sp_mesh, causal):
    q, k, v = _qkv()
    out = ring_self_attention(q, k, v, mesh=sp_mesh, axis="sp",
                              causal=causal)
    ref = _reference(q, k, v, causal)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_match(sp_mesh):
    from functools import partial

    from jax.sharding import PartitionSpec as P

    q, k, v = _qkv()
    spec = P(None, None, "sp", None)
    ring = shard_map(partial(ring_attention, axis_name="sp",
                                 causal=True),
                         mesh=sp_mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)

    def loss_ring(q, k, v):
        return (ring(q, k, v) ** 2).sum()

    def loss_ref(q, k, v):
        return (_reference(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=5e-4, atol=5e-5)


def test_ring_attention_long_sequence_memory_shape(sp_mesh):
    # each device only ever materializes (B, H, T/8, T/8) score blocks
    T_long = 256
    q = jnp.asarray(RNG.randn(1, 1, T_long, D).astype("float32"))
    k = jnp.asarray(RNG.randn(1, 1, T_long, D).astype("float32"))
    v = jnp.asarray(RNG.randn(1, 1, T_long, D).astype("float32"))
    out = ring_self_attention(q, k, v, mesh=sp_mesh, axis="sp")
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / onp.sqrt(D)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-4, atol=2e-5)


def test_ring_requires_mesh():
    from incubator_mxnet_tpu import np as mnp

    q = mnp.random.uniform(size=(1, 1, 8, 4))
    with pytest.raises(ValueError, match="mesh"):
        ring_self_attention(q, q, q, mesh=None)
