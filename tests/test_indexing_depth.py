"""Indexing depth: getitem/setitem forms, take/gather families, boolean
masks, put_along_axis — checked against NumPy (reference:
`tests/python/unittest/test_numpy_op.py` indexing corpus +
`src/operator/tensor/indexing_op.h`)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np, npx

RNG = onp.random.RandomState(11)


def _arr(*shape):
    return RNG.uniform(-5, 5, shape).astype("float32")


def _check_get(ref, key):
    got = np.array(ref)[key].asnumpy()
    onp.testing.assert_array_equal(got, ref[key])


# -- basic slicing -----------------------------------------------------------

def test_getitem_int():
    _check_get(_arr(5, 4), 2)


def test_getitem_negative_int():
    _check_get(_arr(5, 4), -1)


def test_getitem_slice():
    _check_get(_arr(8, 4), slice(2, 6))


def test_getitem_slice_step():
    _check_get(_arr(8, 4), slice(1, 8, 2))


def test_getitem_slice_negative_step():
    _check_get(_arr(8, 4), slice(None, None, -1))


def test_getitem_slice_negative_bounds():
    _check_get(_arr(8, 4), slice(-6, -2))


def test_getitem_tuple_mixed():
    _check_get(_arr(6, 5, 4), (2, slice(1, 4)))


def test_getitem_ellipsis():
    _check_get(_arr(3, 4, 5), (Ellipsis, 2))


def test_getitem_newaxis():
    a = _arr(3, 4)
    got = np.array(a)[:, None].asnumpy()
    onp.testing.assert_array_equal(got, a[:, None])


def test_getitem_full_slice_is_view_semantics():
    a = _arr(4, 4)
    x = np.array(a)
    onp.testing.assert_array_equal(x[:].asnumpy(), a)


def test_getitem_scalar_result():
    a = _arr(3, 3)
    assert float(np.array(a)[1, 2].asnumpy()) == pytest.approx(a[1, 2])


# -- advanced indexing -------------------------------------------------------

def test_getitem_int_array():
    a = _arr(6, 4)
    idx = onp.array([0, 3, 5])
    got = np.array(a)[np.array(idx.astype("int32"))].asnumpy()
    onp.testing.assert_array_equal(got, a[idx])


def test_getitem_int_array_negative():
    a = _arr(6, 4)
    idx = onp.array([-1, -6])
    got = np.array(a)[np.array(idx.astype("int32"))].asnumpy()
    onp.testing.assert_array_equal(got, a[idx])


def test_getitem_two_int_arrays():
    a = _arr(5, 5)
    r = onp.array([0, 2, 4])
    c = onp.array([1, 3, 0])
    got = np.array(a)[np.array(r.astype("int32")),
                      np.array(c.astype("int32"))].asnumpy()
    onp.testing.assert_array_equal(got, a[r, c])


def test_getitem_bool_mask():
    a = _arr(6, 3)
    m = a[:, 0] > 0
    got = np.array(a)[np.array(m)].asnumpy()
    onp.testing.assert_array_equal(got, a[m])


def test_getitem_bool_mask_full():
    a = _arr(4, 3)
    m = a > 0
    got = np.array(a)[np.array(m)].asnumpy()
    onp.testing.assert_array_equal(got, a[m])


# -- setitem -----------------------------------------------------------------

def test_setitem_int():
    a = _arr(4, 3)
    x = np.array(a)
    x[1] = 9.0
    a[1] = 9.0
    onp.testing.assert_array_equal(x.asnumpy(), a)


def test_setitem_slice():
    a = _arr(6, 3)
    x = np.array(a)
    x[2:4] = 0.0
    a[2:4] = 0.0
    onp.testing.assert_array_equal(x.asnumpy(), a)


def test_setitem_strided_slice():
    a = _arr(6, 3)
    x = np.array(a)
    x[::2] = -1.0
    a[::2] = -1.0
    onp.testing.assert_array_equal(x.asnumpy(), a)


def test_setitem_array_value():
    a = _arr(4, 3)
    v = _arr(3)
    x = np.array(a)
    x[2] = np.array(v)
    a[2] = v
    onp.testing.assert_array_equal(x.asnumpy(), a)


def test_setitem_broadcast_row():
    a = _arr(4, 3)
    v = _arr(1, 3)
    x = np.array(a)
    x[1:3] = np.array(v)
    a[1:3] = v
    onp.testing.assert_array_equal(x.asnumpy(), a)


def test_setitem_int_array():
    a = _arr(6, 2)
    x = np.array(a)
    idx = onp.array([1, 4])
    x[np.array(idx.astype("int32"))] = 5.0
    a[idx] = 5.0
    onp.testing.assert_array_equal(x.asnumpy(), a)


def test_setitem_bool_mask():
    a = _arr(5, 2)
    m = a > 0
    x = np.array(a)
    x[np.array(m)] = 0.0
    a[m] = 0.0
    onp.testing.assert_array_equal(x.asnumpy(), a)


def test_setitem_bumps_version():
    x = np.array(_arr(3, 3))
    v0 = x._version
    x[0] = 1.0
    assert x._version > v0


# -- take family -------------------------------------------------------------

def test_take_flat():
    a = _arr(8)
    idx = onp.array([0, 3, 7, 3])
    got = np.take(np.array(a), np.array(idx.astype("int32"))).asnumpy()
    onp.testing.assert_array_equal(got, onp.take(a, idx))


def test_take_axis0():
    a = _arr(5, 3)
    idx = onp.array([4, 0])
    got = np.take(np.array(a), np.array(idx.astype("int32")),
                  axis=0).asnumpy()
    onp.testing.assert_array_equal(got, onp.take(a, idx, axis=0))


def test_take_axis1():
    a = _arr(3, 6)
    idx = onp.array([5, 2, 2])
    got = np.take(np.array(a), np.array(idx.astype("int32")),
                  axis=1).asnumpy()
    onp.testing.assert_array_equal(got, onp.take(a, idx, axis=1))


def test_take_clip_mode():
    a = _arr(4)
    idx = onp.array([0, 10, -10])
    got = np.take(np.array(a), np.array(idx.astype("int32")),
                  mode="clip").asnumpy()
    onp.testing.assert_array_equal(got, onp.take(a, idx, mode="clip"))


def test_take_along_axis():
    a = _arr(4, 5)
    idx = RNG.randint(0, 5, (4, 2))
    got = np.take_along_axis(np.array(a), np.array(idx.astype("int64")),
                             axis=1).asnumpy()
    onp.testing.assert_array_equal(got, onp.take_along_axis(a, idx, axis=1))


def test_take_grad_accumulates_duplicates():
    a = np.array(_arr(4))
    a.attach_grad()
    idx = np.array(onp.array([1, 1, 2], "int32"))
    with autograd.record():
        y = np.take(a, idx)
    y.backward()
    onp.testing.assert_array_equal(a.grad.asnumpy(), [0.0, 2.0, 1.0, 0.0])


def test_put_along_axis():
    a = _arr(3, 4)
    idx = onp.array([[1], [0], [3]])
    x = np.array(a)
    got = np.put_along_axis(x, np.array(idx.astype("int64")),
                            np.array(onp.full((3, 1), 9.0, "float32")),
                            axis=1)
    ref = a.copy()
    onp.put_along_axis(ref, idx, 9.0, axis=1)
    onp.testing.assert_array_equal(x.asnumpy(), ref)
    del got


# -- gather_nd / pick (npx) --------------------------------------------------

def test_gather_nd():
    a = _arr(4, 5)
    idx = onp.array([[0, 3], [1, 0]], "int32")   # (2 dims, 2 points)
    got = npx.gather_nd(np.array(a), np.array(idx)).asnumpy()
    onp.testing.assert_array_equal(got, a[idx[0], idx[1]])


def test_pick():
    a = _arr(4, 5)
    idx = onp.array([0, 2, 4, 1], "float32")
    got = npx.pick(np.array(a), np.array(idx)).asnumpy()
    ref = a[onp.arange(4), idx.astype("int64")]
    onp.testing.assert_array_equal(got, ref)


def test_one_hot():
    idx = onp.array([0, 2, 1], "float32")
    got = npx.one_hot(np.array(idx), 4).asnumpy()
    onp.testing.assert_array_equal(got, onp.eye(4, dtype="float32")[
        idx.astype("int64")])


# -- where / nonzero / searching ---------------------------------------------

def test_where_three_arg():
    c = _arr(3, 4) > 0
    a, b = _arr(3, 4), _arr(3, 4)
    got = np.where(np.array(c), np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_array_equal(got, onp.where(c, a, b))


def test_nonzero():
    a = onp.array([[1.0, 0.0], [0.0, 3.0]], "float32")
    got = np.nonzero(np.array(a))
    ref = onp.nonzero(a)
    for g, r in zip(got, ref):
        onp.testing.assert_array_equal(g.asnumpy(), r)


def test_argwhere():
    a = onp.array([[1.0, 0.0], [0.0, 3.0]], "float32")
    got = np.argwhere(np.array(a)).asnumpy()
    onp.testing.assert_array_equal(got, onp.argwhere(a))


def test_flatnonzero():
    a = onp.array([0.0, 2.0, 0.0, 1.0], "float32")
    got = np.flatnonzero(np.array(a)).asnumpy()
    onp.testing.assert_array_equal(got, onp.flatnonzero(a))


def test_searchsorted():
    a = onp.array([1.0, 3.0, 5.0, 7.0], "float32")
    v = onp.array([0.0, 4.0, 9.0], "float32")
    got = np.searchsorted(np.array(a), np.array(v)).asnumpy()
    onp.testing.assert_array_equal(got, onp.searchsorted(a, v))


def test_argmax_axis():
    a = _arr(4, 5)
    for ax in (0, 1, None):
        got = np.argmax(np.array(a), axis=ax).asnumpy()
        onp.testing.assert_array_equal(got, onp.argmax(a, axis=ax))


def test_argmin_axis():
    a = _arr(4, 5)
    for ax in (0, 1, None):
        got = np.argmin(np.array(a), axis=ax).asnumpy()
        onp.testing.assert_array_equal(got, onp.argmin(a, axis=ax))


def test_argsort_and_sort():
    a = _arr(3, 6)
    onp.testing.assert_array_equal(np.argsort(np.array(a)).asnumpy(),
                                   onp.argsort(a, kind="stable"))
    onp.testing.assert_allclose(np.sort(np.array(a)).asnumpy(),
                                onp.sort(a), rtol=0)


def test_topk_values():
    a = _arr(3, 8)
    got = npx.topk(np.array(a), k=3, ret_typ="value", axis=-1).asnumpy()
    ref = -onp.sort(-a, axis=-1)[:, :3]
    onp.testing.assert_allclose(got, ref, rtol=0)


def test_unique():
    a = onp.array([3.0, 1.0, 3.0, 2.0, 1.0], "float32")
    got = np.unique(np.array(a)).asnumpy()
    onp.testing.assert_array_equal(got, onp.unique(a))


def test_unique_with_counts():
    a = onp.array([3.0, 1.0, 3.0, 2.0, 1.0], "float32")
    vals, counts = np.unique(np.array(a), return_counts=True)
    rv, rc = onp.unique(a, return_counts=True)
    onp.testing.assert_array_equal(vals.asnumpy(), rv)
    onp.testing.assert_array_equal(counts.asnumpy(), rc)


# -- boolean_mask / masking ops ----------------------------------------------

def test_npx_boolean_mask():
    a = _arr(5, 3)
    m = onp.array([1, 0, 1, 0, 1], "float32")
    got = npx.boolean_mask(np.array(a), np.array(m)).asnumpy()
    onp.testing.assert_array_equal(got, a[m.astype(bool)])


def test_npx_sequence_mask():
    a = _arr(4, 3)     # (T, N)
    vl = onp.array([2, 1, 3], "float32")
    got = npx.sequence_mask(np.array(a), np.array(vl),
                            use_sequence_length=True).asnumpy()
    ref = a.copy()
    for n, l in enumerate(vl.astype(int)):
        ref[l:, n] = 0
    onp.testing.assert_array_equal(got, ref)


# -- grads through indexing --------------------------------------------------

def test_getitem_slice_grad():
    a = np.array(_arr(5, 3))
    a.attach_grad()
    with autograd.record():
        y = a[1:4]
    y.backward()
    ref = onp.zeros((5, 3), "float32")
    ref[1:4] = 1.0
    onp.testing.assert_array_equal(a.grad.asnumpy(), ref)


def test_getitem_int_array_grad():
    a = np.array(_arr(5))
    a.attach_grad()
    idx = np.array(onp.array([0, 0, 4], "int32"))
    with autograd.record():
        y = a[idx]
    y.backward()
    onp.testing.assert_array_equal(a.grad.asnumpy(),
                                   [2.0, 0.0, 0.0, 0.0, 1.0])


def test_where_grad_routes_by_condition():
    c = np.array(onp.array([True, False], dtype=bool))
    a = np.array(onp.array([1.0, 2.0], "float32"))
    b = np.array(onp.array([3.0, 4.0], "float32"))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = np.where(c, a, b)
    y.backward()
    onp.testing.assert_array_equal(a.grad.asnumpy(), [1.0, 0.0])
    onp.testing.assert_array_equal(b.grad.asnumpy(), [0.0, 1.0])


# -- degenerate shapes -------------------------------------------------------

def test_getitem_empty_slice():
    a = _arr(4, 3)
    got = np.array(a)[2:2].asnumpy()
    assert got.shape == (0, 3)


def test_take_empty_indices():
    a = _arr(4)
    got = np.take(np.array(a),
                  np.array(onp.zeros((0,), "int32"))).asnumpy()
    assert got.shape == (0,)


def test_setitem_empty_slice_noop():
    a = _arr(4, 3)
    x = np.array(a)
    x[2:2] = 7.0
    onp.testing.assert_array_equal(x.asnumpy(), a)


def test_index_1elem_array():
    a = _arr(1, 1)
    assert float(np.array(a)[0, 0].asnumpy()) == pytest.approx(a[0, 0])