"""serve.gateway — multi-tenant front door over co-resident engines
(ISSUE 9).

Three layers of coverage, all deterministic on CPU:

- host-only unit tests for the tenancy primitives (`parse_tiers`,
  `parse_quota`, `TokenBucket`, `WDRRQueue`): weighted deficit round
  robin converges to the weights, quotas defer (never drop), starved
  outsized heads make progress;
- gateway-logic tests against the stub slot decoder (pure host
  arithmetic, no XLA compile — the `quick`-marked ones): tier-ordered
  dispatch, preemption that keeps tokens and re-queues remaining work,
  the deadline-while-preempted classification (DeadlineExceeded,
  retryable — never an eviction error), per-tenant quota throttling,
  labeled queue-depth gauges, the `gateway_step` fault seam, gateway
  spans joining the per-request trace, and the flight-recorder context;
- the trace-replay ACCEPTANCE GATE on real compiled engines: two
  co-resident tiny GPTs, three tenants across three tiers on a recorded
  trace — every request completes or fails loudly, the high tier's TTFT
  p99 under contention stays within 1.5× its solo value, preempted
  low-priority requests all finish, the per-engine zero-steady-state-
  recompile gate holds, and the `slo.gateway_ttft` error budget is
  compliant for the high tier.
"""
import json
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, serve
from incubator_mxnet_tpu.models.gpt import gpt_tiny
from incubator_mxnet_tpu.serve import tenancy
from incubator_mxnet_tpu.serve.engine import (PageAllocator,
                                              PagePoolExhausted,
                                              PrefixCache)
from incubator_mxnet_tpu.serve.scheduler import (DeadlineExceeded,
                                                 EngineClosed, QueueFull)
from incubator_mxnet_tpu.telemetry import registry, slo, tracing

VOCAB = 97


# ---------------------------------------------------------------------------
# tenancy primitives — pure host (quick)
# ---------------------------------------------------------------------------

def test_parse_tiers_default_and_errors():
    assert tenancy.parse_tiers(None) == tenancy.DEFAULT_TIERS
    assert tenancy.parse_tiers("") == tenancy.DEFAULT_TIERS
    assert tenancy.parse_tiers("gold, silver ,bronze") == \
        ("gold", "silver", "bronze")
    with pytest.raises(ValueError):
        tenancy.parse_tiers("a,,b")
    with pytest.raises(ValueError):
        tenancy.parse_tiers("a,b,a")


def test_parse_quota():
    assert tenancy.parse_quota(None) == (None, None)
    assert tenancy.parse_quota("") == (None, None)
    assert tenancy.parse_quota("0") == (None, None)      # 0 = unmetered
    assert tenancy.parse_quota("100") == (100.0, 400.0)  # burst = 4×rate
    assert tenancy.parse_quota("100:50") == (100.0, 50.0)


def test_token_bucket_refill_debit_credit():
    b = tenancy.TokenBucket(10.0, 20.0)        # explicit virtual clock
    assert b.level(0.0) == 20.0                # starts full
    assert b.try_debit(15.0, 0.0)
    assert b.level(0.0) == 5.0
    assert not b.try_debit(10.0, 0.0)          # defer, level untouched
    assert b.level(0.0) == 5.0
    assert b.level(1.0) == 15.0                # +10 tokens/s refill
    b.credit(10.0)                             # refund caps at burst
    assert b.level(1.0) == 20.0
    # unmetered: no level, every debit succeeds
    free = tenancy.TokenBucket(None)
    assert free.level(0.0) is None
    assert free.try_debit(10**9, 0.0)
    with pytest.raises(ValueError):
        tenancy.TokenBucket(-1.0)
    with pytest.raises(ValueError):
        tenancy.Tenant("t", weight=0.0)


def test_wdrr_weighted_share():
    """Costs above the quantum make the weights visible: tenant a at
    weight 2 accumulates deficit twice as fast, so the pop sequence
    converges to a 2:1 token share."""
    q = tenancy.WDRRQueue(quantum=10)
    for i in range(6):
        q.push("a", ("a", i))
    for i in range(3):
        q.push("b", ("b", i))
    assert len(q) == 9
    w = {"a": 2.0, "b": 1.0}
    order = [q.pop_next(w, lambda r: 40.0, lambda r: True)[0]
             for _ in range(9)]
    assert order[:6] == ["a", "a", "b", "a", "a", "b"]
    assert order.count("a") == 6 and order.count("b") == 3
    assert len(q) == 0 and q.pop_next(w, lambda r: 1.0,
                                      lambda r: True) is None


def test_wdrr_starvation_fallback():
    """A lone head whose cost dwarfs the quantum still pops (its tenant
    pays by going deeply negative) — bounded unfairness over starvation."""
    q = tenancy.WDRRQueue(quantum=10)
    q.push("big", "x")
    assert q.pop_next({}, lambda r: 1000.0, lambda r: True) == "x"
    assert len(q) == 0


def test_wdrr_defers_without_burning_deficit():
    q = tenancy.WDRRQueue(quantum=10)
    q.push("a", "a0")
    q.push("b", "b0")
    # a's head is not dispatchable (quota/backlog): b pops, a's deficit
    # is NOT granted-and-lost — it simply waits
    got = q.pop_next({}, lambda r: 1.0, lambda r: r != "a0")
    assert got == "b0"
    assert q._deficit["a"] == 0.0
    assert q.pop_next({}, lambda r: 1.0, lambda r: False) is None
    assert q.items() == ["a0"]
    assert q.remove("a0") and not q.remove("a0")
    assert len(q) == 0


# ---------------------------------------------------------------------------
# gateway logic against a stub decoder (no XLA, quick)
# ---------------------------------------------------------------------------

class _StubSlots:
    """Paged-interface stand-in (same recipe as test_serve.py): pure
    host arithmetic over a REAL allocator/prefix cache. The final
    prefill chunk emits the prompt's length as the first token, decode
    increments — so a request preempted mid-decode and resumed from
    ``prompt + tokens`` must continue the same arithmetic run."""

    def __init__(self, max_slots=2, max_len=64, page_tokens=16,
                 prefill_chunk=64):
        self.max_slots = max_slots
        self.max_len = max_len
        self.page_tokens = page_tokens
        self.prefill_chunk = prefill_chunk
        pages_per_slot = -(-max_len // page_tokens)
        self.allocator = PageAllocator(max_slots * pages_per_slot + 1,
                                       page_tokens)
        self.prefix_cache = PrefixCache(self.allocator)

    def set_slot_pages(self, slot, pages):
        pass

    def clear_slot(self, slot):
        pass

    def prefill_chunk_step(self, slot, chunk_tokens, t_start, key,
                           temperature=1.0):
        n = len(chunk_tokens)
        return int(t_start) + n, n, 0

    def decode_step(self, last_tok, pos, active, key, temperature):
        return onp.where(active, last_tok + 1, last_tok).astype(onp.int32)

    def xla_program_count(self):
        return 0

    def release(self):
        pass


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


def _stub_gateway(max_slots=2, **gw_kwargs):
    reg = serve.ModelRegistry()
    reg.add("m", _StubSlots(max_slots=max_slots))
    return serve.Gateway(reg, **gw_kwargs)


def test_gateway_constructor_validation():
    with pytest.raises(TypeError):
        serve.Gateway(object())
    with pytest.raises(ValueError):
        serve.Gateway(serve.ModelRegistry())          # empty registry
    reg = serve.ModelRegistry()
    reg.add("m", _StubSlots())
    with pytest.raises(ValueError):
        reg.add("m", _StubSlots())                    # duplicate name
    with pytest.raises(ValueError):
        reg.add("m2", _StubSlots(), share=0.0)
    # engine kwargs cannot retarget a pre-built decoder
    reg2 = serve.ModelRegistry()
    reg2.add("m", _StubSlots(), max_slots=4)
    with pytest.raises(ValueError) as ei:
        serve.Gateway(reg2)
    assert "pre-built" in str(ei.value)


def test_gateway_custom_tiers_and_env_knobs():
    from incubator_mxnet_tpu.test_utils import environment

    gw = _stub_gateway(tiers="gold,bronze")
    assert gw.tiers == ("gold", "bronze")
    h = gw.submit("m", _prompt(4), 1)           # default = middle tier
    assert h.priority == "bronze"
    gw._drive_until([h], timeout=10)
    with environment({"MXNET_SERVE_PRIORITY_TIERS": "x,y,z",
                      "MXNET_GATEWAY_PREEMPT": "0"}):
        gw2 = _stub_gateway()
        assert gw2.tiers == ("x", "y", "z")
        assert not gw2.preempt_enabled


def test_gateway_submit_validation():
    gw = _stub_gateway()
    with pytest.raises(ValueError):
        gw.submit("nope", _prompt(4), 2)              # unknown model
    with pytest.raises(ValueError):
        gw.submit("m", _prompt(4), 2, priority="vip")  # unknown tier
    with pytest.raises(ValueError):
        gw.submit("m", onp.zeros((0,), onp.int32), 2)
    with pytest.raises(ValueError):
        gw.submit("m", _prompt(4), 0)
    with pytest.raises(ValueError):
        gw.submit("m", _prompt(60), 10)               # 70 > max_len 64
    # a request that could NEVER fit the model's page pool is rejected
    # at submit with the loud PagePoolExhausted, not deferred forever
    stub = _StubSlots(max_slots=1)
    stub.allocator = PageAllocator(3, 16)             # 2 usable pages
    stub.prefix_cache = PrefixCache(stub.allocator)
    reg = serve.ModelRegistry()
    reg.add("tiny", stub)
    gw2 = serve.Gateway(reg)
    with pytest.raises(PagePoolExhausted):
        gw2.submit("tiny", _prompt(30), 10)


def test_gateway_queue_backpressure_raises():
    from incubator_mxnet_tpu.fault.retry import classify_exception

    gw = _stub_gateway(max_queue=2)
    gw.submit("m", _prompt(4), 2)
    gw.submit("m", _prompt(5), 2)
    with pytest.raises(QueueFull) as ei:
        gw.submit("m", _prompt(6), 2)
    assert "capacity" in str(ei.value)
    assert classify_exception(ei.value) == "retryable"


def test_gateway_roundtrip_stub():
    gw = _stub_gateway()
    d0 = registry.counter("mx_gateway_dispatch_total",
                          labels={"model": "m",
                                  "priority": "normal"}).value
    out = gw.generate("m", _prompt(4), 3, tenant="acme")
    # stub arithmetic: first token = prompt len, then +1 per decode
    assert list(out[-3:]) == [4, 5, 6]
    assert out.dtype == onp.int32 and out.shape == (7,)
    t = gw.tenant("acme")
    assert t.dispatched == 1 and t.tokens_out == 3
    d1 = registry.counter("mx_gateway_dispatch_total",
                          labels={"model": "m",
                                  "priority": "normal"}).value
    assert d1 == d0 + 1


def test_priority_dispatch_order():
    """With preemption off, tier order still rules dispatch: when the
    single slot frees, the queued high request beats the earlier-queued
    low one."""
    gw = _stub_gateway(max_slots=1, preempt=False)
    a = gw.submit("m", _prompt(4), 4, priority="normal")
    gw.step()
    assert a.state == "dispatched"
    b = gw.submit("m", _prompt(5), 2, priority="low")
    c = gw.submit("m", _prompt(6), 2, priority="high")
    while not a.done:
        gw.step()
    gw.step()
    # the high request took the freed slot (a short one may even finish
    # within the step); the earlier-queued low one is still waiting
    assert c.state in ("dispatched", "done") and b.state == "queued"
    gw._drive_until([b, c], timeout=10)
    assert b.result() == [5, 6] and c.result() == [6, 7]


def test_preemption_resumes_with_tokens_intact():
    """The tentpole semantics: a high-tier arrival preempts the running
    low-tier slot; the victim keeps its tokens, re-enters the queue as
    remaining-chunk work, and its final stream is CONTINUOUS — exactly
    what an uninterrupted run would have produced."""
    gw = _stub_gateway(max_slots=1)
    low = gw.submit("m", _prompt(4), 8, tenant="crawl", priority="low")
    gw.step()
    # one step = prefill + one decode in the stub: two tokens in flight
    assert low.state == "dispatched" and low.tokens == [4, 5]
    ev0 = registry.counter("mx_serve_evictions_total",
                           labels={"reason": "preempted"}).value
    high = gw.submit("m", _prompt(6, seed=1), 3, tenant="acme",
                     priority="high")
    gw.step()
    # the victim is back in the queue with its progress intact ...
    assert low.state == "queued" and low.preemptions == 1
    assert low.tokens == [4, 5]
    assert high.state == "dispatched"
    # ... accounted everywhere the operator looks
    assert gw.preemptions_total == 1
    assert gw.tenant("crawl").preempted == 1
    ev1 = registry.counter("mx_serve_evictions_total",
                           labels={"reason": "preempted"}).value
    assert ev1 == ev0 + 1
    gw._drive_until([low, high], timeout=10)
    assert high.result() == [6, 7, 8]
    # continuity across the preemption: resume prefilled prompt+tokens,
    # so the stream is the same run an undisturbed request produces
    assert low.result() == list(range(4, 12))
    assert low.state == "done" and len(low.tokens) == low.max_new


def test_preempted_deadline_expiry_classifies_retryable():
    """A preempted request whose deadline expires while RE-QUEUED fails
    as DeadlineExceeded (retryable) — never an eviction/shutdown error:
    the preemption was the gateway's choice, not the client's fault."""
    gw = _stub_gateway(max_slots=1)
    low = gw.submit("m", _prompt(4), 8, tenant="crawl", priority="low",
                    deadline_s=0.3)
    gw.step()
    high = gw.submit("m", _prompt(6, seed=1), 30, tenant="acme",
                     priority="high")
    gw.step()
    assert low.state == "queued" and low.preemptions == 1
    time.sleep(0.35)
    gw.step()                                   # expiry sweep
    assert low.state == "failed"
    assert isinstance(low.error, DeadlineExceeded)
    assert not isinstance(low.error, EngineClosed)
    assert low.error_class == "retryable"
    assert "preemption" in str(low.error)
    with pytest.raises(DeadlineExceeded):
        low.result()
    gw._drive_until([high], timeout=10)
    assert len(high.tokens) == 30


def test_tenant_quota_defers_never_drops():
    """An over-quota tenant's request WAITS for the bucket to refill —
    it is never dropped — while unmetered tenants flow past it."""
    gw = _stub_gateway(tenants={"q": {"rate": 40.0, "burst": 8.0}})
    r1 = gw.submit("m", _prompt(4), 4, tenant="q")     # est cost 8
    r2 = gw.submit("m", _prompt(4), 4, tenant="q")     # bucket empty
    free = gw.submit("m", _prompt(5), 2, tenant="free")
    gw.step()
    assert r1.state == "dispatched"
    assert free.state != "queued"              # unmetered: not throttled
    assert r2.state == "queued"                # deferred, not dropped
    while not r1.done:
        gw.step()
    assert r2.state == "queued"                # still waiting on refill
    time.sleep(0.25)                           # 40 tok/s × 0.25 ≥ 8
    gw.step()
    assert r2.state == "dispatched"
    gw._drive_until([r2, free], timeout=10)
    assert r2.result() == [4, 5, 6, 7]


def test_gateway_queue_depth_pull_gauge():
    gw = _stub_gateway()
    hs = [gw.submit("m", _prompt(4), 1, priority="high"),
          gw.submit("m", _prompt(5), 1, priority="high"),
          gw.submit("m", _prompt(6), 1, priority="low")]
    rep = registry.report()
    assert rep['mx_gateway_queue_depth{priority="high"}']["value"] == 2.0
    assert rep['mx_gateway_queue_depth{priority="normal"}']["value"] == 0.0
    assert rep['mx_gateway_queue_depth{priority="low"}']["value"] == 1.0
    gw._drive_until(hs, timeout=10)
    rep = registry.report()
    assert rep['mx_gateway_queue_depth{priority="high"}']["value"] == 0.0


def test_gateway_step_fault_seam():
    from incubator_mxnet_tpu import fault

    gw = _stub_gateway()
    gw.submit("m", _prompt(4), 2)
    fault.configure_injection("gateway_step:1.0:0:1")
    try:
        with pytest.raises(fault.FaultInjected):
            gw.step()
    finally:
        fault.clear_injection()
    gw.step()                                  # limit=1: next step clean


def test_gateway_shutdown_drains_and_fails_queued():
    gw = _stub_gateway(max_slots=1)
    a = gw.submit("m", _prompt(4), 3)
    gw.step()
    b = gw.submit("m", _prompt(5), 3)          # still gateway-queued
    gw.shutdown(drain=True, timeout=10)
    assert a.state == "done" and a.result() == [4, 5, 6]
    assert b.state == "failed" and isinstance(b.error, EngineClosed)
    with pytest.raises(EngineClosed):
        gw.submit("m", _prompt(4), 2)
    # every page returned (prefix cache cleared at shutdown)
    assert gw._models["m"].slots.allocator.used_pages == 0


# ---------------------------------------------------------------------------
# observability: spans + flight recorder (quick)
# ---------------------------------------------------------------------------

@pytest.fixture()
def traced():
    tracing.enable()
    yield
    tracing.disable()
    tracing.reset()


def test_gateway_spans_join_request_trace(traced):
    """gateway.request → gateway.admit → serve.request are ONE trace per
    request: the engine segment's root span parents on the gateway's."""
    gw = _stub_gateway()
    h = gw.submit("m", _prompt(4), 2, tenant="acme", priority="high")
    gw._drive_until([h], timeout=10)
    spans = tracing.finished_spans(h.trace_id)
    names = [s.name for s in spans]
    assert {"gateway.request", "gateway.admit",
            "serve.request"} <= set(names)
    groot = next(s for s in spans if s.name == "gateway.request")
    sreq = next(s for s in spans if s.name == "serve.request")
    assert sreq.trace_id == groot.trace_id == h.trace_id
    assert groot.attrs["tenant"] == "acme"
    assert groot.attrs["priority"] == "high"
    assert groot.attrs["preemptions"] == 0


def test_gateway_preempted_trace_has_two_segments(traced):
    gw = _stub_gateway(max_slots=1)
    low = gw.submit("m", _prompt(4), 4, priority="low")
    gw.step()
    high = gw.submit("m", _prompt(6, seed=1), 2, priority="high")
    gw._drive_until([low, high], timeout=10)
    spans = tracing.finished_spans(low.trace_id)
    names = [s.name for s in spans]
    # two admits and two engine segments — the preemption is visible
    # in the request's own trace
    assert names.count("gateway.admit") == 2
    assert names.count("serve.request") == 2
    groot = next(s for s in spans if s.name == "gateway.request")
    assert groot.attrs["preemptions"] == 1


def test_flight_dump_carries_gateway_context(traced, tmp_path):
    gw = _stub_gateway()
    gw.submit("m", _prompt(4), 2, tenant="acme", priority="high")
    path = tracing.flight_dump("gwtest", path=str(tmp_path / "f.json"))
    with open(path) as f:
        payload = json.load(f)
    ctx = payload["context"]["gateway"]
    assert ctx["tiers"] == {"high": 1, "normal": 0, "low": 0}
    assert ctx["queued"][0]["tenant"] == "acme"
    assert ctx["queued"][0]["priority"] == "high"
    assert ctx["closed"] is False and ctx["preemptions_total"] == 0


# ---------------------------------------------------------------------------
# trace-replay acceptance gate on real compiled engines (ISSUE 9)
# ---------------------------------------------------------------------------

def _loadgen():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)
    return loadgen


def _spicy_net(weight_seed):
    """Non-degenerate random weights, same recipe as test_serve.py."""
    mx.random.seed(11)
    m = gpt_tiny(vocab_size=VOCAB, max_length=64, dropout=0.0)
    m.initialize()
    r = onp.random.RandomState(weight_seed)
    for _name, p in m.collect_params().items():
        if p.shape and len(p.shape) >= 2:
            p.set_data(np.array(
                r.normal(0, 0.35, p.shape).astype("float32")))
    return m


def test_gateway_trace_replay_acceptance(tmp_path):
    """THE acceptance gate: two co-resident tiny GPTs behind one
    gateway, three tenants across three tiers on a recorded trace.
    Every request completes or fails loudly; the high tier's TTFT p99
    under contention stays within 1.5× its solo value; a deterministic
    contention episode preempts low-priority work that then FINISHES;
    per-engine program counts never move after warmup; and the high
    tier's `slo.gateway_ttft` error budget is compliant."""
    loadgen = _loadgen()
    reg = serve.ModelRegistry(total_pages=40)
    reg.add("gpt-a", _spicy_net(42), share=2.0, max_slots=2, max_len=64)
    reg.add("gpt-b", _spicy_net(43), share=1.0, max_slots=2, max_len=64)
    gw = serve.Gateway(reg, tenants={"acme": {"weight": 3.0},
                                     "beta": {"weight": 2.0},
                                     "crawl": {"weight": 1.0}})
    obj = slo.gateway_ttft("high", threshold_s=2.5, target=0.9,
                           name="gw_accept_high")
    try:
        # the shared page budget splits by share (2:1)
        assert (gw._models["gpt-a"].slots.allocator.usable_pages >
                gw._models["gpt-b"].slots.allocator.usable_pages)
        # warm every chunk bucket (16/32/64) + decode on both engines,
        # out of the measured window
        for name in ("gpt-a", "gpt-b"):
            for n in (5, 20, 40):
                gw.generate(name, _prompt(n, seed=n), 2)
        warm = gw.xla_program_counts()
        assert all(c >= 2 for c in warm.values())

        # solo baseline: the high tenant alone
        solo = loadgen.synth_trace(
            8, models={"gpt-a": 2.0, "gpt-b": 1.0},
            tenants={"acme": (1.0, "high")}, seed=5, duration_s=0.4,
            prompt_max=40, max_new_range=(3, 8))
        solo_rep = loadgen.replay(gw, solo, VOCAB, timeout=120.0)
        assert not solo_rep["failed"]
        assert solo_rep["completed"] == len(solo)
        solo_p99 = loadgen.percentile(
            solo_rep["per_tier"]["high"]["ttft"], 99)

        # contended run: 3 tenants / 3 tiers, bursty arrivals, via a
        # save/load JSONL roundtrip (the recorded-trace contract)
        events = loadgen.synth_trace(
            24, models={"gpt-a": 2.0, "gpt-b": 1.0},
            tenants={"acme": (1.5, "high"), "beta": (1.5, "normal"),
                     "crawl": (3.0, "low")},
            seed=7, duration_s=0.6, burst_factor=8.0, prompt_max=40,
            max_new_range=(3, 8))
        events = loadgen.load_trace(loadgen.save_trace(
            str(tmp_path / "trace.jsonl"), events))
        rep = loadgen.replay(gw, events, VOCAB, timeout=180.0)
        assert not rep["failed"], rep["failed"]
        assert rep["completed"] == len(events)
        hi_p99 = loadgen.percentile(rep["per_tier"]["high"]["ttft"], 99)
        assert hi_p99 <= 1.5 * solo_p99 + 0.1, (hi_p99, solo_p99)

        # deterministic contention: fill gpt-a's two slots with low-tier
        # work, then land a high request — a low MUST be preempted, keep
        # its pages/tokens, and still FINISH its full budget
        pre0 = gw.preemptions_total
        lows = [gw.submit("gpt-a", _prompt(6, seed=70 + i), 20,
                          tenant="crawl", priority="low")
                for i in range(2)]
        while not all(r.tokens for r in lows):
            gw.step()
        high = gw.submit("gpt-a", _prompt(8, seed=99), 4, tenant="acme",
                         priority="high")
        gw.step()
        assert gw.preemptions_total == pre0 + 1
        gw._drive_until(lows + [high], timeout=120.0)
        assert high.state == "done" and len(high.tokens) == 4
        assert [r for r in lows if r.preemptions]
        for r in lows:
            assert r.state == "done" and len(r.tokens) == 20

        # zero steady-state recompiles across replays AND preemption
        assert gw.xla_program_counts() == warm
        # the high tier's error budget survived the whole session
        res = obj.evaluate()
        assert res["compliance"] is not None and res["ok"], res
    finally:
        slo.tracker().remove("gw_accept_high")
        gw.shutdown(drain=False)
