"""Operator depth sweeps (VERDICT r2 item 6; reference model:
`tests/python/unittest/test_numpy_op.py` + `test_operator.py` — dtype
sweeps, broadcasting edge shapes, degenerate/empty inputs, and
finite-difference gradient checks via `test_utils.check_numeric_gradient`
(reference `python/mxnet/test_utils.py:1044`))."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu import npx
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.test_utils import check_numeric_gradient

RS = onp.random.RandomState(7)


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def _golden(name, *args):
    f = getattr(onp, name)
    return f(*[a.astype(onp.float64) for a in args])


# ---------------------------------------------------------------------------
# low-precision dtype sweeps: bf16 has ~8 mantissa bits, f16 ~11 — XLA
# numerics genuinely diverge from f32 here, which the f32-only sweep in
# test_numpy_sweep.py cannot see
# ---------------------------------------------------------------------------

LOWP_UNARY = [
    "negative", "abs", "sign", "floor", "ceil", "trunc", "sqrt", "square",
    "exp", "log", "log1p", "sin", "cos", "tanh", "arctan", "sinh", "cosh",
    "arcsinh", "reciprocal", "cbrt", "expm1", "log2", "log10", "rint",
    "degrees", "radians",
]
_TOL = {"bfloat16": (4e-2, 4e-2), "float16": (4e-3, 4e-3)}


@pytest.mark.parametrize("dtype", ["bfloat16", "float16"])
@pytest.mark.parametrize("name", LOWP_UNARY)
def test_unary_low_precision(name, dtype):
    x = RS.uniform(0.3, 1.7, (4, 8)).astype(onp.float32)
    ref = _golden(name, x)
    out = getattr(mnp, name)(mnp.array(x).astype(dtype))
    assert onp.dtype(out.dtype) == onp.dtype(dtype)
    rtol, atol = _TOL[dtype]
    onp.testing.assert_allclose(A(out).astype(onp.float64), ref,
                                rtol=rtol, atol=atol)


LOWP_BINARY = ["add", "subtract", "multiply", "divide", "maximum",
               "minimum", "power", "hypot", "arctan2", "logaddexp"]


@pytest.mark.parametrize("dtype", ["bfloat16", "float16"])
@pytest.mark.parametrize("name", LOWP_BINARY)
def test_binary_low_precision(name, dtype):
    x = RS.uniform(0.3, 1.7, (4, 8)).astype(onp.float32)
    y = RS.uniform(0.3, 1.7, (4, 8)).astype(onp.float32)
    ref = _golden(name, x, y)
    out = getattr(mnp, name)(mnp.array(x).astype(dtype),
                             mnp.array(y).astype(dtype))
    rtol, atol = _TOL[dtype]
    onp.testing.assert_allclose(A(out).astype(onp.float64), ref,
                                rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# broadcasting / degenerate shapes
# ---------------------------------------------------------------------------

BCAST_PAIRS = [
    ((4, 5), (5,)),
    ((4, 1), (1, 5)),
    ((1,), (4, 5)),
    ((3, 1, 5), (1, 4, 1)),
    ((2, 3, 4, 5), (5,)),
    ((0, 5), (5,)),        # zero-size leading dim
    ((4, 5), ()),          # scalar operand
]
BCAST_OPS = ["add", "subtract", "multiply", "divide", "maximum", "minimum",
             "power", "arctan2"]


@pytest.mark.parametrize("shapes", BCAST_PAIRS,
                         ids=[f"{a}x{b}" for a, b in BCAST_PAIRS])
@pytest.mark.parametrize("name", BCAST_OPS)
def test_binary_broadcasting(name, shapes):
    sa, sb = shapes
    x = RS.uniform(0.3, 1.7, sa).astype(onp.float32)
    y = RS.uniform(0.3, 1.7, sb).astype(onp.float32)
    ref = _golden(name, x, y)
    out = getattr(mnp, name)(mnp.array(x), mnp.array(y))
    assert out.shape == ref.shape
    onp.testing.assert_allclose(A(out).astype(onp.float64), ref,
                                rtol=2e-5, atol=1e-6)


REDUCTIONS = ["sum", "mean", "prod", "max", "min", "var", "std"]
RED_CASES = [
    ((4, 5), None, False),
    ((4, 5), 0, False),
    ((4, 5), 1, True),
    ((3, 4, 5), (0, 2), False),
    ((4, 0, 5), 1, False),     # reduce over an EMPTY axis
    ((1,), 0, False),
]


@pytest.mark.parametrize("case", RED_CASES,
                         ids=[f"{s}-ax{a}-k{k}" for s, a, k in RED_CASES])
@pytest.mark.parametrize("name", REDUCTIONS)
def test_reductions_shapes(name, case):
    shape, axis, keepdims = case
    if 0 in shape and name in ("max", "min"):
        pytest.skip("max/min of empty slice is undefined (numpy raises)")
    x = RS.uniform(0.5, 1.5, shape).astype(onp.float32)
    ref = getattr(onp, name)(x.astype(onp.float64), axis=axis,
                             keepdims=keepdims)
    out = getattr(mnp, name)(mnp.array(x), axis=axis, keepdims=keepdims)
    assert tuple(out.shape) == tuple(onp.shape(ref))
    onp.testing.assert_allclose(A(out).astype(onp.float64), ref,
                                rtol=3e-5, atol=1e-6)


@pytest.mark.parametrize("name", ["sum", "mean", "max"])
def test_reductions_large(name):
    """1M-element reduce: accumulation-order numerics at scale."""
    x = RS.uniform(-1, 1, (1024, 1024)).astype(onp.float32)
    ref = getattr(onp, name)(x.astype(onp.float64))
    out = getattr(mnp, name)(mnp.array(x))
    onp.testing.assert_allclose(float(A(out)), ref, rtol=1e-4)


# ---------------------------------------------------------------------------
# finite-difference gradient checks over the npx nn corpus
# ---------------------------------------------------------------------------

def _stable_seed(tag, s):
    # zlib.crc32, NOT hash(): python string hashing is salted per process,
    # so hash-derived seeds silently vary between runs — max-pool FD checks
    # then hit near-ties in some runs only (caught as a once-in-a-suite
    # flake in round 4)
    import zlib

    return zlib.crc32(repr((tag,) + tuple(s)).encode()) % (2 ** 31)


def _u(*s):
    # order-independent inputs: seeded per shape, not from the shared
    # module stream (tests must not change behavior with execution order)
    r = onp.random.RandomState(_stable_seed("u", s))
    return NDArray(r.uniform(-0.9, 0.9, s).astype("float32"))


def _up(*s):
    r = onp.random.RandomState(_stable_seed("up", s))
    return NDArray(r.uniform(0.3, 1.5, s).astype("float32"))


_W34 = NDArray(onp.random.RandomState(11)
               .uniform(0.5, 2.0, (3, 4)).astype("float32"))


def _cng(fn, inputs, **kw):
    """check_numeric_gradient with f32-appropriate finite-difference
    settings: losses evaluate in float32, so at eps=1e-3 the central
    difference resolves only ~2e-4 absolute (ulp(loss)/2eps) and marginal
    comparisons flip with XLA accumulation order. eps=5e-3 balances the
    rounding term (ulp/eps ≈ 5e-5) against the truncation term
    (f'''·eps²/6 ≈ 1e-5) for O(1)-smooth ops."""
    kw.setdefault("eps", 5e-3)
    kw.setdefault("rtol", 2e-2)
    kw.setdefault("atol", 5e-4)
    return check_numeric_gradient(fn, inputs, **kw)


GRAD_UNARY = [
    ("relu_shifted", lambda x: npx.relu(x + 1.3)),  # keep off the kink
    ("sigmoid", npx.sigmoid),
    ("tanh_act", lambda x: npx.activation(x, act_type="tanh")),
    ("softrelu", lambda x: npx.activation(x, act_type="softrelu")),
    ("softsign", lambda x: npx.activation(x, act_type="softsign")),
    ("gelu", npx.gelu),
    # softmax-family outputs sum to one per row, so a plain .sum() loss has
    # an identically-zero gradient — weight the outputs to break the
    # degeneracy (same trick as the reference's softmax grad tests)
    ("softmax", lambda x: npx.softmax(x, axis=-1) * _W34),
    ("log_softmax", lambda x: npx.log_softmax(x, axis=-1) * _W34),
    ("softmin", lambda x: npx.softmax(-x, axis=-1) * _W34),
    ("l2_normalization", npx.l2_normalization),
    ("smooth_l1", npx.smooth_l1),
    ("erf", npx.erf),
]


@pytest.mark.parametrize("case", GRAD_UNARY, ids=[c[0] for c in GRAD_UNARY])
def test_numeric_grad_unary(case):
    _name, fn = case
    _cng(fn, [_u(3, 4)])


def test_numeric_grad_leaky_relu_modes():
    _cng(
        lambda x: npx.leaky_relu(x + 1.1, act_type="leaky", slope=0.3),
        [_u(3, 4)])
    _cng(
        lambda x: npx.leaky_relu(x + 1.1, act_type="elu", slope=0.4),
        [_u(3, 4)])


def test_numeric_grad_fully_connected():
    _cng(
        lambda x, w, b: npx.fully_connected(x, w, b, num_hidden=4),
        [_u(2, 6), _u(4, 6), _u(4)])


def test_numeric_grad_layer_norm():
    _cng(
        lambda x, g, b: npx.layer_norm(x, g, b, axis=-1),
        [_u(3, 6), _up(6), _u(6)])


def test_numeric_grad_group_norm():
    # gamma/beta are per-CHANNEL (C=4), as in the reference GroupNorm
    _cng(
        lambda x, g, b: npx.group_norm(x, g, b, num_groups=2),
        [_u(2, 4, 3), _up(4), _u(4)])


def test_numeric_grad_batch_norm_inference():
    mean, var = _u(3), _up(3)
    _cng(
        lambda x, g, b: npx.batch_norm(x, g, b, mean, var,
                                       use_global_stats=True),
        [_u(2, 3, 4), _up(3), _u(3)])


def test_numeric_grad_convolution_2d():
    _cng(
        lambda x, w, b: npx.convolution(x, w, b, kernel=(3, 3),
                                        num_filter=2, pad=(1, 1)),
        [_u(1, 2, 4, 4), _u(2, 2, 3, 3), _u(2)])


def test_numeric_grad_convolution_1d():
    _cng(
        lambda x, w, b: npx.convolution(x, w, b, kernel=(3,), num_filter=2),
        [_u(1, 2, 6), _u(2, 2, 3), _u(2)])


def test_numeric_grad_pooling():
    _cng(
        lambda x: npx.pooling(x, kernel=(2, 2), pool_type="avg",
                              stride=(2, 2)),
        [_u(1, 2, 4, 4)])
    # max pooling: gradient defined a.e.; inputs drawn continuous so ties
    # have probability ~0
    _cng(
        lambda x: npx.pooling(x, kernel=(2, 2), pool_type="max",
                              stride=(2, 2)),
        [_u(1, 2, 4, 4)])


def test_numeric_grad_batch_dot():
    _cng(
        lambda a, b: npx.batch_dot(a, b),
        [_u(2, 3, 4), _u(2, 4, 2)])
    _cng(
        lambda a, b: npx.batch_dot(a, b, transpose_b=True),
        [_u(2, 3, 4), _u(2, 2, 4)])


def test_numeric_grad_embedding():
    idx = NDArray(onp.array([[0, 2], [1, 0]], onp.int32))
    _cng(
        lambda w: npx.embedding(idx, w, input_dim=3, output_dim=4),
        [_u(3, 4)])


def test_numeric_grad_sequence_mask():
    lens = NDArray(onp.array([1, 2], onp.int32))
    _cng(
        lambda x: npx.sequence_mask(x, lens, use_sequence_length=True),
        [_u(3, 2, 4)])


def test_numeric_grad_roi_align():
    rois = NDArray(onp.array([[0, 0.5, 0.5, 2.5, 2.5]], onp.float32))
    _cng(
        lambda x: npx.roi_align(x, rois, pooled_size=(2, 2),
                                spatial_scale=1.0),
        [_up(1, 2, 4, 4)])


def test_numeric_grad_bilinear_sampler():
    grid = NDArray(RS.uniform(-0.6, 0.6, (1, 2, 3, 3)).astype("float32"))
    # sampler grads are sums of small f32 interpolation weights; widen eps
    # and atol to clear central-difference rounding noise
    _cng(
        lambda x: npx.bilinear_sampler(x, grid),
        [_up(1, 2, 4, 4)], atol=2e-3)


def test_numeric_grad_grid_generator():
    affine = NDArray(onp.array([[1.0, 0.1, 0.0, 0.1, 1.0, 0.0]],
                               onp.float32))
    out = npx.grid_generator(affine, transform_type="affine",
                             target_shape=(3, 3))
    assert out.shape == (1, 2, 3, 3)
    _cng(
        lambda a: npx.grid_generator(a, transform_type="affine",
                                     target_shape=(3, 3)),
        [NDArray(onp.array([[1.0, 0.1, 0.0, 0.1, 1.0, 0.0]], onp.float32))])


# ---------------------------------------------------------------------------
# fft / ifft value checks vs numpy
# ---------------------------------------------------------------------------

def test_fft_matches_numpy():
    x = RS.uniform(-1, 1, (3, 8)).astype(onp.float32)
    out = npx.fft(NDArray(x))
    ref = onp.fft.fft(x)
    got = A(out)
    # reference layout: interleaved real/imag pairs along the last axis
    onp.testing.assert_allclose(got[..., 0::2], ref.real, rtol=1e-4,
                                atol=1e-4)
    onp.testing.assert_allclose(got[..., 1::2], ref.imag, rtol=1e-4,
                                atol=1e-4)


def test_ifft_roundtrip():
    x = RS.uniform(-1, 1, (2, 8)).astype(onp.float32)
    freq = npx.fft(NDArray(x))
    back = npx.ifft(freq)
    onp.testing.assert_allclose(A(back)[:, :8] / 8.0, x, rtol=1e-4,
                                atol=1e-4)


# ---------------------------------------------------------------------------
# integer / bool dtype coverage for elementwise ops
# ---------------------------------------------------------------------------

INT_UNARY = ["negative", "abs", "sign", "square"]


# int64 omitted: the framework inherits jax's x64-disabled default
@pytest.mark.parametrize("dtype", ["int32", "int8"])
@pytest.mark.parametrize("name", INT_UNARY)
def test_unary_integer_dtypes(name, dtype):
    x = RS.randint(-5, 6, (4, 5)).astype(dtype)
    ref = getattr(onp, name)(x)
    out = getattr(mnp, name)(mnp.array(x))
    assert onp.dtype(out.dtype) == onp.dtype(dtype)
    onp.testing.assert_array_equal(A(out), ref)


@pytest.mark.parametrize("name", ["logical_and", "logical_or",
                                  "logical_xor"])
def test_binary_bool(name):
    a = RS.rand(4, 5) > 0.5
    b = RS.rand(4, 5) > 0.5
    ref = getattr(onp, name)(a, b)
    out = getattr(mnp, name)(mnp.array(a), mnp.array(b))
    onp.testing.assert_array_equal(A(out), ref)


# ---------------------------------------------------------------------------
# empty / singleton edge cases through common ops
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ["exp", "tanh", "abs", "sqrt"])
def test_unary_empty_input(name):
    x = onp.zeros((0, 4), onp.float32)
    out = getattr(mnp, name)(mnp.array(x))
    assert out.shape == (0, 4)


def test_concat_empty_with_nonempty():
    a = mnp.array(onp.zeros((0, 3), onp.float32))
    b = mnp.array(onp.ones((2, 3), onp.float32))
    out = mnp.concatenate([a, b], axis=0)
    assert out.shape == (2, 3)


def test_matmul_degenerate_dims():
    a = mnp.array(onp.ones((3, 0), onp.float32))
    b = mnp.array(onp.ones((0, 4), onp.float32))
    out = mnp.dot(a, b)
    assert out.shape == (3, 4)
    onp.testing.assert_array_equal(A(out), onp.zeros((3, 4)))


def test_softmax_single_element_axis():
    x = mnp.array(RS.uniform(-1, 1, (4, 1)).astype("float32"))
    out = npx.softmax(x, axis=-1)
    onp.testing.assert_allclose(A(out), onp.ones((4, 1)), rtol=1e-6)
