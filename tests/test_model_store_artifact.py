"""Packaged trained artifact: the committed `mobilenet0.25_digits` weights
(model_zoo/_store) are the cross-version load-compatibility anchor
(reference: `model_store.py` pretrained downloads +
`tests/nightly/model_backwards_compatibility_check/` — here the artifact
ships IN the package because this build has no egress). If a future
change to Parameter/serialization breaks loading old checkpoints, this
test catches it."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.gluon.model_zoo import model_store
from incubator_mxnet_tpu.gluon.model_zoo.vision import mobilenet0_25


def _digits_test_split():
    pytest.importorskip("sklearn.datasets")
    # IMPORT the training tool's split so test and training can never
    # drift apart (a diverging copy would silently evaluate artifacts on
    # their own training data)
    import importlib.util
    import os

    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "train_store_artifacts.py")
    spec = importlib.util.spec_from_file_location("_train_artifacts", tool)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    (_, _), (Xte, Yte) = mod._digits()
    return Xte, Yte


def test_packaged_artifact_resolves_and_verifies():
    path = model_store.get_model_file("mobilenet0.25_digits")
    assert path.endswith(".params")
    assert model_store.short_hash("mobilenet0.25_digits")  # sha registered


def test_packaged_artifact_loads_and_classifies():
    """Load the committed checkpoint into a freshly-built architecture and
    reproduce its held-out accuracy — pins (a) the .params format across
    versions and (b) that the model_zoo architecture still matches the
    trained weights."""
    Xte, Yte = _digits_test_split()
    net = mobilenet0_25(classes=10)
    net.load_parameters(model_store.get_model_file("mobilenet0.25_digits"))
    pred = onp.argmax(net(np.array(Xte)).asnumpy(), axis=1)
    acc = float((pred == Yte).mean())
    assert acc >= 0.90, acc


def test_checksum_mismatch_detected(tmp_path):
    """A corrupted store file must be rejected, not silently loaded."""
    src = model_store.get_model_file("mobilenet0.25_digits")
    import os
    import shutil

    root = str(tmp_path)
    name = os.path.basename(src)
    shutil.copy(src, os.path.join(root, name))
    sha = model_store._sha1(src)  # noqa: SLF001
    model_store.register_sha1("mobilenet0.25_digits", sha, root=root)
    with open(os.path.join(root, name), "r+b") as f:
        f.seek(100)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(ValueError, match="checksum"):
        model_store.get_model_file("mobilenet0.25_digits", root=root)

def test_mobilenetv2_artifact_loads_and_classifies():
    """Second vision artifact (mobilenetv2_0.25_digits): loads from the
    packaged store and classifies the held-out digits split well above
    chance (training: tools/train_store_artifacts.py)."""
    from incubator_mxnet_tpu.gluon.model_zoo.vision import mobilenet_v2_0_25

    Xte, Yte = _digits_test_split()
    net = mobilenet_v2_0_25(classes=10)
    net.load_parameters(model_store.get_model_file("mobilenetv2_0.25_digits"))
    pred = onp.argmax(net(np.array(Xte[:120])).asnumpy(), axis=1)
    acc = (pred == Yte[:120]).mean()
    assert acc >= 0.9, acc


def test_charlm_artifact_loads_rnn_family():
    """RNN-family artifact (lstm_charlm_tiny): embed + LSTM + dense head
    round-trip through the store registry (serde breadth beyond CNNs)."""
    from incubator_mxnet_tpu import gluon, np as mxnp

    class CharLM(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.embed = gluon.nn.Embedding(28, 32)
            self.lstm = gluon.rnn.LSTM(64, num_layers=1, layout="NTC")
            self.head = gluon.nn.Dense(28, flatten=False)

        def forward(self, x):
            return self.head(self.lstm(self.embed(x)))

    net = CharLM()
    net.initialize()
    net(mxnp.array(onp.zeros((1, 8), "int32")))
    net.load_parameters(model_store.get_model_file("lstm_charlm_tiny"))
    out = net(mxnp.array(onp.zeros((2, 16), "int32")))
    assert out.shape == (2, 16, 28)
    assert onp.isfinite(out.asnumpy()).all()
