"""Span tracing + flight recorder + SLO tracker (ISSUE 5).

Layers, cheapest first:

- tracer mechanics: nesting/IDs (incl. across threads), explicit
  open_span lifecycle, ring bounds, chrome export clock base — all pure
  host, `quick`-marked;
- off-path contract: MXNET_TELEMETRY unset ⇒ every probe is one enabled
  check, measured <3% of a funnel op, zero spans recorded;
- serve request traces against the stub scheduler (quick) AND the real
  compiled engine, where the zero-steady-state-recompile gate
  (`xla_program_count`) must hold WITH tracing enabled;
- flight recorder: an injected `serve_step` fault leaves a dump holding
  the active request's spans; `estimator_step` crash-resume dumps too;
- SLO burn math + the loud health-monitor hook;
- training lifecycle spans: estimator epoch/step, dataloader batch,
  kvstore push/pull/barrier, checkpoint write/resume.
"""
import json
import os
import threading
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.telemetry import monitor, registry, slo, tracing

VOCAB = 97


@pytest.fixture(autouse=True)
def _clean_tracing():
    yield
    tracing.disable()
    tracing.reset()
    slo.tracker().clear()
    monitor.remove_health_check("slo")


def _span_names(trace_id=None):
    return [s.name for s in tracing.finished_spans(trace_id)]


# ---------------------------------------------------------------------------
# tracer mechanics
# ---------------------------------------------------------------------------

def test_span_nesting_and_ids():
    tracing.enable()
    with tracing.span("outer", kind="t") as outer:
        assert tracing.current_span() is outer
        assert tracing.current_trace_id() == outer.trace_id
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            assert inner.span_id != outer.span_id
            inner.event("mark", n=1)
    assert tracing.current_span() is None
    spans = tracing.finished_spans(outer.trace_id)
    assert [s.name for s in spans] == ["outer", "inner"]  # start-ordered
    assert all(s.dur_ns is not None and s.dur_ns >= 0 for s in spans)
    assert spans[1].events and spans[1].events[0][0] == "mark"
    # sibling traces do not share ids
    with tracing.span("other") as other:
        pass
    assert other.trace_id != outer.trace_id


def test_spans_across_threads_join_one_trace():
    """The serve pattern: a root opened on one thread, children created
    on another via explicit parent= — one trace, distinct span ids."""
    tracing.enable()
    root = tracing.open_span("request", lane="req 0")

    def worker():
        with tracing.span("work", parent=root):
            pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    root.close()
    spans = tracing.finished_spans(root.trace_id)
    assert len(spans) == 5                       # root + 4 workers
    kids = [s for s in spans if s.name == "work"]
    assert all(s.parent_id == root.span_id for s in kids)
    assert len({s.span_id for s in spans}) == 5  # ids unique
    assert all(s.lane == "req 0" for s in kids)  # lane inherits


def test_open_span_explicit_lifecycle_and_ring_bound():
    tracing.enable()
    s = tracing.open_span("explicit")
    assert s in tracing.open_spans()
    assert tracing.current_span() is None        # never ambient
    s.close()
    s.close()                                    # idempotent
    assert s not in tracing.open_spans()
    # ring stays bounded
    for i in range(tracing.RING_CAPACITY + 50):
        with tracing.span("burst"):
            pass
    mine = [x for x in tracing.finished_spans() if x.name == "burst"]
    assert len(mine) <= tracing.RING_CAPACITY


def test_error_annotation_on_exception():
    tracing.enable()
    with pytest.raises(ValueError):
        with tracing.span("boom") as s:
            raise ValueError("kaput")
    assert s.attrs["error"] == "ValueError"
    assert "kaput" in s.attrs["error_msg"]


def test_chrome_export_lanes_and_clock_base():
    tracing.enable()
    t_before = time.time() * 1e6
    with tracing.span("laned", lane="req 7", foo="bar"):
        tracing.event("tick")
    ev = tracing.chrome_events()
    xs = [e for e in ev if e["ph"] == "X"]
    assert len(xs) == 1 and xs[0]["name"] == "laned"
    assert xs[0]["args"]["foo"] == "bar"
    # epoch-µs clock base — the same base profiler rebases device events
    # onto, so the merged timeline lines up
    assert t_before <= xs[0]["ts"] <= time.time() * 1e6
    names = [e for e in ev if e["ph"] == "M" and e["name"] == "thread_name"]
    assert any(m["args"]["name"] == "req 7" for m in names)
    assert any(e["ph"] == "i" and e["name"] == "tick" for e in ev)
    payload = tracing.chrome_trace(include_device=True)
    assert {e["name"] for e in payload["traceEvents"]} >= {"laned", "tick"}


def test_committed_timeline_example_loads_and_shares_clock():
    """The acceptance artifact: benchmark/trace_timeline_example.json
    holds host request spans AND XLA device slices on one clock base."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark",
        "trace_timeline_example.json")
    with open(path) as f:
        payload = json.load(f)
    ev = payload["traceEvents"]
    spans = [e for e in ev if e.get("pid") == 2 and e.get("ph") == "X"]
    device = [e for e in ev if e.get("pid", 0) >= 1000
              and e.get("ph") == "X"]
    assert any(e["name"] == "serve.request" for e in spans)
    assert any(e["name"] == "serve.prefill" for e in spans)
    assert device, "no device slices in the committed example"
    lo = min(e["ts"] for e in spans)
    hi = max(e["ts"] + e.get("dur", 0) for e in spans)
    overlapping = [e for e in device if lo <= e["ts"] <= hi]
    # shared clock base: the device slices sit under the request spans
    assert len(overlapping) > 100, (len(overlapping), len(device))


# ---------------------------------------------------------------------------
# off-path contract (<3% of a funnel op with MXNET_TELEMETRY unset)
# ---------------------------------------------------------------------------

def test_off_path_records_nothing_and_is_cheap():
    assert not tracing.is_enabled()
    with tracing.span("ghost", attr=1) as s:
        tracing.event("ghost-event")
        tracing.annotate(x=2)
    assert not s                                  # the shared null span
    assert tracing.finished_spans() == []

    a = np.array(onp.random.RandomState(0).uniform(-1, 1, (16, 16))
                 .astype("float32"))
    np.dot(a, a).wait_to_read()                   # warm the jit cache
    iters = 300
    t0 = time.perf_counter()
    for _ in range(iters):
        np.dot(a, a)
    mx.waitall()
    per_op = (time.perf_counter() - t0) / iters
    # the literal instrumented-site pattern, disabled
    t0 = time.perf_counter()
    for i in range(iters):
        with tracing.span("estimator.step", batch=i):
            pass
    probe = (time.perf_counter() - t0) / iters
    assert probe < 0.03 * per_op, (probe, per_op)


# ---------------------------------------------------------------------------
# serve request traces — stub scheduler (quick) + real compiled engine
# ---------------------------------------------------------------------------

class _StubSlots:
    """Paged-interface stub: pure host arithmetic over a REAL page
    allocator + prefix cache (host-only classes); the final prefill
    chunk emits the prompt's length as the first token, decode
    increments."""

    max_slots, max_len = 2, 64
    page_tokens, prefill_chunk = 16, 64

    def __init__(self):
        from incubator_mxnet_tpu import serve

        pages_per_slot = -(-self.max_len // self.page_tokens)
        self.allocator = serve.PageAllocator(
            self.max_slots * pages_per_slot + 1, self.page_tokens)
        self.prefix_cache = serve.PrefixCache(self.allocator)

    def set_slot_pages(self, slot, pages):
        pass

    def clear_slot(self, slot):
        pass

    def prefill_chunk_step(self, slot, chunk_tokens, t_start, key,
                           temperature=1.0):
        n = len(chunk_tokens)
        return int(t_start) + n, n, 0

    def decode_step(self, last, pos, active, key, temps):
        return onp.where(active, last + 1, last).astype(onp.int32)

    def xla_program_count(self):
        return 0

    def release(self):
        pass


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


def test_serve_request_trace_stub():
    """One trace per request with the full lifecycle — no XLA, quick."""
    from incubator_mxnet_tpu.serve.scheduler import Scheduler

    tracing.enable()
    sched = Scheduler(_StubSlots(), max_queue=16)
    reqs = [sched.submit(_prompt(4 + i, seed=i), 3) for i in range(5)]
    while not all(r.done for r in reqs):
        sched.step()
    for r in reqs:
        assert r.trace_id is not None
        names = sorted(_span_names(r.trace_id))
        assert names == ["serve.decode", "serve.prefill", "serve.queue",
                         "serve.request"], names
        root = [s for s in tracing.finished_spans(r.trace_id)
                if s.name == "serve.request"][0]
        assert root.attrs["tokens"] == 3
        assert root.attrs["reason"] == "length"
        assert root.lane == f"req {r.id}"
    # traces are distinct per request
    assert len({r.trace_id for r in reqs}) == len(reqs)
    # engine-level spans exist alongside
    assert "serve.step" in _span_names()
    assert "serve.decode_step" in _span_names()


def test_serve_trace_deadline_failure_annotated():
    from incubator_mxnet_tpu.serve.scheduler import (DeadlineExceeded,
                                                     Scheduler)

    tracing.enable()
    sched = Scheduler(_StubSlots(), max_queue=8)
    req = sched.submit(_prompt(4), 4, deadline_s=0.0)
    time.sleep(0.005)
    sched.step()
    assert req.state == "failed"
    root = [s for s in tracing.finished_spans(req.trace_id)
            if s.name == "serve.request"][0]
    assert root.attrs["error"] == DeadlineExceeded.__name__
    # never admitted: queue span closed, no prefill/decode segments
    names = _span_names(req.trace_id)
    assert "serve.queue" in names and "serve.prefill" not in names


@pytest.fixture(scope="module")
def net():
    """Same spicy-weights recipe as test_serve.py (non-degenerate greedy
    paths through the real compiled slot programs)."""
    from incubator_mxnet_tpu.models.gpt import gpt_tiny

    mx.random.seed(11)
    m = gpt_tiny(vocab_size=VOCAB, max_length=64, dropout=0.0)
    m.initialize()
    r = onp.random.RandomState(42)
    for _name, p in m.collect_params().items():
        if p.shape and len(p.shape) >= 2:
            p.set_data(np.array(
                r.normal(0, 0.35, p.shape).astype("float32")))
    return m


def test_real_engine_traced_requests_and_recompile_gate(net):
    """The acceptance gate: tracing ON, every request gets a complete
    trace, and the engine's compiled-program count is IDENTICAL to the
    untraced steady state (host-side spans only — nothing enters jit)."""
    from incubator_mxnet_tpu import serve

    eng = serve.ServeEngine(net, max_slots=3, max_len=64, max_queue=32)
    try:
        # warm both prefill buckets + decode UNTRACED
        eng.generate(_prompt(5, seed=9), 3)
        eng.generate(onp.resize(_prompt(5, seed=9), 40), 3)
        warm_count = eng.xla_program_count()
        assert warm_count >= 2

        tracing.enable()
        prompts = [_prompt(int(onp.random.RandomState(i).randint(3, 18)),
                           seed=i) for i in range(6)]
        handles = [eng.submit(p, 4) for p in prompts]
        eng._drive_until(handles)
        for h in handles:
            assert h.error is None
            names = sorted(_span_names(h.trace_id))
            assert names == ["serve.decode", "serve.prefill",
                             "serve.queue", "serve.request"], names
            prefill = [s for s in tracing.finished_spans(h.trace_id)
                       if s.name == "serve.prefill"][0]
            # the chunk-bucket program that served the prompt's last
            # chunk, annotated by the scheduler
            assert prefill.attrs["bucket"] in (16, 32, 64)
            assert prefill.attrs["chunks"] >= 1
        # zero steady-state recompiles WITH tracing enabled
        assert eng.xla_program_count() == warm_count
    finally:
        eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_dump_noop_while_disabled(tmp_path):
    assert not tracing.is_enabled()
    assert tracing.maybe_flight_dump("nope") is None


def test_flight_recorder_on_injected_serve_fault(net, tmp_path):
    """An injected serve_step fault leaves flightrec_*.json holding the
    active (still-open) request trace — the postmortem the ISSUE asks
    for."""
    from incubator_mxnet_tpu import fault, serve
    from incubator_mxnet_tpu.test_utils import environment

    tracing.enable()
    with environment("MXNET_FLIGHTREC_DIR", str(tmp_path)):
        eng = serve.ServeEngine(net, max_slots=2, max_len=64, max_queue=8)
        try:
            req = eng.submit(_prompt(6, seed=3), 4)   # queued, not stepped
            fault.configure_injection("serve_step:1.0:0:1")
            try:
                with pytest.raises(fault.FaultInjected):
                    eng.step()
            finally:
                fault.clear_injection()
            dumps = list(tmp_path.glob("flightrec_serve_step_*.json"))
            assert len(dumps) == 1
            with open(dumps[0]) as f:
                payload = json.load(f)
            assert payload["error"]["type"] == "FaultInjected"
            # the armed chaos schedule rides along in the dump
            assert payload["fault_schedule"]["serve_step"]["fired"] == 1
            open_names = {s["name"] for s in payload["open_spans"]}
            # the queued request's trace is the in-flight context
            assert {"serve.request", "serve.queue"} <= open_names
            assert any(s.get("attrs", {}).get("request") == req.id
                       for s in payload["open_spans"]
                       if s["name"] == "serve.request")
            # the fault event itself is in the dump (on the serve.step
            # span that crashed)
            all_events = [ev for s in payload["spans"]
                          for ev in s.get("events", [])]
            assert any(ev["name"] == "fault.injected"
                       and ev["attrs"].get("seam") == "serve_step"
                       for ev in all_events)
            # the engine recovers on the next clean step
            eng._drive_until([req])
            assert req.error is None
        finally:
            eng.shutdown(drain=False)


def test_flight_recorder_on_estimator_crash_resume(tmp_path):
    """ResilienceHandler's crash-resume drops a flight dump BEFORE
    rewinding to the checkpoint (estimator_step seam)."""
    from incubator_mxnet_tpu import fault, gluon, preemption
    from incubator_mxnet_tpu.fault.resilience import ResilienceHandler
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator
    from incubator_mxnet_tpu.test_utils import environment

    tracing.enable()
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    est = Estimator(net, loss=gluon.loss.L2Loss(), trainer=trainer)
    import logging

    est.logger.setLevel(logging.CRITICAL)
    ckpt = preemption.TrainingCheckpointer(
        str(tmp_path / "ck"), net, trainer, every_n=1,
        register_signal=False)
    X = np.array(onp.random.RandomState(0)
                 .uniform(-1, 1, (32, 4)).astype("float32"))
    Y = np.array(onp.zeros((32, 1), "float32"))
    loader = gluon.data.DataLoader(
        gluon.data.ArrayDataset(X, Y), batch_size=8)
    with environment({"MXNET_FLIGHTREC_DIR": str(tmp_path),
                      "MXNET_RETRY_BASE_DELAY_MS": "1"}):
        fault.configure_injection("estimator_step:1.0:0:1")
        try:
            est.fit(loader, epochs=1, event_handlers=[
                ResilienceHandler(checkpointer=ckpt, max_resumes=2)])
        finally:
            fault.clear_injection()
    dumps = list(tmp_path.glob("flightrec_estimator_crash_*.json"))
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        payload = json.load(f)
    assert payload["error"]["type"] == "FaultInjected"
    crashed = [s for s in payload["spans"]
               if s["name"] == "estimator.step"
               and s.get("attrs", {}).get("error") == "FaultInjected"]
    assert crashed, [s["name"] for s in payload["spans"]]
    assert any(ev["name"] == "fault.injected"
               for ev in crashed[0]["events"])


# ---------------------------------------------------------------------------
# SLO tracker
# ---------------------------------------------------------------------------

def test_slo_latency_burn_math():
    h = registry.histogram("t_slo_ttft_seconds", buckets=(0.1, 0.5, 1.0))
    for _ in range(96):
        h.observe(0.05)
    for _ in range(4):
        h.observe(0.7)                 # 4% bad against a 0.1s threshold
    # target 0.90: budget 10%, bad 4% -> burn 0.4, holds
    r = slo.tracker().latency("lat90", "t_slo_ttft_seconds", 0.1,
                              target=0.90).evaluate()
    assert r["compliance"] == pytest.approx(0.96)
    assert r["burn"] == pytest.approx(0.4)
    assert r["ok"]
    # target 0.99: budget 1%, bad 4% -> burn 4.0, violated
    r2 = slo.tracker().latency("lat99", "t_slo_ttft_seconds", 0.1,
                               target=0.99).evaluate()
    assert r2["burn"] == pytest.approx(4.0)
    assert not r2["ok"]
    # gauges surfaced in the registry
    rep = registry.report()
    assert rep['mx_slo_error_budget_burn{slo="lat99"}']["value"] \
        == pytest.approx(4.0)
    assert rep['mx_slo_ok{slo="lat99"}']["value"] == 0
    assert rep['mx_slo_ok{slo="lat90"}']["value"] == 1
    # no data yet -> no violation, compliance None
    r3 = slo.tracker().latency("lat_empty", "t_slo_never_seen",
                               0.1).evaluate()
    assert r3["compliance"] is None and r3["ok"]


def test_slo_throughput_windows():
    c = registry.counter("t_slo_tokens_total")
    s = slo.tracker().throughput("tput", "t_slo_tokens_total",
                                 min_rate=100.0, target=0.5)
    now = [1000.0]
    s.observe_window(now[0])           # prime
    c.inc(500)
    now[0] += 1.0
    rate = s.observe_window(now[0])    # 500/s: good window
    assert rate == pytest.approx(500.0)
    c.inc(10)
    now[0] += 1.0
    s.observe_window(now[0])           # 10/s: bad window
    comp, detail = s._measure()        # adds one more (bad) window
    assert detail["windows"] == 3 and detail["good"] == 1
    assert comp == pytest.approx(1 / 3)


def test_slo_health_hook_raises_loudly():
    h = registry.histogram("t_slo_bad_seconds", buckets=(0.1, 1.0))
    for _ in range(10):
        h.observe(0.9)                 # 100% bad
    slo.tracker().latency("all_bad", "t_slo_bad_seconds", 0.1,
                          target=0.99)
    slo.install_health_check()
    with pytest.raises(mx.MXNetError, match="all_bad"):
        monitor.check()
    # uninstalling restores a clean check()
    monitor.remove_health_check("slo")
    monitor.check()
    assert slo.violations()            # the tracker itself still reports


def test_slo_presets_register():
    a = slo.serve_ttft(threshold_s=0.25)
    b = slo.step_time(threshold_s=1.0)
    assert a.series == "mx_serve_ttft_seconds"
    assert b.series == "mx_step_time_seconds"
    names = {s.name for s in slo.tracker().slos()}
    assert {"serve_ttft", "step_time"} <= names
    with pytest.raises(ValueError):
        slo.serve_ttft()               # duplicate name is loud


# ---------------------------------------------------------------------------
# training lifecycle spans
# ---------------------------------------------------------------------------

def test_estimator_and_dataloader_spans():
    import logging

    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator

    tracing.enable()
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    est = Estimator(net, loss=gluon.loss.L2Loss(),
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.01}))
    est.logger.setLevel(logging.CRITICAL)
    X = np.array(onp.random.RandomState(0)
                 .uniform(-1, 1, (64, 4)).astype("float32"))
    Y = np.array(onp.zeros((64, 1), "float32"))
    loader = gluon.data.DataLoader(
        gluon.data.ArrayDataset(X, Y), batch_size=16)
    est.fit(loader, epochs=2)
    names = _span_names()
    assert names.count("estimator.epoch") == 2
    steps = [s for s in tracing.finished_spans()
             if s.name == "estimator.step"]
    assert len(steps) == 8                     # 4 batches x 2 epochs
    epochs = [s for s in tracing.finished_spans()
              if s.name == "estimator.epoch"]
    # steps nest under their epoch
    assert all(any(st.parent_id == ep.span_id for ep in epochs)
               for st in steps)
    assert "dataloader.batch" in names


def test_kvstore_and_checkpoint_spans(tmp_path):
    from incubator_mxnet_tpu import kv, preemption

    tracing.enable()
    store = kv.create("local")
    store.init("w", np.array([1.0, 2.0]))
    store.push("w", np.array([0.1, 0.2]))
    store.pull("w")
    store.barrier()
    preemption.atomic_save(
        str(tmp_path / "ck.bin"),
        lambda p: open(p, "wb").write(b"x" * 16))
    names = _span_names()
    for expected in ("kvstore.push", "kvstore.pull", "kvstore.barrier",
                     "checkpoint.write"):
        assert expected in names, (expected, names)


def test_retry_events_annotate_span(tmp_path):
    from incubator_mxnet_tpu.fault.retry import RetryPolicy

    tracing.enable()
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise ConnectionError("transient")
        return "ok"

    with tracing.span("op") as s:
        out = RetryPolicy(max_retries=3, base_delay=0.0, jitter=0.0,
                          name="test").call(flaky)
    assert out == "ok"
    retries = [e for e in s.events if e[0] == "retry"]
    assert len(retries) == 2
    assert retries[0][2]["policy"] == "test"


# ---------------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------------

def test_telemetry_dump_knob_snapshots(tmp_path):
    path = str(tmp_path / "metrics.prom")
    registry.counter("t_dump_knob_total").inc(5)
    p, interval = registry.arm_textfile_dump(f"{path}:0.05")
    try:
        assert p == path and interval == pytest.approx(0.05)
        with open(path) as f:
            assert "t_dump_knob_total 5" in f.read()
        registry.counter("t_dump_knob_total").inc(2)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with open(path) as f:
                if "t_dump_knob_total 7" in f.read():
                    break
            time.sleep(0.02)
        else:
            pytest.fail("periodic dump never refreshed")
    finally:
        registry.stop_textfile_dump()
    # one-shot form (no interval)
    p2, i2 = registry.arm_textfile_dump(str(tmp_path / "once.prom"))
    assert i2 is None and os.path.exists(p2)
    registry.stop_textfile_dump()


def test_env_knobs_registered():
    from incubator_mxnet_tpu import util

    knobs = util.env_knobs()
    for k in ("MXNET_TELEMETRY_DUMP", "MXNET_FLIGHTREC_DIR"):
        assert k in knobs
        assert not knobs[k][0].startswith("(")   # honored


def test_mxnet_telemetry_env_arms_tracing():
    """MXNET_TELEMETRY=1 arms span tracing at import
    (util._apply_env_config) — same knob as stage tracing."""
    from incubator_mxnet_tpu import util
    from incubator_mxnet_tpu.telemetry import stages
    from incubator_mxnet_tpu.test_utils import environment

    assert not tracing.is_enabled()
    with environment("MXNET_TELEMETRY", "1"):
        util._apply_env_config()
    try:
        assert tracing.is_enabled()
        assert stages.is_enabled()
    finally:
        tracing.disable()
        stages.disable()


# ---------------------------------------------------------------------------
# ignored-arg loudness (satellite: VERDICT "dishonest surface")
# ---------------------------------------------------------------------------

def test_lazy_update_is_loud_once_and_counted():
    import warnings

    from incubator_mxnet_tpu.ndarray import optim_ops

    nd = mx.nd
    w = np.array(onp.ones((3,), "float32"))
    g = np.array(onp.ones((3,), "float32"))
    before = registry.counter("mx_ignored_arg_total",
                              labels={"arg": "lazy_update"}).value
    optim_ops._WARNED_IGNORED.discard("lazy_update")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        nd.sgd_update(w, g, lr=0.1, lazy_update=True)
        nd.sgd_update(w, g, lr=0.1, lazy_update=False)   # warn ONCE only
    loud = [x for x in rec if "lazy_update" in str(x.message)]
    assert len(loud) == 1
    assert "IGNORED" in str(loud[0].message)
    after = registry.counter("mx_ignored_arg_total",
                             labels={"arg": "lazy_update"}).value
    assert after - before == 2                 # every occurrence counted
    # not passing it stays silent and uncounted
    with warnings.catch_warnings(record=True) as rec2:
        warnings.simplefilter("always")
        nd.sgd_update(w, g, lr=0.1)
    assert not [x for x in rec2 if "lazy_update" in str(x.message)]
    assert registry.counter("mx_ignored_arg_total",
                            labels={"arg": "lazy_update"}).value == after
