"""`mx.analysis.shardcheck` — the static sharding pre-flight (ISSUE 8).

One seeded-defect fixture per rule SC001-SC006, each detected under the
forced 8-device CPU platform (conftest.py), plus clean-pass gates on the
real sharded programs: the DataParallel trainer step (the multichip-
dryrun BERT configuration) and both serve decoder program families.
The meta-test at the bottom is the CI gate: framework lint + the
spec/eval_shape tiers of shardcheck over the tree must stay at zero
findings.
"""
import os
import sys

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.analysis import SHARD_RULES, shardcheck
from incubator_mxnet_tpu.parallel import make_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _need_8():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def _sds(shape, dtype="float32"):
    import jax

    return jax.ShapeDtypeStruct(shape, onp.dtype(dtype))


def _rules(report):
    return sorted({f.kind for f in report})


# ---------------------------------------------------------------------------
# seeded-defect fixtures, one per rule
# ---------------------------------------------------------------------------

def test_sc001_unconstrained_param_flagged():
    # 2 MiB param with no spec on an 8-way mesh: silently replicated
    r = shardcheck(None, _sds((1024, 512)), mesh={"dp": 8}, specs=(None,))
    assert _rules(r) == ["SC001"], r.summary()
    f = r.by_rule("SC001")[0]
    assert f.nbytes == 1024 * 512 * 4
    assert "replicated" in f.message
    # per-device cost is the FULL size — nothing was sharded
    assert r.per_device_bytes == 1024 * 512 * 4
    # explicit P() is deliberate replication, small arrays are noise:
    # neither fires
    import jax

    P = jax.sharding.PartitionSpec
    assert len(shardcheck(None, _sds((1024, 512)), mesh={"dp": 8},
                          specs=(P(),))) == 0
    assert len(shardcheck(None, _sds((8, 4)), mesh={"dp": 8},
                          specs=(None,))) == 0


def test_sc002_divisibility_violation_flagged():
    import jax

    P = jax.sharding.PartitionSpec
    r = shardcheck(None, _sds((10, 4)), mesh={"dp": 8},
                   specs=(P("dp", None),))
    assert _rules(r) == ["SC002"], r.summary()
    msg = r.by_rule("SC002")[0].message
    assert "dim 0" in msg and "10" in msg and "dp" in msg
    # rank overflow is the same rule
    r = shardcheck(None, _sds((16,)), mesh={"dp": 8},
                   specs=(P("dp", None),))
    assert _rules(r) == ["SC002"], r.summary()


def test_sc003_unknown_axis_flagged():
    import jax

    P = jax.sharding.PartitionSpec
    r = shardcheck(None, _sds((16, 4)), mesh={"dp": 8},
                   specs=(P("zz", None),))
    assert _rules(r) == ["SC003"], r.summary()
    assert "'zz'" in r.by_rule("SC003")[0].message
    # severity error: the layout cannot be materialized at all
    assert r.by_rule("SC003")[0].severity == "error"


def test_sc004_donation_lost_flagged():
    _need_8()
    import jax

    P = jax.sharding.PartitionSpec
    mesh = make_mesh({"dp": 8})

    def step(w):
        return (w * 2.0,)

    r = shardcheck(step, _sds((128, 64)), mesh=mesh,
                   specs=(P("dp", None),), out_specs=(P(),),
                   donate_argnums=(0,))
    assert "SC004" in _rules(r), r.summary()
    assert "alias" in r.by_rule("SC004")[0].message
    # same specs both sides -> donation holds, no finding
    r = shardcheck(step, _sds((128, 64)), mesh=mesh,
                   specs=(P("dp", None),), out_specs=(P("dp", None),),
                   donate_argnums=(0,))
    assert "SC004" not in _rules(r), r.summary()
    assert r.donated_bytes == 128 * 64 * 4


def test_sc005_full_param_allgather_flagged():
    _need_8()
    import jax

    P = jax.sharding.PartitionSpec
    mesh = make_mesh({"dp": 8})

    # sharded input, replicated output: GSPMD must all-gather the full
    # operand every step — the compiled-HLO census catches it
    r = shardcheck(lambda w: w * 1.0, _sds((128, 64)), mesh=mesh,
                   specs=(P("dp", None),), out_specs=P())
    assert "SC005" in _rules(r), r.summary()
    assert "compile" in r.tiers
    ag = r.collectives.get("all-gather")
    assert ag and ag["count"] >= 1 and ag["bytes"] == 128 * 64 * 4
    # sharded end-to-end: no collective, no finding
    r = shardcheck(lambda w: w * 1.0, _sds((128, 64)), mesh=mesh,
                   specs=(P("dp", None),), out_specs=P("dp", None))
    assert len(r) == 0 and not r.collectives, r.summary()


def test_sc005_jaxpr_tier_sees_explicit_collectives():
    _need_8()
    import jax
    from jax.experimental.shard_map import shard_map

    P = jax.sharding.PartitionSpec
    mesh = make_mesh({"dp": 8})
    fn = shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                   in_specs=P("dp"), out_specs=P())
    # compile=False: the census must come from the jaxpr walk alone
    r = shardcheck(fn, _sds((8, 4)), mesh=mesh, specs=(P("dp"),),
                   out_specs=P(), compile=False)
    assert "jaxpr" in r.tiers and "compile" not in r.tiers
    assert r.collectives.get("all-reduce", {}).get("count") == 1, \
        r.collectives


def test_sc006_budget_exceeded_flagged():
    import jax

    P = jax.sharding.PartitionSpec
    r = shardcheck(None, _sds((1024, 512)), mesh={"dp": 8},
                   specs=(P("dp", None),), hbm_budget_gb=1e-6)
    assert _rules(r) == ["SC006"], r.summary()
    assert r.budget_bytes == int(1e-6 * 2**30)
    # sharding is accounted: the per-device estimate is total/8
    assert r.per_device_bytes == 1024 * 512 * 4 // 8
    # same layout under a sane budget is clean
    r = shardcheck(None, _sds((1024, 512)), mesh={"dp": 8},
                   specs=(P("dp", None),), hbm_budget_gb=16.0)
    assert len(r) == 0


def test_sc006_env_knob_budget():
    os.environ["MXNET_SHARDCHECK_HBM_GB"] = "0.0000001"
    try:
        r = shardcheck(None, _sds((1024, 512)), mesh={"dp": 8},
                       specs=(None,))
        assert "SC006" in _rules(r), r.summary()
    finally:
        del os.environ["MXNET_SHARDCHECK_HBM_GB"]


def test_rule_catalogue_complete():
    assert sorted(SHARD_RULES) == ["SC001", "SC002", "SC003", "SC004",
                                   "SC005", "SC006"]
    # telemetry: findings increment the per-rule counter
    from incubator_mxnet_tpu.telemetry import registry

    c = registry.counter("mx_shardcheck_findings_total",
                         labels={"rule": "SC003"})
    before = c.value
    import jax

    P = jax.sharding.PartitionSpec
    shardcheck(None, _sds((16, 4)), mesh={"dp": 8}, specs=(P("nope"),))
    assert c.value == before + 1


# ---------------------------------------------------------------------------
# clean-pass gates on the real sharded programs
# ---------------------------------------------------------------------------

def test_trainer_dryrun_config_passes_clean():
    """The multichip-dryrun BERT (TP param shardings, dp-sharded batch)
    must pre-flight clean through spec+eval_shape tiers — the same
    report `__graft_entry__.dryrun_multichip` stamps into its tail."""
    _need_8()
    from incubator_mxnet_tpu import gluon, optimizer
    from incubator_mxnet_tpu.models.bert import (bert_small,
                                                 tp_param_shardings)
    from incubator_mxnet_tpu.parallel.sharded import DataParallel

    mesh = make_mesh({"dp": 2, "tp": 4})
    net = bert_small(vocab_size=256, max_length=32, dropout=0.1,
                     seq_shard_axis="tp")
    net.initialize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm_loss(out, y):
        mlm_scores, _ = out
        return ce(mlm_scores.reshape(-1, 256), y.reshape(-1))

    dpar = DataParallel(net, mlm_loss, optimizer.Adam(learning_rate=1e-4),
                        mesh=mesh, param_shardings=tp_param_shardings(net))
    # construction-level (spec tier): params + optimizer states
    rep = dpar.shardcheck_report()
    assert len(rep) == 0, rep.summary()
    # full abstract trace with a batch (compile=False keeps tier-1 fast;
    # the compiled-tier collective census runs in tools/shardcheck.py)
    rng = onp.random.RandomState(0)
    tokens = np.array(rng.randint(0, 256, (4, 16)).astype("int32"))
    labels = np.array(rng.randint(0, 256, (4, 16)).astype("int32"))
    rep = dpar.shardcheck_report(tokens, labels, compile=False)
    assert len(rep) == 0, rep.summary()
    assert "eval_shape" in rep.tiers
    assert rep.stamp().startswith("shardcheck[DataParallel.step]")


def test_trainer_full_compile_tier_clean_and_audits_collectives():
    """Small trainer through ALL tiers incl. the simulated-mesh compile:
    clean, and the census shows the DP gradient all-reduce."""
    _need_8()
    from incubator_mxnet_tpu import gluon, optimizer
    from incubator_mxnet_tpu.parallel.sharded import DataParallel

    mesh = make_mesh({"dp": 8})
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    dp = DataParallel(net, gluon.loss.L2Loss(),
                      optimizer.SGD(learning_rate=0.5), mesh=mesh)
    X = onp.zeros((64, 4), "float32")
    Y = onp.zeros((64, 1), "float32")
    rep = dp.shardcheck_report(np.array(X), np.array(Y))
    assert len(rep) == 0, rep.summary()
    assert "compile" in rep.tiers
    assert rep.collectives.get("all-reduce", {}).get("count", 0) >= 1, \
        rep.collectives


def test_trainer_construction_knob_raises_on_seeded_defect():
    """MXNET_SHARDCHECK=raise catches a divisibility defect at trainer
    CONSTRUCTION — before jit would fail cryptically at the first step."""
    _need_8()
    import jax

    from incubator_mxnet_tpu import gluon, optimizer
    from incubator_mxnet_tpu.base import MXNetError
    from incubator_mxnet_tpu.parallel.sharded import DataParallel

    P = jax.sharding.PartitionSpec
    mesh = make_mesh({"dp": 8})
    net = gluon.nn.Dense(3, in_units=4)   # weight (3, 4): 3 % 8 != 0
    net.initialize()
    os.environ["MXNET_SHARDCHECK"] = "raise"
    try:
        with pytest.raises(MXNetError, match="SC002"):
            DataParallel(net, gluon.loss.L2Loss(), optimizer.SGD(),
                         mesh=mesh,
                         param_shardings=[P("dp", None), P()])
    finally:
        del os.environ["MXNET_SHARDCHECK"]


def test_serve_decoder_families_pass_clean_and_budget_accurate():
    """Both serve program families pre-flight clean, and the SC006
    per-device estimate for the decode program lands within 15% of the
    measured live-buffer bytes (acceptance criterion)."""
    import jax

    from incubator_mxnet_tpu.models.gpt import gpt_tiny
    from incubator_mxnet_tpu.serve.engine import SlotDecoder

    mx.random.seed(0)
    m = gpt_tiny(vocab_size=97, max_length=64, dropout=0.0)
    m.initialize()
    sd = SlotDecoder(m, max_slots=3, max_len=64)
    reps = sd.shardcheck_report()
    assert sorted(reps) == ["decode", "prefill"]
    for fam, rep in reps.items():
        assert len(rep) == 0, (fam, rep.summary())
        assert "eval_shape" in rep.tiers, (fam, rep.tiers)
        # the whole KV pool is donated back in both families
        assert rep.donated_bytes >= sd.cache_bytes, (fam, rep.donated_bytes)
    measured = (sum(v.nbytes for v in
                    jax.tree_util.tree_leaves(sd._dec._params))
                + sd.cache_bytes + sd._table_device().nbytes)
    est = reps["decode"].per_device_bytes
    assert abs(est - measured) / measured < 0.15, (est, measured)
    # a budget below the estimate trips SC006 on the same programs
    tiny = sd.shardcheck_report(hbm_budget_gb=measured / 2 / 2**30)
    assert any(f.kind == "SC006" for f in tiny["decode"]), \
        tiny["decode"].summary()


def test_serve_int8_family_passes_clean():
    from incubator_mxnet_tpu.models.gpt import gpt_tiny
    from incubator_mxnet_tpu.serve.engine import SlotDecoder

    mx.random.seed(0)
    m = gpt_tiny(vocab_size=97, max_length=64, dropout=0.0)
    m.initialize()
    sd = SlotDecoder(m, max_slots=3, max_len=64, kv_dtype="int8")
    for fam, rep in sd.shardcheck_report().items():
        assert len(rep) == 0, (fam, rep.summary())


# ---------------------------------------------------------------------------
# CI meta-gate: both static passes stay at zero findings over the tree
# ---------------------------------------------------------------------------

def test_static_gates_meta():
    """Framework lint (incl. FL010) over the tree + the spec/eval_shape
    tier of shardcheck over the real entry points: all zero findings.
    Every future PR inherits this gate."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    lint = framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu"),
         os.path.join(REPO, "tools"),
         os.path.join(REPO, "bench.py"),
         os.path.join(REPO, "__graft_entry__.py")])
    assert not lint, lint

    # shardcheck spec tier over a TP-sharded trainer layout (no compile)
    import jax

    from incubator_mxnet_tpu import gluon, optimizer
    from incubator_mxnet_tpu.models.bert import (bert_small,
                                                 tp_param_shardings)
    from incubator_mxnet_tpu.parallel.sharded import DataParallel

    if len(jax.devices()) >= 8:
        mesh = make_mesh({"dp": 2, "tp": 4})
        net = bert_small(vocab_size=256, max_length=32, dropout=0.0,
                         seq_shard_axis="tp")
        net.initialize()
        dpar = DataParallel(net, gluon.loss.L2Loss(), optimizer.SGD(),
                            mesh=mesh,
                            param_shardings=tp_param_shardings(net))
        rep = dpar.shardcheck_report()
        assert len(rep) == 0, rep.summary()

    # eval_shape tier over the serve decoder programs
    from incubator_mxnet_tpu.models.gpt import gpt_tiny
    from incubator_mxnet_tpu.serve.engine import SlotDecoder

    m = gpt_tiny(vocab_size=97, max_length=32, dropout=0.0)
    m.initialize()
    for fam, rep in SlotDecoder(m, max_slots=2,
                                max_len=32).shardcheck_report().items():
        assert len(rep) == 0, (fam, rep.summary())
