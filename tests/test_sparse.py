"""Sparse NDArray tests (reference strategy:
`tests/python/unittest/test_sparse_ndarray.py`, `test_sparse_operator.py`)."""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np
from incubator_mxnet_tpu.ndarray import sparse


def A(x):
    return onp.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)


def test_row_sparse_creation_and_densify():
    d = onp.array([[1, 2], [0, 0], [3, 4]], dtype="float32")
    rs = sparse.row_sparse_array(d)
    assert rs.stype == "row_sparse"
    assert rs.shape == (3, 2)
    onp.testing.assert_allclose(A(rs), d)
    onp.testing.assert_allclose(A(rs.indices), [0, 2])
    onp.testing.assert_allclose(A(rs.data), [[1, 2], [3, 4]])
    rs2 = sparse.row_sparse_array(
        (onp.array([[5.0, 6.0]], dtype="float32"), onp.array([1])),
        shape=(3, 2))
    onp.testing.assert_allclose(A(rs2), [[0, 0], [5, 6], [0, 0]])


def test_csr_creation_and_densify():
    d = onp.array([[1, 0, 2], [0, 0, 0], [0, 3, 0]], dtype="float32")
    c = sparse.csr_matrix(d)
    assert c.stype == "csr"
    onp.testing.assert_allclose(A(c), d)
    onp.testing.assert_allclose(A(c.data), [1, 2, 3])
    onp.testing.assert_allclose(A(c.indices), [0, 2, 1])
    onp.testing.assert_allclose(A(c.indptr), [0, 2, 2, 3])
    dense = c.tostype("default")
    onp.testing.assert_allclose(A(dense), d)


def test_csr_stays_consistent_after_inplace_mutation():
    # code-review finding: dense in-place mutation must not leave the CSR
    # payload stale
    c = sparse.csr_matrix(onp.array([[1, 0], [0, 2]], dtype="float32"))
    c *= 2
    onp.testing.assert_allclose(A(c), [[2, 0], [0, 4]])
    onp.testing.assert_allclose(A(c.data), [2, 4])
    out = sparse.dot(c, np.array(onp.eye(2, dtype="float32")))
    onp.testing.assert_allclose(A(out), [[2, 0], [0, 4]])
    c2 = c.copy()
    onp.testing.assert_allclose(A(c2.data), [2, 4])


def test_retain():
    d = onp.array([[1, 1], [2, 2], [3, 3], [4, 4]], dtype="float32")
    rs = sparse.row_sparse_array(d)
    kept = sparse.retain(rs, np.array([0, 3]))
    onp.testing.assert_allclose(A(kept.indices), [0, 3])
    onp.testing.assert_allclose(A(kept), [[1, 1], [0, 0], [0, 0], [4, 4]])


def test_sparse_dot_matches_dense():
    rng = onp.random.RandomState(0)
    d = rng.rand(5, 4).astype("float32") * (rng.rand(5, 4) > 0.5)
    w = rng.rand(4, 3).astype("float32")
    c = sparse.csr_matrix(d)
    out = sparse.dot(c, np.array(w))
    onp.testing.assert_allclose(A(out), d @ w, rtol=1e-5)
    outT = sparse.dot(c, np.array(w.T), transpose_b=True)
    onp.testing.assert_allclose(A(outT), d @ w, rtol=1e-5)


def test_sparse_dot_autograd_flows_to_dense_rhs():
    # code-review finding: gradients must reach the dense operand
    d = onp.array([[1, 0], [0, 2], [3, 0]], dtype="float32")
    c = sparse.csr_matrix(d)
    w = np.array(onp.ones((2, 4), dtype="float32"))
    w.attach_grad()
    with autograd.record():
        out = sparse.dot(c, w)
        loss = np.sum(out)
    loss.backward()
    # dL/dw = csr^T @ ones(3,4)
    onp.testing.assert_allclose(A(w.grad), d.T @ onp.ones((3, 4)), rtol=1e-5)


def test_sparse_dot_dense_fallback_autograd():
    d = onp.array([[1.0, 2.0], [3.0, 4.0]], dtype="float32")
    a = np.array(d)
    b = np.array(onp.eye(2, dtype="float32"))
    a.attach_grad()
    with autograd.record():
        out = sparse.dot(a, b)
        loss = np.sum(out * out)
    loss.backward()
    onp.testing.assert_allclose(A(a.grad), 2 * d, rtol=1e-5)


def test_embedding_sparse_grad_row_sparse_cotangent():
    # code-review finding: sparse_grad=True must produce a RowSparse grad
    # storing only looked-up rows
    from incubator_mxnet_tpu import npx

    vocab, dim = 50, 4
    w = np.array(onp.random.RandomState(1).rand(vocab, dim).astype("float32"))
    w.attach_grad(stype="row_sparse")
    idx = np.array(onp.array([1, 3, 3], dtype="float32"))
    with autograd.record():
        e = npx.embedding(idx, w, input_dim=vocab, output_dim=dim,
                          sparse_grad=True)
        loss = np.sum(e)
    loss.backward()
    g = w.grad
    assert isinstance(g, sparse.RowSparseNDArray)
    assert g.num_rows == 2  # only rows 1 and 3 stored
    onp.testing.assert_allclose(A(g.indices), [1, 3])
    want = onp.zeros((vocab, dim), dtype="float32")
    want[1] += 1
    want[3] += 2
    onp.testing.assert_allclose(A(g), want)


def test_embedding_sparse_grad_trainer_lazy_update():
    # End-to-end: gluon Embedding(sparse_grad=True) + Trainer sgd — only
    # touched rows move (reference lazy_update semantics)
    from incubator_mxnet_tpu import gluon

    vocab, dim = 30, 3
    emb = gluon.nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize()
    w0 = A(emb.weight.data()).copy()
    trainer = gluon.Trainer(emb.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    x = np.array(onp.array([2, 7], dtype="float32"))
    with autograd.record():
        out = emb(x)
        loss = np.sum(out)
    loss.backward()
    assert isinstance(emb.weight.data()._grad, sparse.RowSparseNDArray)
    trainer.step(1)
    w1 = A(emb.weight.data())
    moved = onp.where(onp.abs(w1 - w0).sum(axis=1) > 0)[0]
    onp.testing.assert_array_equal(moved, [2, 7])
    onp.testing.assert_allclose(w1[2], w0[2] - 0.5, rtol=1e-5)


def test_embedding_sparse_grad_adam_lazy_update():
    from incubator_mxnet_tpu import gluon

    vocab, dim = 20, 2
    emb = gluon.nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize()
    w0 = A(emb.weight.data()).copy()
    trainer = gluon.Trainer(emb.collect_params(), "adam",
                            {"learning_rate": 0.1})
    for _ in range(2):
        x = np.array(onp.array([4], dtype="float32"))
        with autograd.record():
            loss = np.sum(emb(x))
        loss.backward()
        trainer.step(1)
    w1 = A(emb.weight.data())
    moved = onp.where(onp.abs(w1 - w0).sum(axis=1) > 0)[0]
    onp.testing.assert_array_equal(moved, [4])


def test_sparse_zeros_and_add():
    z = sparse.zeros("row_sparse", (4, 2))
    assert z.stype == "row_sparse"
    assert A(z).sum() == 0
    rs = sparse.row_sparse_array(
        (onp.array([[1.0, 1.0]], dtype="float32"), onp.array([2])),
        shape=(4, 2))
    s = z + rs
    assert isinstance(s, sparse.RowSparseNDArray)
    onp.testing.assert_allclose(A(s), A(rs))


def test_scipy_interop():
    import scipy.sparse as sp

    m = sp.random(6, 5, density=0.4, format="csr", dtype="float32",
                  random_state=3)
    c = sparse.csr_matrix(m)
    onp.testing.assert_allclose(A(c), m.toarray(), rtol=1e-6)
