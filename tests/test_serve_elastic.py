"""serve.elastic — the elastic replica-set control plane (ISSUE 18).

All deterministic on CPU against the stub slot decoder (pure host
arithmetic, real PageAllocator/PrefixCache — same recipe as
test_gateway.py): scale-up spawns a WARMED replica and journals it,
scale-down drains (never below the floor) and retires once idle, a
replica killed mid-trace by the ``replica_crash`` chaos seam is
replaced with its in-flight work re-queued and ZERO failed requests, a
fault mid-spawn (``replica_spawn`` seam) rolls the fleet back to
exactly N, the page-budget funding gate fails LOUDLY, and the advisor
consume path acts on each recommendation exactly once.
"""
import numpy as onp
import pytest

from incubator_mxnet_tpu import serve
from incubator_mxnet_tpu.fault import injection
from incubator_mxnet_tpu.serve.elastic import (ReplicaScaleError,
                                               ReplicaSetController)
from incubator_mxnet_tpu.serve.engine import (PageAllocator,
                                              PagePoolExhausted,
                                              PrefixCache)
from incubator_mxnet_tpu.telemetry import registry

VOCAB = 97


@pytest.fixture(autouse=True)
def _clear_schedule():
    injection.clear_injection()
    yield
    injection.clear_injection()


class _StubSlots:
    """Paged-interface stand-in (same recipe as test_gateway.py): the
    final prefill chunk emits the prompt's length as the first token,
    decode increments — a request resumed after a replica crash from
    ``prompt + tokens`` must continue the same arithmetic run."""

    def __init__(self, max_slots=2, max_len=64, page_tokens=16,
                 prefill_chunk=64, n_pages=None):
        self.max_slots = max_slots
        self.max_len = max_len
        self.page_tokens = page_tokens
        self.prefill_chunk = prefill_chunk
        pages_per_slot = -(-max_len // page_tokens)
        self.allocator = PageAllocator(
            n_pages if n_pages is not None
            else max_slots * pages_per_slot + 1, page_tokens)
        self.prefix_cache = PrefixCache(self.allocator)
        self.released = False
        self.programs = 2          # pretend both families are compiled

    def set_slot_pages(self, slot, pages):
        pass

    def clear_slot(self, slot):
        pass

    def prefill_chunk_step(self, slot, chunk_tokens, t_start, key,
                           temperature=1.0):
        n = len(chunk_tokens)
        return int(t_start) + n, n, 0

    def decode_step(self, last_tok, pos, active, key, temperature):
        return onp.where(active, last_tok + 1, last_tok).astype(onp.int32)

    def xla_program_count(self):
        return self.programs

    def release(self):
        self.released = True


def _elastic_gateway(max_replicas=3, min_replicas=1, **gw_kwargs):
    reg = serve.ModelRegistry()
    reg.add("m", _StubSlots())
    gw = serve.Gateway(reg, **gw_kwargs)
    ctl = gw.enable_elastic(
        factories={"m": lambda n_pages: _StubSlots(n_pages=n_pages)},
        min_replicas=min_replicas, max_replicas=max_replicas)
    return gw, ctl


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


def _drive(gw, handles, steps=400):
    for _ in range(steps):
        gw.step()
        if all(h.done for h in handles):
            return
    raise AssertionError(
        f"requests not done: {[h.state for h in handles]}")


def _counter(name):
    rep = registry.report()
    return rep.get(name, {}).get("value", 0) or 0


# ---------------------------------------------------------------------------
# scale-up: spawn, warm, publish
# ---------------------------------------------------------------------------

def test_scale_up_spawns_warmed_replica_and_journals():
    gw, ctl = _elastic_gateway()
    try:
        assert ctl.replica_count("m") == 1
        u0 = _counter('mx_elastic_scale_events_total{direction="up"}')
        added = ctl.scale_up("m")
        assert [r.label for r in added] == ["m#1"]
        assert ctl.replica_count("m") == 2
        # warmed before published: the program-count snapshot exists and
        # the warmup drove real traffic through the scheduler
        assert ctl.warm_programs["m#1"] == 2
        assert added[0].sched.idle          # warmup fully drained
        assert _counter('mx_elastic_scale_events_total{direction="up"}') \
            == u0 + 1
        assert [e["direction"] for e in ctl.events] == ["up"]
        # the new replica takes traffic
        hs = [gw.submit("m", _prompt(8, i), 4) for i in range(4)]
        _drive(gw, hs)
        assert {h.state for h in hs} == {"done"}
        assert any(len(r.live) or True for r in gw._models["m"].replicas)
    finally:
        gw.shutdown(drain=False)


def test_scale_up_respects_ceiling_and_reuses_draining():
    gw, ctl = _elastic_gateway(max_replicas=2)
    try:
        ctl.scale_up("m")
        assert ctl.scale_up("m") == []      # at the ceiling: no-op
        assert ctl.replica_count("m") == 2
        # a draining replica is un-drained before any spawn
        ctl.scale_down("m")
        assert ctl.replica_count("m", live_only=True) == 1
        added = ctl.scale_up("m")
        assert len(added) == 1 and not added[0].draining
        assert ctl.replica_count("m") == 2   # reused, not spawned
    finally:
        gw.shutdown(drain=False)


def test_replica_indices_never_reused():
    gw, ctl = _elastic_gateway(max_replicas=3)
    try:
        ctl.scale_up("m")                    # -> m#1
        ctl.scale_down("m")
        gw.step()                            # idle drain retires it
        assert ctl.replica_count("m") == 1
        added = ctl.scale_up("m")            # -> m#2, never m#1 again
        assert [r.label for r in added] == ["m#2"]
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# scale-down: drain, floor
# ---------------------------------------------------------------------------

def test_scale_down_drains_and_never_below_min():
    gw, ctl = _elastic_gateway()
    try:
        ctl.scale_up("m", 2)
        assert ctl.replica_count("m") == 3
        assert ctl.scale_down("m", 5) == 2   # floor-clamped
        assert ctl.replica_count("m", live_only=True) == 1
        assert ctl.scale_down("m") == 0      # at the floor already
        gw.step()                            # both idle: retired
        assert ctl.replica_count("m") == 1
        d = _counter('mx_elastic_scale_events_total{direction="down"}')
        assert d >= 2
    finally:
        gw.shutdown(drain=False)


def test_draining_replica_finishes_in_flight_then_retires():
    gw, ctl = _elastic_gateway()
    try:
        ctl.scale_up("m")
        hs = [gw.submit("m", _prompt(8, i), 6) for i in range(4)]
        for _ in range(3):
            gw.step()                        # dispatch across replicas
        victim = next(r for r in gw._models["m"].replicas if r.live)
        ctl.scale_down("m", 1)
        # the drained replica may be the busy one; either way nothing
        # fails and everything completes
        _drive(gw, hs)
        assert {h.state for h in hs} == {"done"}
        gw.step()
        assert ctl.replica_count("m") == 1
        assert victim.sched.idle or not victim.draining
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# replica death (chaos): replace + zero failed requests
# ---------------------------------------------------------------------------

def test_replica_crash_mid_trace_replaced_zero_failed():
    gw, ctl = _elastic_gateway()
    try:
        ctl.scale_up("m")
        hs = [gw.submit("m", _prompt(8, i), 8) for i in range(6)]
        for _ in range(4):
            gw.step()                        # in flight on both replicas
        r0 = _counter(
            'mx_elastic_scale_events_total{direction="replace"}')
        injection.configure_injection("replica_crash@1:1.0:0:1")
        gw.step()                            # the tick reaps and replaces
        injection.clear_injection()
        labels = [r.label for r in gw._models["m"].replicas]
        assert "m#1" not in labels           # the dead replica is gone
        assert "m#2" in labels               # replacement spawned+warmed
        assert _counter(
            'mx_elastic_scale_events_total{direction="replace"}') \
            == r0 + 1
        _drive(gw, hs)
        states = [h.state for h in hs]
        assert states.count("failed") == 0, states
        assert {h.state for h in hs} == {"done"}
        # resumed arithmetic stayed continuous: first token is the
        # prompt length, then +1 per decode — crash resume included
        for h in hs:
            toks = h.result()
            assert toks == list(range(toks[0], toks[0] + len(toks)))
    finally:
        gw.shutdown(drain=False)


def test_crash_below_min_heals_next_tick_even_if_spawn_fails_once():
    gw, ctl = _elastic_gateway()
    try:
        # kill the only replica while ALSO failing the replacement spawn:
        # the fleet degrades to zero, then heals on a later tick
        injection.configure_injection(
            "replica_crash@0:1.0:0:1,replica_spawn:1.0:0:1")
        gw.step()
        injection.clear_injection()
        assert ctl.replica_count("m") in (0, 1)
        gw.step()                            # heal path retries
        assert ctl.replica_count("m") == 1
        hs = [gw.submit("m", _prompt(8, i), 4) for i in range(2)]
        _drive(gw, hs)
        assert {h.state for h in hs} == {"done"}
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# failed spawn: rollback to N
# ---------------------------------------------------------------------------

def test_spawn_fault_rolls_back_to_n_replicas():
    gw, ctl = _elastic_gateway()
    try:
        injection.configure_injection("replica_spawn:1.0:0:1")
        with pytest.raises(injection.FaultInjected):
            ctl.scale_up("m")
        injection.clear_injection()
        # fleet unchanged, no half-registered replica, engine released
        assert ctl.replica_count("m") == 1
        assert [r.label for r in gw._models["m"].replicas] == ["m"]
        assert "m#1" not in ctl.warm_programs
        # the next spawn works and does NOT reuse the burned index
        added = ctl.scale_up("m")
        assert [r.label for r in added] == ["m#1"]
    finally:
        gw.shutdown(drain=False)


def test_warmup_failure_is_rolled_back_and_loud():
    gw, ctl = _elastic_gateway()

    class _BadDecode(_StubSlots):
        def decode_step(self, *a, **k):
            raise RuntimeError("device wedged")

    ctl._factories["m"] = lambda n_pages: _BadDecode(n_pages=n_pages)
    try:
        with pytest.raises(ReplicaScaleError, match="warmup"):
            ctl.scale_up("m")
        assert ctl.replica_count("m") == 1
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# page-budget funding gate
# ---------------------------------------------------------------------------

def test_rebalance_pages_funding_gate_is_loud():
    reg = serve.ModelRegistry(total_pages=24)
    reg.add("m", _StubSlots())
    assert reg.rebalance_pages("m", 2) == 12
    assert reg.rebalance_pages("m", 6) == 4
    with pytest.raises(PagePoolExhausted, match="replica"):
        reg.rebalance_pages("m", 7)          # 24/7 < 4 pages: unfunded
    with pytest.raises(ValueError):
        reg.rebalance_pages("ghost", 2)
    # an unbudgeted registry never constrains (None = no shared pool)
    assert serve.ModelRegistry().rebalance_pages is not None


def test_unfunded_scale_up_leaves_fleet_intact():
    reg = serve.ModelRegistry(total_pages=16)
    reg.add("m", _StubSlots(n_pages=8))
    gw = serve.Gateway(reg)
    ctl = gw.enable_elastic(
        factories={"m": lambda n_pages: _StubSlots(n_pages=n_pages)},
        max_replicas=8)
    try:
        ctl.scale_up("m")                    # 16/2 = 8: funded
        ctl.scale_up("m")                    # 16/3 = 5: funded
        ctl.scale_up("m")                    # 16/4 = 4: funded
        with pytest.raises(PagePoolExhausted):
            ctl.scale_up("m")                # 16/5 < 4: LOUD, no spawn
        assert ctl.replica_count("m") == 4
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# advisor consumption + telemetry
# ---------------------------------------------------------------------------

def test_controller_consumes_each_recommendation_once():
    gw, ctl = _elastic_gateway()
    try:
        adv = gw._advisors.get("m")
        if adv is None:
            from incubator_mxnet_tpu.serve.advisor import AutoscaleAdvisor

            adv = gw._advisors["m"] = AutoscaleAdvisor("m")
        rec = {"t": 10.0, "action": "scale_up", "model": "m", "n": 1,
               "reason": "test", "evidence": {}}
        adv._log.append(rec)
        assert ctl.tick(now=11.0) == 1
        assert ctl.replica_count("m") == 2
        # the same recommendation is never acted on twice
        assert ctl.tick(now=12.0) == 0
        assert ctl.replica_count("m") == 2
        adv._log.append(dict(rec, t=20.0, action="scale_down"))
        ctl.tick(now=21.0)
        assert ctl.replica_count("m", live_only=True) == 1
    finally:
        gw.shutdown(drain=False)


def test_mx_serve_replicas_gauge_tracks_fleet():
    gw, ctl = _elastic_gateway()
    try:
        assert _counter('mx_serve_replicas{model="m"}') == 1
        ctl.scale_up("m")
        assert _counter('mx_serve_replicas{model="m"}') == 2
        ctl.scale_down("m")
        gw.step()
        assert _counter('mx_serve_replicas{model="m"}') == 1
    finally:
        gw.shutdown(drain=False)


def test_elastic_serve_knob_arms_controller(monkeypatch):
    monkeypatch.setenv("MXNET_ELASTIC_SERVE", "1")
    monkeypatch.setenv("MXNET_ELASTIC_MIN_REPLICAS", "1")
    monkeypatch.setenv("MXNET_ELASTIC_MAX_REPLICAS", "4")
    reg = serve.ModelRegistry()
    reg.add("m", _StubSlots())
    gw = serve.Gateway(reg)
    try:
        assert isinstance(gw._elastic, ReplicaSetController)
        assert gw._elastic.min_replicas == 1
        assert gw._elastic.max_replicas == 4
    finally:
        gw.shutdown(drain=False)


def test_prebuilt_model_without_factory_raises_clear_error():
    reg = serve.ModelRegistry()
    reg.add("m", _StubSlots())
    gw = serve.Gateway(reg)
    ctl = gw.enable_elastic()                # no factories
    try:
        with pytest.raises(ValueError, match="factories"):
            ctl.scale_up("m")
        assert ctl.replica_count("m") == 1
    finally:
        gw.shutdown(drain=False)
