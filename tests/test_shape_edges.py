"""Degenerate and boundary shapes through reductions, reshapes, joins and
broadcasting — the reference's zero-size/one-element corpus
(`tests/python/unittest/test_numpy_op.py` degenerate-shape sweeps)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np

RNG = onp.random.RandomState(13)


def _arr(*shape):
    return RNG.uniform(-2, 2, shape).astype("float32")


# -- zero-size arrays --------------------------------------------------------

def test_zeros_size_array_creation():
    x = np.zeros((0, 3))
    assert x.shape == (0, 3)
    assert x.size == 0


def test_empty_sum_is_zero():
    assert float(np.sum(np.zeros((0,))).asnumpy()) == 0.0


def test_empty_prod_is_one():
    assert float(np.prod(np.zeros((0,))).asnumpy()) == 1.0


def test_empty_mean_is_nan():
    out = float(np.mean(np.zeros((0,))).asnumpy())
    assert onp.isnan(out)


def test_empty_concat():
    a = np.zeros((0, 3))
    b = np.array(_arr(2, 3))
    got = np.concatenate([a, b], axis=0).asnumpy()
    assert got.shape == (2, 3)


def test_empty_reshape():
    x = np.zeros((0, 4)).reshape(0, 2, 2)
    assert x.shape == (0, 2, 2)


def test_empty_transpose():
    x = np.zeros((0, 4)).T
    assert x.shape == (4, 0)


def test_empty_elementwise():
    out = np.exp(np.zeros((0, 2)))
    assert out.shape == (0, 2)


def test_empty_matmul():
    a = np.zeros((0, 4))
    b = np.array(_arr(4, 3))
    assert np.matmul(a, b).shape == (0, 3)


def test_empty_boolean_mask_result():
    a = _arr(3)
    m = onp.zeros(3, bool)
    got = np.array(a)[np.array(m)].asnumpy()
    assert got.shape == (0,)


# -- reductions over axes incl. empty/keepdims -------------------------------

def _check_reduce(name, shape, axis, keepdims=False, **kw):
    a = _arr(*shape)
    fn = getattr(np, name)
    ref = getattr(onp, name)
    got = fn(np.array(a), axis=axis, keepdims=keepdims).asnumpy()
    onp.testing.assert_allclose(got, ref(a, axis=axis, keepdims=keepdims),
                                rtol=1e-5, atol=1e-6, **kw)


def test_sum_axis0():
    _check_reduce("sum", (4, 5), 0)


def test_sum_axis1_keepdims():
    _check_reduce("sum", (4, 5), 1, keepdims=True)


def test_sum_axis_tuple():
    _check_reduce("sum", (3, 4, 5), (0, 2))


def test_sum_axis_none():
    _check_reduce("sum", (3, 4), None)


def test_sum_negative_axis():
    _check_reduce("sum", (3, 4, 5), -1)


def test_mean_axis_tuple_keepdims():
    _check_reduce("mean", (3, 4, 5), (1, 2), keepdims=True)


def test_max_axis():
    _check_reduce("max", (4, 6), 1)


def test_min_axis():
    _check_reduce("min", (4, 6), 0)


def test_prod_axis():
    _check_reduce("prod", (3, 4), 1)


def test_var_axis():
    _check_reduce("var", (5, 6), 0)


def test_std_axis():
    _check_reduce("std", (5, 6), 1)


def test_var_ddof():
    a = _arr(6, 3)
    got = np.var(np.array(a), axis=0, ddof=1).asnumpy()
    onp.testing.assert_allclose(got, onp.var(a, axis=0, ddof=1), rtol=1e-5)


def test_cumsum_axis():
    a = _arr(3, 4)
    for ax in (0, 1, None):
        got = np.cumsum(np.array(a), axis=ax).asnumpy()
        onp.testing.assert_allclose(got, onp.cumsum(a, axis=ax), rtol=1e-5)


def test_cumprod_axis():
    a = _arr(3, 4)
    got = np.cumprod(np.array(a), axis=1).asnumpy()
    onp.testing.assert_allclose(got, onp.cumprod(a, axis=1), rtol=1e-5)


def test_nansum():
    a = _arr(3, 4)
    a[0, 0] = onp.nan
    got = np.nansum(np.array(a), axis=0).asnumpy()
    onp.testing.assert_allclose(got, onp.nansum(a, axis=0), rtol=1e-5)


def test_nanmean():
    a = _arr(3, 4)
    a[1, 2] = onp.nan
    got = np.nanmean(np.array(a), axis=1).asnumpy()
    onp.testing.assert_allclose(got, onp.nanmean(a, axis=1), rtol=1e-5)


def test_nanmax_nanmin():
    a = _arr(3, 4)
    a[2, 1] = onp.nan
    onp.testing.assert_allclose(np.nanmax(np.array(a), axis=0).asnumpy(),
                                onp.nanmax(a, axis=0), rtol=1e-6)
    onp.testing.assert_allclose(np.nanmin(np.array(a), axis=0).asnumpy(),
                                onp.nanmin(a, axis=0), rtol=1e-6)


def test_amax_alias():
    a = _arr(4, 4)
    onp.testing.assert_allclose(np.amax(np.array(a)).asnumpy(),
                                onp.amax(a), rtol=1e-6)


def test_ptp():
    a = _arr(4, 5)
    got = np.ptp(np.array(a), axis=1).asnumpy()
    onp.testing.assert_allclose(got, onp.ptp(a, axis=1), rtol=1e-6)


def test_median():
    a = _arr(5, 4)
    got = np.median(np.array(a), axis=0).asnumpy()
    onp.testing.assert_allclose(got, onp.median(a, axis=0), rtol=1e-6)


def test_quantile():
    a = _arr(20)
    got = np.quantile(np.array(a), 0.3).asnumpy()
    onp.testing.assert_allclose(got, onp.quantile(a, 0.3), rtol=1e-5)


def test_percentile():
    a = _arr(20)
    got = np.percentile(np.array(a), 75).asnumpy()
    onp.testing.assert_allclose(got, onp.percentile(a, 75), rtol=1e-5)


def test_average_weighted():
    a = _arr(6)
    w = onp.abs(_arr(6)) + 0.1
    got = np.average(np.array(a), weights=np.array(w)).asnumpy()
    onp.testing.assert_allclose(got, onp.average(a, weights=w), rtol=1e-5)


def test_all_any():
    a = onp.array([[1.0, 0.0], [1.0, 1.0]], "float32")
    onp.testing.assert_array_equal(np.all(np.array(a), axis=1).asnumpy(),
                                   onp.all(a, axis=1))
    onp.testing.assert_array_equal(np.any(np.array(a), axis=0).asnumpy(),
                                   onp.any(a, axis=0))


def test_count_nonzero():
    a = onp.array([[1.0, 0.0, 2.0], [0.0, 0.0, 3.0]], "float32")
    got = np.count_nonzero(np.array(a), axis=1).asnumpy()
    onp.testing.assert_array_equal(got, onp.count_nonzero(a, axis=1))


# -- broadcasting edges ------------------------------------------------------

def test_broadcast_scalar_to_matrix():
    a = _arr(3, 4)
    got = (np.array(a) + np.array(onp.float32(2.0))).asnumpy()
    onp.testing.assert_allclose(got, a + 2.0, rtol=1e-6)


def test_broadcast_column_row():
    c = _arr(4, 1)
    r = _arr(1, 5)
    got = (np.array(c) * np.array(r)).asnumpy()
    onp.testing.assert_allclose(got, c * r, rtol=1e-6)


def test_broadcast_to():
    a = _arr(1, 3)
    got = np.broadcast_to(np.array(a), (4, 3)).asnumpy()
    onp.testing.assert_array_equal(got, onp.broadcast_to(a, (4, 3)))


def test_broadcast_incompatible_raises():
    with pytest.raises(Exception):
        (np.array(_arr(3, 2)) + np.array(_arr(3, 4))).asnumpy()


def test_broadcast_grad_sums_over_broadcast_axes():
    a = np.array(_arr(1, 3))
    a.attach_grad()
    b = np.array(_arr(4, 3))
    with autograd.record():
        y = a + b
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), onp.full((1, 3), 4.0))


# -- reshape / transpose edges -----------------------------------------------

def test_reshape_minus_one():
    a = _arr(4, 6)
    assert np.array(a).reshape(-1, 3).shape == (8, 3)


def test_reshape_to_scalar_like():
    a = _arr(1, 1)
    assert np.array(a).reshape(()).shape == ()


def test_transpose_axes_perm():
    a = _arr(2, 3, 4)
    got = np.transpose(np.array(a), (2, 0, 1)).asnumpy()
    onp.testing.assert_array_equal(got, onp.transpose(a, (2, 0, 1)))


def test_swapaxes():
    a = _arr(2, 3, 4)
    got = np.swapaxes(np.array(a), 0, 2).asnumpy()
    onp.testing.assert_array_equal(got, onp.swapaxes(a, 0, 2))


def test_moveaxis():
    a = _arr(2, 3, 4)
    got = np.moveaxis(np.array(a), 0, -1).asnumpy()
    onp.testing.assert_array_equal(got, onp.moveaxis(a, 0, -1))


def test_squeeze_all_and_axis():
    a = _arr(1, 3, 1)
    assert np.squeeze(np.array(a)).shape == (3,)
    assert np.squeeze(np.array(a), axis=0).shape == (3, 1)


def test_expand_dims():
    a = _arr(3, 4)
    assert np.expand_dims(np.array(a), 1).shape == (3, 1, 4)
    assert np.expand_dims(np.array(a), -1).shape == (3, 4, 1)


def test_ravel_flatten():
    a = _arr(3, 4)
    onp.testing.assert_array_equal(np.ravel(np.array(a)).asnumpy(),
                                   a.ravel())


def test_flip():
    a = _arr(3, 4)
    got = np.flip(np.array(a), axis=1).asnumpy()
    onp.testing.assert_array_equal(got, onp.flip(a, axis=1))


def test_roll():
    a = _arr(3, 4)
    got = np.roll(np.array(a), 2, axis=1).asnumpy()
    onp.testing.assert_array_equal(got, onp.roll(a, 2, axis=1))


def test_rot90():
    a = _arr(3, 4)
    got = np.rot90(np.array(a)).asnumpy()
    onp.testing.assert_array_equal(got, onp.rot90(a))


def test_atleast_nd():
    a = _arr(3)
    assert np.atleast_2d(np.array(a)).shape == (1, 3)
    assert np.atleast_3d(np.array(a)).shape == (1, 3, 1)


# -- join / split edges ------------------------------------------------------

def test_concatenate_axis1():
    a, b = _arr(2, 3), _arr(2, 2)
    got = np.concatenate([np.array(a), np.array(b)], axis=1).asnumpy()
    onp.testing.assert_array_equal(got, onp.concatenate([a, b], axis=1))


def test_stack_new_axis():
    a, b = _arr(2, 3), _arr(2, 3)
    for ax in (0, 1, 2, -1):
        got = np.stack([np.array(a), np.array(b)], axis=ax).asnumpy()
        onp.testing.assert_array_equal(got, onp.stack([a, b], axis=ax))


def test_vstack_hstack_dstack():
    a, b = _arr(2, 3), _arr(2, 3)
    onp.testing.assert_array_equal(
        np.vstack([np.array(a), np.array(b)]).asnumpy(), onp.vstack([a, b]))
    onp.testing.assert_array_equal(
        np.hstack([np.array(a), np.array(b)]).asnumpy(), onp.hstack([a, b]))
    onp.testing.assert_array_equal(
        np.dstack([np.array(a), np.array(b)]).asnumpy(), onp.dstack([a, b]))


def test_split_equal():
    a = _arr(6, 4)
    got = np.split(np.array(a), 3, axis=0)
    ref = onp.split(a, 3, axis=0)
    for g, r in zip(got, ref):
        onp.testing.assert_array_equal(g.asnumpy(), r)


def test_split_by_indices():
    a = _arr(7, 2)
    got = np.split(np.array(a), [2, 5], axis=0)
    ref = onp.split(a, [2, 5], axis=0)
    for g, r in zip(got, ref):
        onp.testing.assert_array_equal(g.asnumpy(), r)


def test_array_split_uneven():
    a = _arr(7, 2)
    got = np.array_split(np.array(a), 3, axis=0)
    ref = onp.array_split(a, 3, axis=0)
    for g, r in zip(got, ref):
        onp.testing.assert_array_equal(g.asnumpy(), r)


def test_tile():
    a = _arr(2, 3)
    got = np.tile(np.array(a), (2, 2)).asnumpy()
    onp.testing.assert_array_equal(got, onp.tile(a, (2, 2)))


def test_repeat_axis():
    a = _arr(2, 3)
    got = np.repeat(np.array(a), 3, axis=1).asnumpy()
    onp.testing.assert_array_equal(got, onp.repeat(a, 3, axis=1))


def test_pad_constant():
    a = _arr(2, 3)
    got = np.pad(np.array(a), ((1, 1), (0, 2))).asnumpy()
    onp.testing.assert_array_equal(got, onp.pad(a, ((1, 1), (0, 2))))


def test_pad_edge_reflect():
    a = _arr(3, 4)
    for mode in ("edge", "reflect"):
        got = np.pad(np.array(a), ((1, 1), (1, 1)), mode=mode).asnumpy()
        onp.testing.assert_array_equal(got, onp.pad(a, ((1, 1), (1, 1)),
                                                    mode=mode))


# -- 1-element / scalar boundary ---------------------------------------------

def test_scalar_array_reductions():
    x = np.array(onp.float32(3.5))
    assert float(np.sum(x).asnumpy()) == pytest.approx(3.5)
    assert float(np.max(x).asnumpy()) == pytest.approx(3.5)


def test_item_on_one_element():
    assert np.array(onp.ones((1, 1), "float32")).item() == 1.0


def test_float_conversion_requires_scalar():
    with pytest.raises(Exception):
        float(np.array(onp.ones((2,), "float32")))


def test_matmul_vector_vector():
    a, b = _arr(4), _arr(4)
    got = float(np.matmul(np.array(a), np.array(b)).asnumpy())
    assert got == pytest.approx(float(a @ b), rel=1e-5)


def test_matmul_matrix_vector():
    a, b = _arr(3, 4), _arr(4)
    got = np.matmul(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(got, a @ b, rtol=1e-5)


def test_sum_grad_broadcasts_ones():
    a = np.array(_arr(3, 4))
    a.attach_grad()
    with autograd.record():
        y = np.sum(a)
    y.backward()
    onp.testing.assert_array_equal(a.grad.asnumpy(), onp.ones((3, 4)))


def test_mean_grad_scales():
    a = np.array(_arr(2, 5))
    a.attach_grad()
    with autograd.record():
        y = np.mean(a)
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), onp.full((2, 5), 0.1),
                                rtol=1e-6)


def test_max_grad_routes_to_argmax():
    av = onp.array([[1.0, 3.0], [5.0, 2.0]], "float32")
    a = np.array(av)
    a.attach_grad()
    with autograd.record():
        y = np.max(a, axis=1)
    y.backward()
    onp.testing.assert_array_equal(a.grad.asnumpy(),
                                   [[0.0, 1.0], [1.0, 0.0]])