"""Symbol API depth: composition, shape/type inference, json round-trip,
executor semantics, gradient binding (reference:
`tests/python/unittest/test_symbol.py`)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, sym
from incubator_mxnet_tpu import symbol as symbol_mod

RNG = onp.random.RandomState(47)


def _nd(*shape):
    return np.array(RNG.uniform(-1, 1, shape).astype("float32"))


def test_variable_identity():
    a = sym.Variable("a")
    assert a.name == "a"
    assert a.list_arguments() == ["a"]


def test_compose_arithmetic():
    a, b = sym.Variable("a"), sym.Variable("b")
    c = a * 2 + b
    assert set(c.list_arguments()) == {"a", "b"}


def test_scalar_ops_compose():
    a = sym.Variable("a")
    c = (a + 1.0) * 2.0 - 3.0
    out = c.bind(None, {"a": _nd(2, 2)}).forward()[0]
    ref = (out.asnumpy() + 0)  # smoke: executes
    assert ref.shape == (2, 2)


def test_eval_matches_eager():
    a, b = sym.Variable("a"), sym.Variable("b")
    c = a * b + a
    av, bv = _nd(3, 3), _nd(3, 3)
    got = c.eval(a=av, b=bv)[0].asnumpy()
    onp.testing.assert_allclose(got, av.asnumpy() * bv.asnumpy()
                                + av.asnumpy(), rtol=1e-5)


def test_infer_shape_forward():
    a = sym.Variable("a")
    w = sym.Variable("w")
    b = sym.Variable("b")
    d = sym.FullyConnected(a, w, b, num_hidden=7, name="fc")
    arg_shapes, out_shapes, _ = d.infer_shape(a=(5, 3), w=(7, 3), b=(7,))
    assert out_shapes[0] == (5, 7)
    assert arg_shapes[d.list_arguments().index("w")] == (7, 3)


def test_infer_shape_partial():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    _, outs, _ = c.infer_shape(a=(2, 3), b=(2, 3))
    assert outs[0] == (2, 3)


def test_list_outputs_multi():
    a = sym.Variable("a")
    g = symbol_mod.Group([a * 2, a + 1])
    assert len(g.list_outputs()) == 2
    assert g.num_outputs == 2


def test_getitem_output_selection():
    a = sym.Variable("a")
    s = sym.split(a, 2, axis=0)
    first = s[0]
    ex = first.bind(None, {"a": _nd(4, 2)})
    out = ex.forward()[0]
    assert out.shape == (2, 2)


def test_json_roundtrip_preserves_graph():
    a, b = sym.Variable("a"), sym.Variable("b")
    w, bb = sym.Variable("w"), sym.Variable("bias")
    c = sym.FullyConnected(a * b, w, bb, num_hidden=4, name="fc")
    js = c.tojson()
    c2 = symbol_mod.fromjson(js)
    assert set(c2.list_arguments()) == set(c.list_arguments())
    args = {"a": _nd(2, 3), "b": _nd(2, 3),
            "w": _nd(4, 3), "bias": _nd(4)}
    o1 = c.bind(None, dict(args)).forward()[0].asnumpy()
    o2 = c2.bind(None, dict(args)).forward()[0].asnumpy()
    onp.testing.assert_allclose(o1, o2, rtol=1e-5)


def test_save_load_file(tmp_path):
    a = sym.Variable("a")
    c = sym.relu(a * 2, name="r")
    p = str(tmp_path / "sym.json")
    c.save(p)
    c2 = symbol_mod.load(p)
    assert c2.list_arguments() == c.list_arguments()


def test_executor_backward_grads():
    a = sym.Variable("a")
    c = (a * a).sum()
    av = _nd(3)
    ex = c.bind(None, {"a": av}, args_grad={"a": np.zeros((3,))})
    ex.forward(is_train=True)
    ex.backward()
    onp.testing.assert_allclose(ex.grad_dict["a"].asnumpy(),
                                2 * av.asnumpy(), rtol=1e-5)


def test_simple_bind_allocates_from_shapes():
    a = sym.Variable("a")
    w = sym.Variable("w", shape=(3, 5))
    c = sym.FullyConnected(a, w, no_bias=True, num_hidden=3, name="fc")
    ex = c.simple_bind(None, a=(2, 5))
    out = ex.forward()[0]
    assert out.shape == (2, 3)


def test_attributes_round_trip():
    a = sym.Variable("a", shape=(2, 2), attr={"test_attr": "hello"})
    assert a.attr("test_attr") == "hello"
    assert a.attr("__shape__") is not None


def test_name_uniquing():
    a = sym.Variable("x")
    f1 = sym.relu(a)
    f2 = sym.relu(a)
    assert f1.name != f2.name


def test_grouped_symbol():
    a, b = sym.Variable("a"), sym.Variable("b")
    g = symbol_mod.Group([a * 2, b + 1])
    assert len(g.list_outputs()) == 2
    outs = g.bind(None, {"a": _nd(2), "b": _nd(2)}).forward()
    assert len(outs) == 2


def test_symbol_activation_ops():
    a = sym.Variable("a")
    av = _nd(3, 3)
    for op in ("relu", "sigmoid", "tanh"):
        s = getattr(sym, op)(a)
        out = s.eval(a=av)[0].asnumpy()
        assert out.shape == (3, 3)


def test_symbol_reshape_transpose():
    a = sym.Variable("a")
    out = sym.transpose(sym.reshape(a, shape=(3, 4))).eval(
        a=_nd(4, 3))[0]
    assert out.shape == (4, 3)


def test_symbolblock_from_symbol():
    from incubator_mxnet_tpu import gluon

    a = sym.Variable("data")
    w = sym.Variable("w", shape=(4, 6))
    c = sym.FullyConnected(a, w, no_bias=True, num_hidden=4, name="fc")
    blk = gluon.SymbolBlock(c, [a], params={"w": _nd(4, 6)})
    blk.initialize()
    out = blk(_nd(2, 6))
    assert out.shape == (2, 4)