"""Static-analysis subsystem: auditor hazard classes (one positive + one
clean case per class), framework-lint rules (fixture snippets that must
trip each rule + the real pre-fix hazards), regression tests for the
advisor-found fixes that seeded the lint rules, and the tier-1 smokes
(lint over the whole package, audit of a hybridized model_zoo block)."""
import os
import subprocess
import sys

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np as mnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    return framework_lint


def _kinds(report):
    return {f.kind for f in report}


# ---------------------------------------------------------------------------
# auditor: host-sync
# ---------------------------------------------------------------------------

def test_audit_host_sync_positive():
    def f(x):
        if float(x.sum()) > 0:      # device->host sync in the hot path
            return x + 1
        return x

    rep = mx.analysis.audit(f, mnp.ones((4, 4)))
    assert "host-sync" in _kinds(rep)
    # the abstract trace must also catch it as a definite error
    assert any(f_.severity == "error" for f_ in rep.by_kind("host-sync"))


def test_audit_host_sync_clean():
    def f(x):
        return (x * 2 + 1).sum()

    rep = mx.analysis.audit(f, mnp.ones((4, 4)))
    assert len(rep) == 0
    assert rep.jaxpr is not None     # traced clean end-to-end


def test_audit_host_sync_in_block_forward():
    from incubator_mxnet_tpu import gluon

    class Syncy(gluon.HybridBlock):
        def forward(self, x):
            return x * float(x.max())        # sync inside forward

    net = Syncy()
    net.initialize()
    x = mnp.ones((2, 3))
    net(x)
    rep = net.audit(x)
    assert "host-sync" in _kinds(rep)


# ---------------------------------------------------------------------------
# auditor: recompilation hazards
# ---------------------------------------------------------------------------

def test_audit_python_scalar_arg_positive_and_clean():
    def f(x, s):
        return x * s

    rep = mx.analysis.audit(f, mnp.ones((2, 2)), 3.14)
    assert "recompile-python-scalar" in _kinds(rep)

    rep2 = mx.analysis.audit(f, mnp.ones((2, 2)), mnp.array(3.14))
    assert "recompile-python-scalar" not in _kinds(rep2)


def test_audit_weak_type_positive_and_clean():
    import jax.numpy as jnp

    def f(x):
        return x + 1

    weak = mx.nd.NDArray(jnp.asarray(2.0))          # weak-typed buffer
    assert weak._data.weak_type
    rep = mx.analysis.audit(f, weak)
    assert "recompile-weak-type" in _kinds(rep)

    strong = mnp.array([2.0], dtype="float32")
    rep2 = mx.analysis.audit(f, strong)
    assert "recompile-weak-type" not in _kinds(rep2)


def test_audit_unhashable_static_kwarg_positive_and_clean():
    def f(x, cfg=None):
        return x * 2 if cfg else x

    rep = mx.analysis.audit(f, mnp.ones((2, 2)), cfg=[1, 2])
    assert "recompile-unhashable-static" in _kinds(rep)

    rep2 = mx.analysis.audit(f, mnp.ones((2, 2)), cfg=(1, 2))
    assert "recompile-unhashable-static" not in _kinds(rep2)


def test_jit_cache_report_flags_scalar_churn():
    x = mnp.ones((4,))
    for i in range(10):
        mnp.add(x, 0.125 + i)        # distinct static scalar per call
    rep = mx.analysis.jit_cache_report(threshold=8)
    assert any(f.kind == "recompile-cache-churn" and f.op == "add"
               for f in rep)


# ---------------------------------------------------------------------------
# auditor: dtype promotion drift + buffer mutation
# ---------------------------------------------------------------------------

def test_audit_promotion_drift_positive_and_clean():
    def f(a, b):
        return a / b

    a = mnp.array([1, 2], dtype="int32")
    b = mnp.array([2, 2], dtype="int32")
    rep = mx.analysis.audit(f, a, b)
    # reference table: true_divide(int32, int32) -> float64; jax -> float32
    assert "dtype-promotion-drift" in _kinds(rep)

    def g(a, b):
        return a + b

    af = mnp.array([1.0, 2.0], dtype="float32")
    rep2 = mx.analysis.audit(g, af, af)
    assert len(rep2) == 0


def test_audit_buffer_mutation_positive_and_clean():
    def f(x):
        x += 1                       # in-place rebind of the input buffer
        return x

    rep = mx.analysis.audit(f, mnp.ones((2, 2)))
    assert "aliased-buffer-mutation" in _kinds(rep)

    def g(x):
        return x + 1

    rep2 = mx.analysis.audit(g, mnp.ones((2, 2)))
    assert "aliased-buffer-mutation" not in _kinds(rep2)


# ---------------------------------------------------------------------------
# auditor: MXNET_ANALYSIS knob
# ---------------------------------------------------------------------------

def _sync_fn(x):
    return x + float(x.sum())


def test_analysis_knob_raise(monkeypatch):
    monkeypatch.setenv("MXNET_ANALYSIS", "raise")
    with pytest.raises(mx.MXNetError, match="MXNET_ANALYSIS=raise"):
        mx.analysis.audit(_sync_fn, mnp.ones((2, 2)))


def test_analysis_knob_warn_logs(monkeypatch, caplog):
    import logging

    monkeypatch.setenv("MXNET_ANALYSIS", "warn")
    with caplog.at_level(logging.WARNING, "incubator_mxnet_tpu.analysis"):
        rep = mx.analysis.audit(_sync_fn, mnp.ones((2, 2)))
    assert len(rep) > 0
    assert any("host-sync" in r.message for r in caplog.records)


def test_analysis_knob_documented():
    from incubator_mxnet_tpu import util

    how, doc = util.env_knobs()["MXNET_ANALYSIS"]
    assert "analysis" in how and "raise" in doc


# ---------------------------------------------------------------------------
# framework lint: rule fixtures
# ---------------------------------------------------------------------------

def test_lint_fl001_pad_guard():
    fl = _lint()
    bad = ("def pick(rows, block):\n"
           "    pad = (-rows) % block\n"
           "    return pad\n")
    hits = fl.lint_source(bad, "x.py")
    assert [h.rule for h in hits] == ["FL001"]
    good = ("def pick(rows, block):\n"
            "    pad = (-rows) % block if block else 0\n"
            "    return pad\n")
    assert fl.lint_source(good, "x.py") == []


def test_lint_fl002_bool_leak():
    fl = _lint()
    bad = ("class A:\n"
           "    def __getitem__(self, key):\n"
           "        if isinstance(key, int):\n"
           "            return key\n"
           "        return None\n")
    hits = fl.lint_source(bad, "x.py")
    assert [h.rule for h in hits] == ["FL002"]
    guarded = ("class A:\n"
               "    def __getitem__(self, key):\n"
               "        if isinstance(key, int) and not "
               "isinstance(key, bool):\n"
               "            return key\n"
               "        return None\n")
    assert fl.lint_source(guarded, "x.py") == []
    # same pattern outside an indexing-path function: not the rule's scope
    other = ("def compute(key):\n"
             "    return isinstance(key, int)\n")
    assert fl.lint_source(other, "x.py") == []


def test_lint_fl003_host_numpy_in_ops():
    fl = _lint()
    bad = ("import numpy as onp\n"
           "def _fwd_kernel(x):\n"
           "    return onp.zeros((2, 2))\n")
    hits = fl.lint_source(bad, "incubator_mxnet_tpu/ops/fake.py")
    assert [h.rule for h in hits] == ["FL003"]
    # float0 cotangent zeros are the jax-mandated exemption
    exempt = ("import numpy as onp\n"
              "import jax\n"
              "def _bwd(seeds):\n"
              "    return onp.zeros(seeds.shape, jax.dtypes.float0)\n")
    assert fl.lint_source(exempt, "incubator_mxnet_tpu/ops/fake.py") == []
    # outside ops/: not the rule's scope
    assert fl.lint_source(bad, "incubator_mxnet_tpu/image.py") == []


def test_lint_fl004_ops_ledger():
    fl = _lint()
    src = 'register_op_meta("bogus_xyz_op", "np", None)\n'
    hits = fl.lint_source(src, "x.py", coverage_text="| `add` | ... |")
    assert [h.rule for h in hits] == ["FL004"]
    assert fl.lint_source(src, "x.py",
                          coverage_text="| `bogus_xyz_op` | ... |") == []
    # no coverage text available -> rule is skipped, not spuriously firing
    assert fl.lint_source(src, "x.py") == []


# ---------------------------------------------------------------------------
# framework lint: the real pre-fix hazards must trip, the fixed tree not
# ---------------------------------------------------------------------------

def test_lint_flags_prefix_fused_block_pad():
    fl = _lint()
    path = os.path.join(REPO, "incubator_mxnet_tpu/ops/fused_block.py")
    fixed = open(path).read()
    assert "pad = (-rows) % block if block else 0" in fixed
    assert fl.lint_file(path) == []
    prefix = fixed.replace("pad = (-rows) % block if block else 0",
                           "pad = (-rows) % block")
    hits = fl.lint_source(prefix, path)
    assert [h.rule for h in hits] == ["FL001", "FL001"]


def test_lint_flags_prefix_sparse_isinstance_int():
    fl = _lint()
    path = os.path.join(REPO, "incubator_mxnet_tpu/ndarray/sparse.py")
    fixed = open(path).read()
    assert fl.lint_file(path) == []
    prefix = fixed.replace(
        "if isinstance(key, numbers.Integral) and not isinstance(key, bool):"
        "\n            key = int(key)",
        "if isinstance(key, int):")
    assert prefix != fixed
    hits = fl.lint_source(prefix, path)
    assert [h.rule for h in hits] == ["FL002"]


def test_framework_lint_tree_is_clean():
    """Tier-1 gate: the committed tree passes its own lint."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "framework_lint.py"),
         "incubator_mxnet_tpu/"],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "clean" in out.stdout


def test_framework_lint_list_rules():
    fl = _lint()
    assert set(fl.RULES) == {"FL001", "FL002", "FL003", "FL004", "FL005",
                             "FL006", "FL007", "FL008", "FL009", "FL010",
                             "FL011", "FL012", "FL013",
                             "FL014", "FL015", "FL016", "FL017",
                             "FL018", "FL019", "FL020", "FL021",
                             "FL022"}


def test_lint_fl019_wallclock_durations():
    fl = _lint()
    path = "incubator_mxnet_tpu/telemetry/fake.py"
    direct = ("import time\n"
              "def f(t0):\n"
              "    return time.time() - t0\n")
    hits = fl.lint_source(direct, path)
    assert [h.rule for h in hits] == ["FL019"]
    assigned = ("import time\n"
                "def f():\n"
                "    t0 = time.time()\n"
                "    work()\n"
                "    return time.time() - t0\n")
    # both the assigned-name use and the direct subtraction flag
    assert {h.rule for h in fl.lint_source(assigned, path)} == {"FL019"}
    # timestamps (no subtraction) are legitimate wall-clock uses
    stamp = ("import time\n"
             "def f(rec):\n"
             "    rec['at'] = time.time()\n"
             "    return rec\n")
    assert fl.lint_source(stamp, path) == []
    # monotonic/perf_counter durations are the sanctioned idiom
    good = ("import time\n"
            "def f():\n"
            "    t0 = time.perf_counter()\n"
            "    work()\n"
            "    return time.perf_counter() - t0\n")
    assert fl.lint_source(good, path) == []
    # scope: ops/ modules are FL005's turf, not FL019's
    assert all(h.rule != "FL019" for h in fl.lint_source(
        direct, "incubator_mxnet_tpu/ops/fake.py"))
    # noqa escape with a reason
    excused = ("import time\n"
               "def f(epoch):\n"
               "    return time.time() - epoch  # noqa: FL019 - x-host\n")
    assert fl.lint_source(excused, path) == []


# ---------------------------------------------------------------------------
# regressions: the fixes the lint rules were learned from
# ---------------------------------------------------------------------------

def test_fused_block_empty_batch():
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops import fused_block as fb

    # interpret=False exercises the padding path that used to divide by 0
    for interpret in (False, None):
        out = fb.gelu_dropout(jnp.zeros((0, 256)), 0.1, (0, 1),
                              interpret=interpret)
        assert out.shape == (0, 256)
        out2 = fb.residual_dropout_ln(
            jnp.zeros((0, 256)), jnp.zeros((0, 256)), jnp.ones(256),
            jnp.zeros(256), 0.1, (0, 1), interpret=interpret)
        assert out2.shape == (0, 256)
    # 3-D empty leading axes collapse to zero rows too
    out3 = fb.gelu_dropout(jnp.zeros((2, 0, 128)), 0.5, (3, 4),
                           interpret=False)
    assert out3.shape == (2, 0, 128)


def test_sparse_mean_tuple_axis():
    from incubator_mxnet_tpu.ndarray import sparse

    d = onp.arange(12, dtype="float32").reshape(3, 4)
    d[d % 3 == 0] = 0
    csr = sparse.csr_matrix(d)
    onp.testing.assert_allclose(
        sparse.mean(csr, axis=(0, 1)).asnumpy(), d.mean(axis=(0, 1)),
        rtol=1e-6)
    onp.testing.assert_allclose(
        sparse.mean(csr, axis=(0, 1), keepdims=True).asnumpy(),
        d.mean(axis=(0, 1), keepdims=True), rtol=1e-6)
    rsp = mx.nd.NDArray(d).tostype("row_sparse")
    onp.testing.assert_allclose(
        sparse.mean(rsp, axis=[0, 1]).asnumpy(), d.mean(axis=(0, 1)),
        rtol=1e-6)
    # single-axis path unchanged
    onp.testing.assert_allclose(
        sparse.mean(csr, axis=0).asnumpy(), d.mean(axis=0), rtol=1e-6)


def test_csr_getitem_numpy_int_takes_indptr_path():
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.ndarray import sparse

    d = onp.arange(12, dtype="float32").reshape(3, 4)
    d[d % 3 == 0] = 0
    csr = sparse.csr_matrix(d)
    for key in (onp.int64(1), onp.int32(1), 1):
        row = csr[key]
        assert isinstance(row, sparse.CSRNDArray)
        onp.testing.assert_allclose(row.asnumpy(), d[1:2])
    # negative numpy int: same normalization as python int
    row = csr[onp.int64(-1)]
    assert isinstance(row, sparse.CSRNDArray)
    onp.testing.assert_allclose(row.asnumpy(), d[-1:])
    # the integer path never touched the dense buffer
    fresh = sparse.csr_matrix(d)
    _ = fresh[onp.int64(0)]
    assert NDArray._data.__get__(fresh) is None
    # bool is NOT an integer index (numpy new-axis semantics): dense path
    out = csr[True]
    assert not isinstance(out, sparse.CSRNDArray)


def test_big_index_helpers_exclude_bool():
    from incubator_mxnet_tpu.ndarray.ndarray import _needs_static_big_index

    big = 2 ** 40
    assert not _needs_static_big_index(True, (big,))
    assert _needs_static_big_index(2 ** 35, (big,))


# ---------------------------------------------------------------------------
# tier-1 smoke: hybridized model_zoo block audits clean in eval mode
# ---------------------------------------------------------------------------

def test_audit_hybridized_model_zoo_clean():
    from incubator_mxnet_tpu.gluon.model_zoo import vision as zoo

    net = zoo.squeezenet1_1()
    net.initialize()
    net.hybridize()
    x = mnp.ones((1, 3, 64, 64), dtype="float32")
    net(x)                           # warmup: deferred init + cached graph
    rep = net.audit(x)               # eval mode (no record scope)
    assert len(rep) == 0, rep.summary()
    assert rep.jaxpr is not None
