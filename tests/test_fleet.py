"""Fleet observability plane (ISSUE 12 gates): collective profiler +
census, straggler scoring, chunked snapshot transport, clock-offset /
stitching math, flight-recorder fanout merge, the collective_delay fault
seam, and the 2-process end-to-end gate (launch.py recipe from
test_kvstore_dist.py: an injected slow rank must win the straggler score
and the stitched timeline must align barrier spans within the estimated
clock-offset bound)."""
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx  # noqa: F401 - package init (env knobs)
from incubator_mxnet_tpu.fault import injection
from incubator_mxnet_tpu.telemetry import fleet, registry, tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fleet():
    fleet.reset()
    injection.clear_injection()
    yield
    fleet.disable()
    fleet.reset()
    injection.clear_injection()


# ---------------------------------------------------------------------------
# straggler score math
# ---------------------------------------------------------------------------

def test_straggler_scores_slow_rank_wins():
    samples = {0: {"step_time_mean": 0.10, "barrier_lateness_mean": 0.001},
               1: {"step_time_mean": 0.10, "barrier_lateness_mean": 0.002},
               2: {"step_time_mean": 0.45, "barrier_lateness_mean": 0.300}}
    scores = fleet.straggler_scores(samples)
    assert max(scores, key=scores.get) == 2
    assert scores[2] > 1.0          # well above the mean on both signals
    assert scores[0] <= 0.0 or scores[0] < scores[2]


def test_straggler_scores_two_ranks_signed():
    # n=2: the slow rank sits at z=+1, the fast at -1 — the SIGNED max
    # keeps the argmax on the slow one
    scores = fleet.straggler_scores(
        {0: {"step_time_mean": 0.1}, 1: {"step_time_mean": 0.4}})
    assert scores[1] == pytest.approx(1.0)
    assert max(scores, key=scores.get) == 1


def test_straggler_scores_ignores_sparse_and_flat_signals():
    samples = {0: {"a": None, "flat": 5.0, "lone": 1.0},
               1: {"a": None, "flat": 5.0}}
    scores = fleet.straggler_scores(samples)
    # None everywhere, zero spread, and single-rank signals contribute 0
    assert scores == {0: 0.0, 1: 0.0}


# ---------------------------------------------------------------------------
# chunked snapshot transport
# ---------------------------------------------------------------------------

def _fake_fleet_transport(n_ranks, payloads, max_bytes=4096):
    """A dist.exchange_objs stand-in: every rank sends the same thing the
    local caller sends (identical code path on each rank), and the pickled
    size contract of the real 4 KiB command slot is enforced."""
    import pickle

    def exchange(obj):
        assert len(pickle.dumps(obj)) <= max_bytes - 4, "slot overflow"
        return [obj for _ in range(n_ranks)]

    return exchange


def test_exchange_large_chunks_past_command_slot():
    big = {"rank": 0, "blob": "x" * 50_000,
           "nested": {str(i): float(i) for i in range(300)}}
    out = fleet.exchange_large(
        big, chunk=1000, _exchange=_fake_fleet_transport(3, big))
    assert len(out) == 3
    assert all(o == big for o in out)


def test_exchange_large_small_object_single_round():
    calls = []

    def exchange(obj):
        calls.append(obj)
        return [obj, obj]

    out = fleet.exchange_large({"ok": 1}, chunk=3000, _exchange=exchange)
    assert out == [{"ok": 1}, {"ok": 1}]
    # one metadata round (the count) + one piece round
    assert len(calls) == 2 and calls[0] == 1


def test_exchange_large_single_process_short_circuit():
    obj = {"r": 0}
    assert fleet.exchange_large(obj) == [obj]


# ---------------------------------------------------------------------------
# collective_delay fault seam (satellite 1)
# ---------------------------------------------------------------------------

def test_collective_delay_sleeps_not_raises(monkeypatch):
    from incubator_mxnet_tpu.parallel import dist

    monkeypatch.setenv("MXNET_FAULT_DELAY_MS", "60")
    injection.configure_injection({"collective_delay": (1.0, 0, 2)})
    assert dist._FAULT_HOOK is not None
    x = onp.ones((4,), "float32")
    dist.allreduce(x)                      # warm (fires once)
    t0 = time.perf_counter()
    out = dist.allreduce(x)                # fires again: sleep, no raise
    dt = time.perf_counter() - t0
    assert dt >= 0.055, dt
    onp.testing.assert_allclose(onp.asarray(out), x)
    rep = registry.report()
    cell = rep.get('mx_fault_delay_seconds_total{seam="collective_delay"}')
    assert cell and cell["value"] >= 0.11  # two 60 ms sleeps
    # limit=2 exhausted: the third call is clean and fast
    t0 = time.perf_counter()
    dist.allreduce(x)
    assert time.perf_counter() - t0 < 0.05


def test_collective_delay_rank_targeting(monkeypatch):
    from incubator_mxnet_tpu.parallel import dist

    monkeypatch.setenv("MXNET_FAULT_DELAY_MS", "60")
    monkeypatch.setenv("PROCESS_ID", "0")
    injection.configure_injection({"collective_delay@1": (1.0, 0, 8)})
    info = injection.schedule_info()["collective_delay"]
    assert info["rank"] == 1 and info["kind"] == "delay"
    x = onp.ones((2,), "float32")
    dist.allreduce(x)                      # warm
    t0 = time.perf_counter()
    dist.allreduce(x)                      # we are rank 0: no delay
    assert time.perf_counter() - t0 < 0.05
    # retarget to OUR rank: the delay fires
    injection.configure_injection({"collective_delay@0": (1.0, 0, 8)})
    t0 = time.perf_counter()
    dist.allreduce(x)
    assert time.perf_counter() - t0 >= 0.055


def test_collective_delay_env_spec_round_trip(monkeypatch):
    monkeypatch.setenv("MXNET_FAULT_INJECT", "collective_delay@1:0.5:7:3")
    injection.configure_from_env()
    info = injection.schedule_info()["collective_delay"]
    assert info == {"prob": 0.5, "seed": 7, "limit": 3, "kind": "delay",
                    "rank": 1, "draws": 0, "fired": 0}


# ---------------------------------------------------------------------------
# census + probe (tentpole: in-graph wrappers)
# ---------------------------------------------------------------------------

def test_census_counts_traced_collective_bytes():
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from incubator_mxnet_tpu.parallel import collectives

    fleet.enable()
    assert collectives._CENSUS is not None
    mesh = Mesh(onp.array(jax.devices()[:2]), ("dp",))

    def f(v):
        return collectives.all_reduce(v, "dp")

    jf = jax.jit(shard_map(f, mesh=mesh, in_specs=(P("dp"),),
                           out_specs=P(), check_rep=False))
    jf(jnp.zeros((8, 4), jnp.float32)).block_until_ready()
    rep = registry.report()
    calls = rep.get('mx_collective_trace_calls_total'
                    '{axis="dp",op="all_reduce"}')
    assert calls and calls["value"] >= 1
    nbytes = rep.get('mx_collective_bytes_total{axis="dp",op="all_reduce"}')
    # per-shard payload at trace time: (4, 4) float32 = 64 B
    assert nbytes and nbytes["value"] >= 64


def test_census_off_is_dead_branch():
    """PR-2 dead-branch contract for the wrapper hook: telemetry off,
    the census probe is one global load + is-None check — <3% of even a
    tiny traced op (the bench.py overhead gate measures the full wrapper;
    this is the unit-level floor)."""
    from incubator_mxnet_tpu.parallel import collectives

    fleet.disable()
    assert collectives._CENSUS is None
    import jax.numpy as jnp

    a = jnp.ones((16, 16), jnp.float32)
    (a @ a).block_until_ready()
    iters = 300
    t0 = time.perf_counter()
    for _ in range(iters):
        a @ a
    import jax

    jax.block_until_ready(a)
    per_op = (time.perf_counter() - t0) / iters
    c = collectives._CENSUS
    t0 = time.perf_counter()
    for _ in range(iters):
        if c is not None:                   # the literal off-path pattern
            pass
    probe_per_op = (time.perf_counter() - t0) / iters
    assert probe_per_op < 0.03 * per_op, (probe_per_op, per_op)


def test_probe_collectives_emits_series_for_every_op():
    fleet.enable()
    res = fleet.probe_collectives(nbytes=1 << 12, iters=1)
    ops = [op for op in res if op != "_meta"]
    assert set(ops) == {"all_reduce", "all_gather", "reduce_scatter",
                        "broadcast", "ring_permute", "all_to_all"}
    rep = registry.report()
    axis = res["_meta"]["axis"]
    for op in ops:
        assert "error" not in res[op], (op, res[op])
        assert res[op]["seconds"] > 0
        key = f'mx_collective_seconds{{axis="{axis}",op="{op}"}}'
        assert key in rep, key


# ---------------------------------------------------------------------------
# fleet report + health hook (single-process shape)
# ---------------------------------------------------------------------------

def test_fleet_report_single_process_and_gauges():
    fleet.enable()
    registry.step(0.02, examples=8)
    rep = fleet.fleet_report()
    assert rep["n_ranks"] == 1 and rep["rank"] == 0
    assert 0 in rep["ranks"] and "registry" in rep["ranks"][0]
    assert rep["straggler"]["rank"] == 0
    g = registry.report()
    assert g["mx_fleet_ranks"]["value"] == 1
    assert g["mx_fleet_straggler_rank"]["value"] == 0


def test_straggler_health_check_raises_past_threshold():
    from incubator_mxnet_tpu.base import MXNetError

    fleet.enable()
    check = fleet.install_health_check(threshold=2.0)
    fleet._LAST_REPORT = None
    check()                                  # no report: silent
    fleet._LAST_REPORT = {"straggler": {"rank": 3, "score": 2.6,
                                        "signals": {3: {"step": 9.0}}}}
    with pytest.raises(MXNetError, match="rank 3"):
        check()
    fleet._LAST_REPORT = {"straggler": {"rank": 1, "score": 0.4,
                                        "signals": {}}}
    check()                                  # under threshold: silent


# ---------------------------------------------------------------------------
# clock offsets, trace stitching, flightrec merge (host-side math)
# ---------------------------------------------------------------------------

def test_clock_offsets_single_process_zero():
    out = fleet.estimate_clock_offsets()
    assert out["offsets"] == [0.0] and out["bound_s"] == 0.0


def _write_rank_dump(d, rank, offset_s, ts0_us, n_ranks=2):
    spans = [{"trace_id": "t" * 32, "span_id": f"s{rank}{i}",
              "parent_id": None, "name": "dist.barrier",
              "ts_us": ts0_us + i * 10_000, "dur_us": 500.0,
              "thread": 1, "lane": "dist",
              "attrs": {"coll_seq": i + 1, "op": "barrier"}, "events": []}
             for i in range(3)]
    path = os.path.join(d, f"fleet_spans_rank{rank:03d}.json")
    with open(path, "w") as fh:
        json.dump({"rank": rank, "n_ranks": n_ranks, "host": f"h{rank}",
                   "pid": 100 + rank, "clock_offset_s": offset_s,
                   "offset_bound_s": 0.002, "fleet_trace": "t" * 32,
                   "barrier": {}, "spans": spans}, fh)
    return path


def test_stitch_traces_rebases_by_clock_offset(tmp_path):
    d = str(tmp_path)
    # rank 1's clock runs 5 ms ahead: raw timestamps disagree by 5000 µs,
    # the stitcher subtracts the estimated offset and realigns
    _write_rank_dump(d, 0, 0.0, ts0_us=1_000_000.0)
    _write_rank_dump(d, 1, 0.005, ts0_us=1_005_000.0)
    out = fleet.stitch_traces(d)
    assert out["fleet"] == {"n_ranks": 2, "files": 2, "n_spans": 6,
                            "offset_bound_s": 0.002}
    lanes = {e["pid"] for e in out["traceEvents"] if e["ph"] == "X"}
    assert lanes == {3000, 3001}
    by_seq: dict = {}
    for e in out["traceEvents"]:
        if e["ph"] != "X":
            continue
        by_seq.setdefault(e["args"]["coll_seq"], []).append(e["ts"])
    for seq, ts in by_seq.items():
        assert len(ts) == 2
        assert abs(ts[0] - ts[1]) <= 0.002 * 1e6, (seq, ts)


def test_stitch_traces_empty_dir_is_loud(tmp_path):
    with pytest.raises(FileNotFoundError):
        fleet.stitch_traces(str(tmp_path))


def test_merge_flight_dumps_groups_by_rank(tmp_path):
    d = str(tmp_path)
    for rank, reason in ((0, "peer_crash"), (1, "crash")):
        with open(os.path.join(
                d, f"flightrec_{reason}_rank{rank:03d}_42.json"), "w") as fh:
            json.dump({"reason": reason, "pid": 100 + rank,
                       "error": {"type": "RuntimeError", "message": "boom"}
                       if reason == "crash" else None,
                       "context": {"fleet": {"rank": rank, "n_ranks": 2}},
                       "spans": [{"name": "dist.barrier"}]}, fh)
    with open(os.path.join(d, "fleet_crash_rank001.marker"), "w") as fh:
        json.dump({"rank": 1, "pid": 101, "error": "RuntimeError: boom"},
                  fh)
    merged = fleet.merge_flight_dumps(d)
    assert merged["n_ranks"] == 2 and merged["n_dumps"] == 2
    assert merged["ranks"]["1"][0]["reason"] == "crash"
    assert merged["ranks"]["0"][0]["reason"] == "peer_crash"
    assert merged["markers"][0]["rank"] == 1
    # the CLI formatter renders it without blowing up
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import fleetwatch
    finally:
        sys.path.pop(0)
    text = fleetwatch.format_postmortem(merged)
    assert "rank   1" in text and "peer_crash" in text


def test_rank_stamped_flightrec_filename(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    tracing.enable()
    old = tracing._RANK_STAMP
    tracing._RANK_STAMP = 5
    try:
        with tracing.span("work"):
            pass
        path = tracing.flight_dump("unit")
    finally:
        tracing._RANK_STAMP = old
        tracing.disable()
        tracing.reset()
    assert "rank005_" in os.path.basename(path)


# ---------------------------------------------------------------------------
# 2-process end-to-end gate (the multichip-dryrun recipe on CPU)
# ---------------------------------------------------------------------------

FLEET_WORKER = textwrap.dedent("""
    import json, os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.fault import injection
    from incubator_mxnet_tpu.parallel import dist
    from incubator_mxnet_tpu.telemetry import fleet, registry, tracing

    out_dir = os.environ["FLEET_TEST_DIR"]
    dist.initialize()
    rank, n = dist.rank(), dist.num_processes()
    assert n == 2, n
    assert fleet.is_enabled()            # armed by MXNET_TELEMETRY=1

    dist.barrier("warmup")               # compiles the barrier program
    fleet.estimate_clock_offsets(rounds=3)

    # rank 1 is the straggler: slow local "steps" make it genuinely
    # LATE at every barrier (the skew exchange sees real arrival gaps)
    for i in range(4):
        t0 = time.perf_counter()
        time.sleep(0.25 if rank == 1 else 0.01)
        registry.step(time.perf_counter() - t0, examples=8)
        dist.barrier(f"step{i}")

    # the injected collective_delay@1 fired on rank 1 ONLY (the @rank
    # filter, live in a real multi-rank launch)
    info = injection.schedule_info()["collective_delay"]
    assert (info["fired"] > 0) == (rank == 1), (rank, info)

    rep = fleet.fleet_report()
    assert rep["n_ranks"] == 2, rep["n_ranks"]
    assert rep["straggler"]["rank"] == 1, rep["straggler"]
    assert rep["straggler"]["score"] > 0.5, rep["straggler"]
    bs = fleet.barrier_stats()
    if rank == 1:
        assert bs["lateness_max"] >= 0.05, bs   # arrived late for real

    series = registry.report()
    for op in ("allreduce", "barrier", "exchange_objs"):
        key = 'mx_collective_seconds{axis="host",op="%s"}' % op
        assert key in series, (rank, key, sorted(series)[:10])

    fleet.dump_rank_trace(out_dir)
    with open(os.path.join(out_dir, f"report_rank{rank}.json"), "w") as fh:
        json.dump({"straggler": rep["straggler"]["rank"],
                   "clock": rep["clock"],
                   "lateness_max": bs["lateness_max"]}, fh)
    dist.barrier("final")
    print(f"fleetworker {rank} ok straggler={rep['straggler']['rank']}",
          flush=True)
    if rank == 1 and os.environ.get("FLEET_TEST_CRASH") == "1":
        raise RuntimeError("injected fleet crash (rank 1)")
    if rank == 0 and os.environ.get("FLEET_TEST_CRASH") == "1":
        time.sleep(4.0)   # outlive rank 1's crash; launch.py's SIGTERM
                          # lands here and the fleet handler converts it
                          # to SystemExit so atexit dumps peer_crash
""")


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_fleet_workers(tmp_path, crash):
    script = tmp_path / "fleet_worker.py"
    script.write_text(FLEET_WORKER)
    share = tmp_path / "share"
    share.mkdir()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # children: real 1-device CPU
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_TELEMETRY"] = "1"
    env["MXNET_FAULT_INJECT"] = "collective_delay@1:1.0:0:64"
    env["MXNET_FAULT_DELAY_MS"] = "120"
    env["MXNET_FLIGHTREC_DIR"] = str(share)
    env["FLEET_TEST_DIR"] = str(share)
    env["FLEET_TEST_CRASH"] = "1" if crash else "0"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--port", str(_free_port()), sys.executable,
         str(script)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=280)
    return res, share


def test_fleet_two_process_straggler_and_stitch(tmp_path):
    """The ISSUE 12 dryrun gate on CPU: collective_delay armed on rank 1
    → every rank's fleet_report names rank 1 the straggler; both ranks'
    span dumps stitch into one timeline whose matching coll_seq barrier
    spans align within the estimated clock-offset bound."""
    res, share = _run_fleet_workers(tmp_path, crash=False)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "fleetworker 0 ok straggler=1" in res.stdout
    assert "fleetworker 1 ok straggler=1" in res.stdout

    stitched = fleet.stitch_traces(str(share))
    assert stitched["fleet"]["n_ranks"] == 2
    bound_us = max(stitched["fleet"]["offset_bound_s"], 0.005) * 1e6
    barriers: dict = {}
    for e in stitched["traceEvents"]:
        if e.get("ph") == "X" and e["name"] == "dist.barrier":
            barriers.setdefault(e["args"].get("coll_seq"),
                                {})[e["args"]["rank"]] = e["ts"]
    both = {s: t for s, t in barriers.items() if len(t) == 2}
    assert both, barriers
    # barrier EXIT instants coincide fleet-wide; rank 1 arrives late but
    # the span ends (ts+dur ~ exit) within skew+offset of rank 0's
    for seq, ts in both.items():
        assert abs(ts[0] - ts[1]) < 1e6, (seq, ts)  # same second, sane

    # the per-rank reports agree (every rank saw the same straggler)
    reports = [json.loads((share / f"report_rank{r}.json").read_text())
               for r in range(2)]
    assert all(r["straggler"] == 1 for r in reports)
    assert reports[0]["clock"]["offsets"] is not None


def test_fleet_two_process_crash_fanout(tmp_path):
    """Rank 1 crashes after the final barrier: its excepthook drops a
    crash marker + rank-stamped flightrec, surviving rank 0's atexit
    sees the marker and dumps peer_crash — merge_flight_dumps shows
    BOTH ranks in one post-mortem."""
    res, share = _run_fleet_workers(tmp_path, crash=True)
    assert res.returncode != 0        # rank 1 died loudly
    assert "fleetworker 0 ok" in res.stdout
    assert "fleetworker 1 ok" in res.stdout
    merged = fleet.merge_flight_dumps(str(share))
    assert merged["markers"], "crashing rank left no marker"
    assert merged["markers"][0]["rank"] == 1
    ranks = merged["ranks"]
    assert "1" in ranks, (sorted(ranks), merged["markers"])
    assert any(d["reason"] == "crash" for d in ranks["1"])
    assert "0" in ranks, (sorted(ranks), merged["markers"])
    assert any(d["reason"] == "peer_crash" for d in ranks["0"])
