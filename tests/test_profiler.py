"""Profiler: per-op aggregate stats populated from the apply_op funnel and
chrome-trace dump (reference: tests/python/unittest/test_profiler.py)."""
import json
import os

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, profiler


def test_record_op_from_funnel(tmp_path):
    profiler.set_config(filename=str(tmp_path / "profile.json"),
                        aggregate_stats=True)
    profiler.set_state("run")
    try:
        a = np.array([[1.0, 2.0], [3.0, 4.0]])
        b = np.array([[5.0, 6.0], [7.0, 8.0]])
        c = np.dot(a, b)
        d = c + a
        d.wait_to_read()
    finally:
        profiler.set_state("stop")

    table = profiler.dumps()
    assert "dot" in table
    path = profiler.dump()
    with open(path) as f:
        payload = json.load(f)
    names = {e["name"] for e in payload["traceEvents"]}
    assert any("dot" in n for n in names)
    assert os.path.exists(path)


def test_profiler_off_records_nothing():
    profiler.dumps(reset=True)
    a = np.array([1.0, 2.0])
    (a * 2).wait_to_read()
    assert profiler.dumps().count("\n") <= 1 or "mul" not in profiler.dumps()


def test_scope_records():
    profiler.set_state("run")
    try:
        with profiler.Scope("custom_region"):
            np.array([1.0]).wait_to_read()
    finally:
        profiler.set_state("stop")
    assert "custom_region" in profiler.dumps()
    profiler.dumps(reset=True)
    mx.waitall()
