"""Profiler: per-op aggregate stats populated from the apply_op funnel and
chrome-trace dump (reference: tests/python/unittest/test_profiler.py)."""
import json
import os

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, profiler


def test_record_op_from_funnel(tmp_path):
    profiler.set_config(filename=str(tmp_path / "profile.json"),
                        aggregate_stats=True)
    profiler.set_state("run")
    try:
        a = np.array([[1.0, 2.0], [3.0, 4.0]])
        b = np.array([[5.0, 6.0], [7.0, 8.0]])
        c = np.dot(a, b)
        d = c + a
        d.wait_to_read()
    finally:
        profiler.set_state("stop")

    table = profiler.dumps()
    assert "dot" in table
    path = profiler.dump()
    with open(path) as f:
        payload = json.load(f)
    names = {e["name"] for e in payload["traceEvents"]}
    assert any("dot" in n for n in names)
    assert os.path.exists(path)


def test_profiler_off_records_nothing():
    profiler.dumps(reset=True)
    a = np.array([1.0, 2.0])
    (a * 2).wait_to_read()
    assert profiler.dumps().count("\n") <= 1 or "mul" not in profiler.dumps()


def test_scope_records():
    profiler.set_state("run")
    try:
        with profiler.Scope("custom_region"):
            np.array([1.0]).wait_to_read()
    finally:
        profiler.set_state("stop")
    assert "custom_region" in profiler.dumps()
    profiler.dumps(reset=True)
    mx.waitall()


def test_device_trace_events_in_dump(tmp_path):
    """start/stop must capture a jax device trace; dump() merges its
    lanes; a jitted step's runtime events appear (VERDICT r2 item 5:
    device events for a jitted step, not just host dispatch wall-time)."""
    from incubator_mxnet_tpu import gluon

    profiler.dumps(reset=True)
    profiler.set_config(filename=str(tmp_path / "prof.json"),
                        profile_device=True)
    net = gluon.nn.Dense(32, in_units=64)
    net.initialize()
    x = np.random.uniform(size=(16, 64))
    net(x)                     # deferred init + first compile
    net.hybridize()
    net(x).wait_to_read()
    profiler.set_state("run")
    try:
        for _ in range(3):
            y = net(x)
        y.wait_to_read()
        mx.waitall()
    finally:
        profiler.set_state("stop")
    evts = profiler.device_events()
    assert evts, "no device-trace events captured"
    lanes = {e.get("args", {}).get("name", "") for e in evts
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    # at least one runtime lane beyond the host-funnel lane (on the CPU
    # test backend XLA events land on the /host:CPU lane; on TPU they
    # land on /device:TPU:N)
    assert any(ln.startswith(("/device:", "/host:")) for ln in lanes), lanes
    path = profiler.dump()
    with open(path) as f:
        payload = json.load(f)
    pids = {e.get("pid") for e in payload["traceEvents"]}
    assert any(p >= 1000 for p in pids), "device lane missing from dump()"
    profiler.dumps(reset=True)


def test_device_trace_can_be_disabled(tmp_path):
    profiler.dumps(reset=True)
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_device=False)
    profiler.set_state("run")
    try:
        np.random.uniform(size=(4, 4)).wait_to_read()
    finally:
        profiler.set_state("stop")
    assert profiler.device_events() == []
    profiler.set_config(profile_device=True)
    profiler.dumps(reset=True)
