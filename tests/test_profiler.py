"""Profiler: per-op aggregate stats populated from the apply_op funnel and
chrome-trace dump (reference: tests/python/unittest/test_profiler.py)."""
import json
import os

import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, profiler


def test_record_op_from_funnel(tmp_path):
    profiler.set_config(filename=str(tmp_path / "profile.json"),
                        aggregate_stats=True)
    profiler.set_state("run")
    try:
        a = np.array([[1.0, 2.0], [3.0, 4.0]])
        b = np.array([[5.0, 6.0], [7.0, 8.0]])
        c = np.dot(a, b)
        d = c + a
        d.wait_to_read()
    finally:
        profiler.set_state("stop")

    table = profiler.dumps()
    assert "dot" in table
    path = profiler.dump()
    with open(path) as f:
        payload = json.load(f)
    names = {e["name"] for e in payload["traceEvents"]}
    assert any("dot" in n for n in names)
    assert os.path.exists(path)


def test_profiler_off_records_nothing():
    profiler.dumps(reset=True)
    a = np.array([1.0, 2.0])
    (a * 2).wait_to_read()
    assert profiler.dumps().count("\n") <= 1 or "mul" not in profiler.dumps()


def test_scope_records():
    profiler.set_state("run")
    try:
        with profiler.Scope("custom_region"):
            np.array([1.0]).wait_to_read()
    finally:
        profiler.set_state("stop")
    assert "custom_region" in profiler.dumps()
    profiler.dumps(reset=True)
    mx.waitall()


def test_device_trace_events_in_dump(tmp_path):
    """start/stop must capture a jax device trace; dump() merges its
    lanes; a jitted step's runtime events appear (VERDICT r2 item 5:
    device events for a jitted step, not just host dispatch wall-time)."""
    from incubator_mxnet_tpu import gluon

    profiler.dumps(reset=True)
    profiler.set_config(filename=str(tmp_path / "prof.json"),
                        profile_device=True)
    net = gluon.nn.Dense(32, in_units=64)
    net.initialize()
    x = np.random.uniform(size=(16, 64))
    net(x)                     # deferred init + first compile
    net.hybridize()
    net(x).wait_to_read()
    profiler.set_state("run")
    try:
        for _ in range(3):
            y = net(x)
        y.wait_to_read()
        mx.waitall()
    finally:
        profiler.set_state("stop")
    evts = profiler.device_events()
    assert evts, "no device-trace events captured"
    lanes = {e.get("args", {}).get("name", "") for e in evts
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    # at least one runtime lane beyond the host-funnel lane (on the CPU
    # test backend XLA events land on the /host:CPU lane; on TPU they
    # land on /device:TPU:N)
    assert any(ln.startswith(("/device:", "/host:")) for ln in lanes), lanes
    path = profiler.dump()
    with open(path) as f:
        payload = json.load(f)
    pids = {e.get("pid") for e in payload["traceEvents"]}
    assert any(p >= 1000 for p in pids), "device lane missing from dump()"
    profiler.dumps(reset=True)


def test_device_trace_can_be_disabled(tmp_path):
    profiler.dumps(reset=True)
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_device=False)
    profiler.set_state("run")
    try:
        np.random.uniform(size=(4, 4)).wait_to_read()
    finally:
        profiler.set_state("stop")
    assert profiler.device_events() == []
    profiler.set_config(profile_device=True)
    profiler.dumps(reset=True)


def test_dumps_json_format(tmp_path):
    """ISSUE 2 satellite: `dumps(format="json")` must return the
    aggregate tables as JSON instead of silently ignoring the arg."""
    profiler.dumps(reset=True)
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_device=False)
    profiler.set_state("run")
    try:
        a = np.array([[1.0, 2.0], [3.0, 4.0]])
        np.dot(a, a).wait_to_read()
    finally:
        profiler.set_state("stop")
    payload = json.loads(profiler.dumps(format="json"))
    assert any(r["name"] == "dot" for r in payload["host"])
    row = next(r for r in payload["host"] if r["name"] == "dot")
    assert set(row) == {"name", "count", "total_ms", "min_ms", "max_ms"}
    assert "memory" not in payload
    mem = json.loads(profiler.dumps(format="json", memory=True, reset=True))
    assert "devices" in mem["memory"]
    import pytest

    with pytest.raises(ValueError):
        profiler.dumps(format="csv")
    profiler.set_config(profile_device=True)


def test_pause_suppresses_device_trace_events(tmp_path):
    """ISSUE 2 satellite: pause() must not only flip the host flag — the
    device trace keeps recording, so events whose timestamp falls in a
    paused window are dropped at ingest (deterministic synthetic trace)."""
    import gzip

    events = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 7, "name": "before_pause", "ts": 100, "dur": 10},
        {"ph": "X", "pid": 7, "name": "during_pause", "ts": 5000, "dur": 10},
        {"ph": "X", "pid": 7, "name": "after_resume", "ts": 9000, "dur": 10},
    ]}
    d = tmp_path / "trace"
    d.mkdir()
    with gzip.open(str(d / "x.trace.json.gz"), "wt") as f:
        json.dump(events, f)
    # host epoch anchor 0 so trace ts == epoch µs; paused window [4000, 8000]
    profiler.dumps(reset=True)
    profiler._STATE["trace_t0_us"] = 0.0
    del profiler._PAUSED_INTERVALS[:]
    profiler._PAUSED_INTERVALS.append([4000.0, 8000.0])
    try:
        profiler._ingest_device_trace(str(d))
        names = {e["name"] for e in profiler.device_events()
                 if e.get("ph") == "X"}
        assert names == {"before_pause", "after_resume"}, names
        # metadata rows always survive the filter
        assert any(e.get("ph") == "M" for e in profiler.device_events())
    finally:
        del profiler._PAUSED_INTERVALS[:]
        profiler.dumps(reset=True)


def test_pause_resume_flags_and_intervals():
    profiler.start()
    try:
        profiler.pause()
        assert not profiler.is_running()
        assert profiler._PAUSED_INTERVALS[-1][1] is None   # open interval
        profiler.resume()
        assert profiler.is_running()
        assert profiler._PAUSED_INTERVALS[-1][1] is not None
    finally:
        profiler.stop()
        profiler.dumps(reset=True)


# ---------------------------------------------------------------------------
# memory profiler (round 4: VERDICT #7 — reference
# `src/profiler/storage_profiler.h:130` + kMemory mode)
# ---------------------------------------------------------------------------

def test_memory_stats_and_snapshot(tmp_path):
    import os

    from incubator_mxnet_tpu import np, profiler

    keep = np.ones((256, 256))          # a live buffer to account for
    keep.wait_to_read()
    stats = profiler.memory_stats()
    assert stats, "no devices reported"
    for _dev, st in stats.items():
        assert st.get("bytes_in_use", 0) >= 0
    rows = profiler.live_buffer_table(5)
    assert rows and rows[0][2] > 0      # (shape, dtype, nbytes)
    p = profiler.memory_snapshot(str(tmp_path / "mem.prof"))
    assert os.path.getsize(p) > 0
    del keep


def test_dumps_memory_section_and_peak_op():
    from incubator_mxnet_tpu import np, profiler

    profiler.set_config(profile_memory=True)
    profiler.start()
    try:
        big = np.ones((128, 128)) * 2.0
        _ = (big @ big).sum()
        _.wait_to_read()
    finally:
        profiler.stop()
        profiler.set_config(profile_memory=False)
    out = profiler.dumps(memory=True, reset=True)
    assert "Memory" in out
    assert "MiB in use" in out
    assert "observed live-bytes peak" in out
    assert "Largest live buffers" in out


def test_analyze_memory_reports_plan():
    """`profiler.analyze_memory` surfaces XLA's buffer plan (argument /
    output / temp bytes) for a compiled fn — the compile-time face of the
    reference's storage profiler."""
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu import profiler

    def f(a, b):
        return jnp.tanh(a @ b).sum()

    a = jnp.ones((128, 128), jnp.float32)
    an = profiler.analyze_memory(jax.grad(f), a, a)
    if an is None:
        import pytest

        pytest.skip("backend reports no memory analysis")
    assert an["argument_size_in_bytes"] == 2 * 128 * 128 * 4
    assert an["temp_size_in_bytes"] > 0


def test_remat_resnet_block_peak_below_plain():
    """The saved-residual ledger (what the backward must hold live — the
    activation peak driver) must shrink under remat for a ResNet
    bottleneck stack. XLA CPU's temp accounting is not liveness-faithful
    (see remat.py docstring), so the ledger is the portable peak pin
    (reference: MXNET_BACKWARD_DO_MIRROR, env_var.md:230)."""
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu import remat as mxremat

    C, L = 32, 4
    rngs = onp.random.RandomState(0)
    ws = [jnp.asarray(rngs.uniform(-0.1, 0.1, (3, 3, C, C)), jnp.float32)
          for _ in range(L)]
    x = jnp.ones((8, 56, 56, C), jnp.float32)

    def block(h, w):
        y = jax.lax.conv_general_dilated(
            h, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jax.nn.relu(y) + h          # residual conv block

    def loss_plain(ws):
        h = x
        for w in ws:
            h = block(h, w)
        return jnp.sum(h * h)

    def loss_remat(ws):
        h = x
        ck = jax.checkpoint(block)
        for w in ws:
            h = ck(h, w)
        return jnp.sum(h * h)

    plain_b = mxremat.saved_bytes(loss_plain, ws)
    remat_b = mxremat.saved_bytes(loss_remat, ws)
    assert remat_b < plain_b, (remat_b, plain_b)
